"""JSON HTTP API over the registry + engine + batcher stack.

Endpoints (all JSON; schema in docs/SERVING.md):

* ``POST /v1/similar``     — ``{"genes": [...]}`` or ``{"vectors":
  [[...]]}`` + ``"k"`` -> per-query neighbor lists (gene queries drop
  the query row itself from its own neighbors);
* ``POST /v1/embedding``   — raw embedding rows for named genes;
* ``POST /v1/interaction`` — GGIPNN softmax scores for gene pairs;
* ``GET  /v1/genes``       — a slice of the served vocab (loadgen uses
  this to draw realistic query keys);
* ``GET  /healthz``        — **readiness**: served model version + queue
  facts while a model is loaded, 503 ``not_ready`` until then (fleet
  supervisors and external probes must not route to an empty replica);
* ``GET  /livez``          — **liveness**: 200 whenever the process can
  answer HTTP at all, model or no model;
* ``GET  /metrics``        — the obs Prometheus registry, text format.

Status mapping: queue-full backpressure -> **429**, per-request deadline
-> **504**, unknown gene / malformed body -> **400**, no model loaded ->
**503**, stalled request body (slow loris) -> **408** + connection
close.  The front end is the non-blocking event loop in
``serve/eventloop.py`` (keep-alive, read deadlines, optional
SO_REUSEPORT multi-acceptor); every route is a method on
:class:`ServeApp`, which tests drive directly and through
ephemeral-port HTTP.

The hot read path — ``GET /v1/similar?gene=...&k=...`` with no
traceparent and no fault injection — is served from the event loop
itself: a bounded LRU of **pre-serialized response bodies** keyed by
``(model version, gene, k)`` answers repeats with a single scatter-
gather write of reused bytes (no JSON assembly, no handler thread),
and concurrent identical misses **coalesce** onto one batcher ticket
(one engine slot per hot gene regardless of fan-in).  Everything else
— POSTs, traced requests, fault-injected replicas, error shapes —
runs the full :meth:`ServeApp.handle` pipeline on a bounded worker
pool with semantics identical to the old threaded front end.

Every connection runs under the event loop's read deadline
(``ServeConfig.read_timeout_s``): once a request's first byte arrives
the whole request must arrive within the window or the loop answers
408 and closes, so a client dripping one byte per poll cannot pin
anything past the deadline.  Fault injection
(``resilience/faults.py``) hooks the dispatch behind an explicit
opt-in (``--faults`` / ``GENE2VEC_TPU_FAULTS``) and is entirely absent
otherwise.

Each request runs under an obs span (``serve_request``), batches under
``serve_batch``/``serve_compute`` (batcher.py) — with a
:class:`~gene2vec_tpu.obs.run.Run` installed (cli/serve.py always makes
one) the whole enqueue->batch->compute->respond pipeline lands in that
run's ``events.jsonl`` and ``/metrics`` serves its registry.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, unquote_plus, urlparse

import numpy as np

from gene2vec_tpu.obs import flight as flight_mod
from gene2vec_tpu.obs import probes
from gene2vec_tpu.obs import tracecontext
from gene2vec_tpu.obs.alerts import RateLimiter
from gene2vec_tpu.obs.flight import FlightRecorder
from gene2vec_tpu.obs.registry import MetricsRegistry
from gene2vec_tpu.obs.trace import ambient_span
from gene2vec_tpu.obs.tracecontext import Sampler, TraceContext
from gene2vec_tpu.serve.routes import (
    JOBS_ROUTE,
    SHARD_ROUTES,
    V1_ROUTES,
    collapse_jobs_route,
    split_model_route,
)
from gene2vec_tpu.serve.batcher import (
    DeadlineExceeded,
    LRUCache,
    MicroBatcher,
    RejectedError,
)
from gene2vec_tpu.serve.engine import SimilarityEngine
from gene2vec_tpu.serve.eventloop import (
    ConnHandle,
    EventLoopConfig,
    EventLoopHTTPServer,
    HandlerPool,
    HTTPRequest,
    Response,
    parse_json_body,
)
from gene2vec_tpu.serve.interaction import InteractionScorer
from gene2vec_tpu.serve.registry import ModelRegistry
from gene2vec_tpu.serve.tenancy import (
    BATCH_TENANT,
    DEFAULT_BATCH_WEIGHT,
    DEFAULT_TENANT,
    TenantAdmission,
    TenantPolicy,
    sanitize_tenant,
)


class ApiError(Exception):
    """Route failure with an HTTP status."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine/batcher/queue policy knobs (cli/serve.py flags)."""

    max_batch: int = 64
    max_delay_ms: float = 5.0
    max_queue: int = 256
    cache_size: int = 4096
    timeout_ms: float = 2000.0
    max_k: int = 256
    max_queries_per_request: int = 64
    # -- retrieval index (serve/ann.py; cli/serve.py --index) -------------
    # exact (default, bitwise-identical to the pre-ANN engine) | quant
    # (int8 full-table scan + exact-rescore tail) | ivf (centroid scan
    # -> nprobe lists -> int8 candidates -> exact rescore)
    index: str = "exact"
    # IVF lists probed per query (recall/latency knob)
    nprobe: int = 8
    # exact-rescore tail size multiplier: r = rescore_mult * k
    rescore_mult: int = 4
    # warm-time per-bucket kernel attribution (engine.profile_buckets):
    # AOT-compile every batch bucket at startup/swap and publish
    # kernel_* cost gauges on /metrics (docs/OBSERVABILITY.md
    # #kernel-attribution--rooflines).  Costs one extra compile pass
    # per bucket, so it is opt-in (cli/serve.py --kernel-profile)
    kernel_profile: bool = False
    # per-request read deadline: once the first byte of a request has
    # arrived the WHOLE request must arrive within this window
    # (slow-loris guard; expiry -> 408 + close)
    read_timeout_s: float = 10.0
    # root-trace sampling rate for requests WITHOUT a traceparent
    # header (0 = trace only when the caller propagates a sampled
    # context; sampled callers are always honored)
    trace_sample: float = 0.0
    # -- event-loop front end (serve/eventloop.py) ------------------------
    # keep-alive connections idle longer than this are closed
    idle_timeout_s: float = 30.0
    # requests served per connection before the front end closes it
    # (0 = unbounded); bounds per-connection state lifetime
    max_conn_requests: int = 0
    # acceptor event loops; > 1 enables SO_REUSEPORT multi-acceptor
    acceptors: int = 1
    # bounded worker pool for the full-dispatch path (POSTs, traced or
    # fault-injected requests); saturation answers 429
    http_workers: int = 8
    http_queue: int = 512
    # -- flight recorder (obs/flight.py; cli/serve.py --burst-*) ----------
    # a 5xx burst of >= burst_threshold within burst_window_s dumps the
    # ring to the run dir; dump cadence is arbitrated by the shared
    # obs.alerts.RateLimiter (one budget with incident bundles)
    burst_threshold: int = 10
    burst_window_s: float = 5.0
    # -- multi-tenant admission (serve/tenancy.py; cli/serve.py
    # --tenant-quota/--tenant-override) -----------------------------------
    # per-tenant token-bucket quota: sustained requests/s admitted per
    # tenant (the X-Tenant header; untagged traffic is the "default"
    # tenant).  0 disables tenancy entirely — no bucket, no label, no
    # per-request cost.  Quotas are per-replica: a fleet of N admits
    # N x this rate per tenant in aggregate.
    tenant_rate: float = 0.0
    # bucket burst headroom (0 = 2 x tenant_rate)
    tenant_burst: float = 0.0
    # per-tenant overrides, "id:rate[:burst[:weight]]" strings; weight
    # is the batcher's weighted-fair-dequeue share
    tenant_overrides: Tuple[str, ...] = ()
    # -- offline batch jobs (gene2vec_tpu/batch/; cli/serve.py
    # --jobs-dir) ---------------------------------------------------------
    # job store root; None disables the /v1/jobs surface entirely (no
    # manager, no worker thread)
    jobs_dir: Optional[str] = None
    # the batch lane's weighted-fair share against interactive lanes
    # (docs/BATCH.md#priority-tier-contract); always wired, so batch
    # submissions stay background-priority even with tenancy off
    batch_weight: float = DEFAULT_BATCH_WEIGHT
    # batch pacing (batch/runner.py Pacer): fraction of wall time a job
    # may consume (1.0 = no idle gap) and the queue-fullness fraction
    # above which chunks yield entirely
    batch_duty: float = 1.0
    batch_guard_max: float = 0.5


#: routes whose latency gets its own labeled histogram series; anything
#: else collapses into "other" so garbage paths can't mint label sets
_KNOWN_ROUTES = V1_ROUTES | SHARD_ROUTES | frozenset((
    "/", "/livez", "/healthz", "/metrics", JOBS_ROUTE,
))


def _route_label(route: str) -> str:
    """The bounded per-route label: job sub-routes collapse to
    ``/v1/jobs``, anything outside the route table to ``other``."""
    route = collapse_jobs_route(route)
    return route if route in _KNOWN_ROUTES else "other"

#: powers-of-two seconds buckets, 0.5 ms .. ~8 s: fine enough that the
#: fleet aggregator's bucket-edge p50/p99 estimates are within 2x
_ROUTE_BUCKETS = tuple(0.0005 * (2 ** e) for e in range(15))


class ServeApp:
    """The route layer: owns the registry, engine, batcher, and scorer."""

    def __init__(
        self,
        registry: ModelRegistry,
        config: ServeConfig = ServeConfig(),
        metrics: Optional[MetricsRegistry] = None,
        ggipnn_checkpoint: Optional[str] = None,
        mesh=None,
        fault_injector=None,
        model_name: str = "default",
    ):
        self.registry = registry
        self.config = config if config is not None else ServeConfig()
        config = self.config
        #: this app's catalog name.  "default" (single-model serving)
        #: keeps every metric series label-free and every response
        #: shape byte-identical to the pre-catalog stack; a named app
        #: (serve/catalog.py) labels its route/batcher series with
        #: ``{model=}`` and stamps the name into response model docs.
        self.model_name = str(model_name)
        self._mlabels = (
            {"model": self.model_name}
            if self.model_name != "default" else None
        )
        #: name -> sibling ServeApp table, set by ModelCatalog so
        #: ``/v1/<name>/*`` delegates across models; None outside a
        #: catalog (model-prefixed paths then 404)
        self.catalog_apps: Optional[Dict[str, "ServeApp"]] = None
        # resilience/faults.py FaultInjector — None means no fault code
        # runs at all (the production default)
        self.faults = fault_injector
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        if self.faults is not None and self.faults.metrics is None:
            self.faults.metrics = self.metrics
        if registry.metrics is None:
            registry.metrics = self.metrics
        if registry.loaded:
            # the registry publishes these on swap; backfill for a model
            # loaded before the metrics registry was attached (labeled
            # twin included under a non-default registry name)
            registry._gauge_labeled(
                "model_iteration", registry.model.iteration
            )
            registry._gauge_labeled("model_vocab_size", len(registry.model))
        # mesh set => the two-stage distributed top-k over the
        # registry's row-sharded matrix (engine._make_topk_sharded)
        self.engine = SimilarityEngine(
            max_batch=config.max_batch, mesh=mesh,
            index=config.index, nprobe=config.nprobe,
            rescore_mult=config.rescore_mult,
        )
        # multi-tenant admission: None (the default) means tenancy is
        # entirely off — requests carry the default tenant id and never
        # touch a bucket (docs/SERVING.md#multi-tenant-admission)
        tenant_policy = TenantPolicy.from_args(
            config.tenant_rate, config.tenant_burst or None,
            config.tenant_overrides,
        )
        self.tenants: Optional[TenantAdmission] = (
            TenantAdmission(tenant_policy, metrics=self.metrics)
            if tenant_policy is not None else None
        )
        self.batcher = MicroBatcher(
            self._compute_batch,
            max_batch=config.max_batch,
            max_delay_s=config.max_delay_ms / 1000.0,
            max_queue=config.max_queue,
            cache_size=config.cache_size,
            default_timeout_s=config.timeout_ms / 1000.0,
            metrics=self.metrics,
            tenant_weights=self._tenant_weight,
            labels=self._mlabels,
        )
        self.ggipnn_checkpoint = ggipnn_checkpoint
        self._scorer: Optional[InteractionScorer] = None
        self._scorer_lock = threading.Lock()
        self._started = time.monotonic()
        # jit compile-event visibility: the process-wide CompileWatcher
        # feeds a monotone counter on /metrics (publish_engine_metrics
        # mirrors the watcher by delta), which the fleet aggregator
        # sums into fleet_jit_compiles and the default
        # jit-recompile-storm alert rule watches per scrape tick
        self._compile_watcher = probes.CompileWatcher.install()
        self._compile_events_published = 0
        # head sampler for headerless traffic; propagated sampled
        # contexts bypass it (the root already decided)
        self.sampler = (
            Sampler(config.trace_sample) if config.trace_sample > 0
            else None
        )
        # always-on bounded ring of recent requests; cli/serve.py sets
        # flight_dir (the run dir) and installs the SIGQUIT dump — a
        # 5xx burst dumps from the handler path below, through the
        # shared rate limiter (obs/alerts.py) so burst dumps and any
        # rule-triggered bundles draw from one disk-write budget
        self.flight_limiter = RateLimiter(
            min_interval_s=config.burst_window_s
        )
        self.flight = FlightRecorder(
            burst_threshold=config.burst_threshold,
            burst_window_s=config.burst_window_s,
            limiter=self.flight_limiter,
        )
        self.flight_dir: Optional[str] = None
        # -- event-loop hot path state ---------------------------------
        # pre-serialized response bodies keyed (model version, gene, k):
        # a hot GET is answered with reused bytes, no JSON assembly; a
        # hot swap invalidates naturally (new version => new keys)
        self.response_cache = LRUCache(config.cache_size)
        # coalescing table for concurrent identical GETs: key -> list of
        # (peer, deadline, t0) waiting on ONE batcher ticket
        self._coalesce: Dict[tuple, list] = {}
        self._coalesce_lock = threading.Lock()
        # -- offline batch jobs (gene2vec_tpu/batch/) ------------------
        # the /v1/jobs lifecycle manager: jobs query THIS replica's
        # batcher on the low-weight batch tenant lane.  Imported lazily
        # — serve/__init__ imports this module, and batch/ imports
        # serve.tenancy (docs/BATCH.md).  None (the default) keeps the
        # whole plane absent: no store, no worker thread, 404 routes.
        self.jobs = None
        if config.jobs_dir:
            from gene2vec_tpu.batch.jobs import JobManager
            from gene2vec_tpu.batch.runner import BatcherBackend, Pacer

            self.jobs = JobManager(
                config.jobs_dir,
                backend_factory=lambda: BatcherBackend(self),
                metrics=self.metrics,
                pacer_factory=lambda backend: Pacer(
                    guard=backend.pressure,
                    guard_max=config.batch_guard_max,
                    duty=config.batch_duty,
                ),
            )

    def _route_labels(self, route: str) -> Dict[str, str]:
        """The bounded label set for per-route latency series: the
        canonical route (model prefixes already stripped by dispatch)
        plus — only under a catalog name — ``model=``.  Single-model
        deployments keep the exact historical label sets, so the fleet
        aggregator's route-p99 snapshot keys (and the default alert
        rules watching them) are unchanged."""
        labels = {"route": _route_label(route)}
        if self._mlabels is not None:
            labels["model"] = self.model_name
        return labels

    def _model_doc(self, model) -> dict:
        """The response's ``model`` object; carries the catalog name so
        a client (and the chaos drill's cross-model checker) can verify
        WHICH model answered."""
        doc = {"dim": model.dim, "iteration": model.iteration}
        if self.model_name != "default":
            doc["name"] = self.model_name
        return doc

    def _tenant_weight(self, tenant: str) -> float:
        """The batcher's weighted-fair drain share: the reserved batch
        lane runs at ``batch_weight`` always (even with tenancy off —
        background priority is not opt-in), everyone else at their
        quota weight (1.0 untenanted)."""
        if tenant == BATCH_TENANT:
            return self.config.batch_weight
        if self.tenants is not None:
            return self.tenants.weight(tenant)
        return 1.0

    def start(self) -> "ServeApp":
        self.batcher.start()
        if self.jobs is not None:
            self.jobs.start()
        return self

    def stop(self) -> None:
        if self.jobs is not None:
            self.jobs.stop()
        self.batcher.stop()
        self.registry.stop_watcher()

    # -- batch compute (worker thread) ------------------------------------

    def _compute_batch(self, items: List[dict], k_max: int) -> List[dict]:
        """Resolve every queued query against ONE model snapshot and run
        the padded top-k.  Items resolved here (not at submit) so a hot
        swap mid-queue cannot mix two iterations inside one batch."""
        model = self.registry.model
        vectors: List[np.ndarray] = []
        self_rows: List[Optional[int]] = []
        for item in items:
            if "gene" in item:
                row = model.index.get(item["gene"])
                if row is None:
                    # swapped away between admission and compute —
                    # per-item failure, the rest of the batch proceeds
                    vectors.append(np.zeros(model.dim, np.float32))
                    self_rows.append(-2)
                    continue
                vectors.append(model.emb[row])
                self_rows.append(row)
            else:
                vectors.append(
                    np.asarray(item["vector"], dtype=np.float32)
                )
                self_rows.append(None)
        # gene queries ask one extra so dropping the self-hit still
        # leaves k neighbors
        kq = min(k_max + 1, len(model))
        if self.engine.index_mode != "exact" and model.ann is None:
            # approximate engine over a snapshot without an index
            # (registry built exact, or a legacy LoadedModel): served
            # exactly, but visibly — a fleet rollout that silently
            # never uses its index would hide a real capacity gap
            self.metrics.counter("engine_index_fallback_total").inc()
        neighbors = self.engine.similar_batch(model, vectors, kq)
        out: List[dict] = []
        for item, row, hits in zip(items, self_rows, neighbors):
            if row == -2:
                out.append(
                    {"error": f"gene {item['gene']!r} not in the "
                              f"served model (iteration "
                              f"{model.iteration})"}
                )
                continue
            if row is not None:
                gene = model.tokens[row]
                hits = [h for h in hits if h[0] != gene]
            out.append(
                {
                    "neighbors": [
                        {"gene": g, "score": round(s, 6)}
                        for g, s in hits[: item["k"]]
                    ],
                    "iteration": model.iteration,
                }
            )
        return out

    # -- routes ------------------------------------------------------------

    def _model_or_503(self):
        try:
            return self.registry.model
        except RuntimeError as e:
            raise ApiError(503, str(e)) from e

    def _validate_k(self, body: dict) -> int:
        k = body.get("k", 10)
        if not isinstance(k, int) or k < 1 or k > self.config.max_k:
            raise ApiError(
                400, f"k must be an int in [1, {self.config.max_k}]"
            )
        return k

    def similar(self, body: dict,
                tenant: str = DEFAULT_TENANT) -> dict:
        model = self._model_or_503()
        k = self._validate_k(body)
        timeout_s = self._timeout_s(body)
        genes = body.get("genes")
        vectors = body.get("vectors")
        if (genes is None) == (vectors is None):
            raise ApiError(
                400, "provide exactly one of 'genes' or 'vectors'"
            )
        queries: List[dict] = []
        if genes is not None:
            if not isinstance(genes, list) or not genes:
                raise ApiError(400, "'genes' must be a non-empty list")
            unknown = [g for g in genes if g not in model.index]
            if unknown:
                raise ApiError(
                    400,
                    f"unknown gene(s) {unknown[:5]!r} "
                    f"(model iteration {model.iteration})",
                )
            queries = [{"gene": g, "k": k} for g in genes]
        else:
            if not isinstance(vectors, list) or not vectors:
                raise ApiError(400, "'vectors' must be a non-empty list")
            for v in vectors:
                if not isinstance(v, list) or len(v) != model.dim:
                    raise ApiError(
                        400,
                        f"each vector must have dim {model.dim}",
                    )
            queries = [{"vector": v, "k": k} for v in vectors]
        if len(queries) > self.config.max_queries_per_request:
            raise ApiError(
                400,
                f"at most {self.config.max_queries_per_request} queries "
                "per request",
            )
        # submit everything before waiting on anything, so one request's
        # queries share a batch window instead of paying it per query
        tickets = []
        try:
            for q in queries:
                cache_key = (
                    (model.version, "similar", q["gene"], k)
                    if "gene" in q else None
                )
                tickets.append(
                    (q, self.batcher.submit_async(
                        q, k, cache_key=cache_key, timeout_s=timeout_s,
                        tenant=tenant,
                    ))
                )
        except RejectedError as e:
            raise ApiError(429, str(e)) from e
        results = []
        # the iteration that ACTUALLY answered: the batcher resolves
        # items against its own model snapshot at compute time, so a
        # hot swap between admission and compute would otherwise stamp
        # this response with an iteration its neighbors did not come
        # from — the mixed-iteration answer every chaos drill gates at
        # zero.  One request's queries landing in batches on opposite
        # sides of a swap is refused as a retryable 503 (the front
        # door's client retries it off the caller's path).
        served_iteration: Optional[int] = None
        for q, ticket in tickets:
            try:
                r = ticket.get()
            except DeadlineExceeded as e:
                raise ApiError(504, str(e)) from e
            if "error" in r:
                raise ApiError(400, r["error"])
            it = r.get("iteration")
            if served_iteration is None:
                served_iteration = it
            elif it is not None and it != served_iteration:
                raise ApiError(
                    503,
                    f"hot swap landed mid-request (iterations "
                    f"{served_iteration} and {it} in one response); "
                    "retry",
                )
            results.append(
                {"query": q.get("gene"), "neighbors": r["neighbors"]}
            )
        doc = {
            "model": self._model_doc(model),
            "results": results,
        }
        if served_iteration is not None:
            doc["model"]["iteration"] = served_iteration
        return doc

    def embedding(self, body: dict) -> dict:
        model = self._model_or_503()
        genes = body.get("genes")
        if not isinstance(genes, list) or not genes:
            raise ApiError(400, "'genes' must be a non-empty list")
        if len(genes) > self.config.max_queries_per_request:
            raise ApiError(
                400,
                f"at most {self.config.max_queries_per_request} genes "
                "per request",
            )
        rows = []
        for g in genes:
            row = model.index.get(g)
            if row is None:
                raise ApiError(
                    400,
                    f"unknown gene {g!r} (model iteration "
                    f"{model.iteration})",
                )
            rows.append(
                {"gene": g, "vector": [float(v) for v in model.emb[row]]}
            )
        return {
            "model": self._model_doc(model),
            "embeddings": rows,
        }

    def _get_scorer(self, model) -> InteractionScorer:
        """Scorer bound to the served iteration; rebuilt after hot swap."""
        with self._scorer_lock:
            if self._scorer is None or self._scorer.version != model.version:
                with ambient_span(
                    "scorer_build", iteration=model.iteration
                ):
                    self._scorer = InteractionScorer(
                        model, checkpoint_path=self.ggipnn_checkpoint
                    )
            return self._scorer

    def interaction(self, body: dict) -> dict:
        model = self._model_or_503()
        pairs = body.get("pairs")
        if not isinstance(pairs, list) or not pairs or not all(
            isinstance(p, list) and len(p) == 2 for p in pairs
        ):
            raise ApiError(
                400, "'pairs' must be a non-empty list of [gene, gene]"
            )
        if len(pairs) > self.config.max_queries_per_request:
            raise ApiError(
                400,
                f"at most {self.config.max_queries_per_request} pairs "
                "per request",
            )
        scorer = self._get_scorer(model)
        try:
            scores = scorer.score([tuple(p) for p in pairs])
        except KeyError as e:
            raise ApiError(
                400,
                f"unknown gene {e.args[0]!r} (model iteration "
                f"{model.iteration})",
            ) from e
        self.metrics.counter("serve_interaction_pairs_total").inc(
            len(pairs)
        )
        return {
            "model": self._model_doc(model),
            "trained_head": scorer.trained,
            "scores": [
                {"pair": p, "score": round(s, 6)}
                for p, s in zip(pairs, scores)
            ],
        }

    # -- shard data/control plane (serve/shardgroup.py scatter-gather) -----

    def _shard_facts(self, model) -> dict:
        base = int(getattr(model, "row_base", 0) or 0)
        return {
            "index": self.registry.shard[0],
            "num_shards": self.registry.shard[1],
            "rows": [base, base + len(model)],
            "total_rows": getattr(model, "total_rows", None),
            "epoch": getattr(model, "epoch", None),
            "iteration": model.iteration,
        }

    def _require_shard(self) -> None:
        if self.registry.shard is None:
            raise ApiError(
                404,
                "this replica is not sharded (/v1/shard/* needs "
                "cli.serve --shard-index/--num-shards)",
            )

    def shard_topk(self, body: dict) -> dict:
        """Shard-local top-k over this replica's row range, with GLOBAL
        row ids — one leg of the front door's scatter.  ``vectors`` are
        scored directly; ``genes`` must be OWNED by this shard (the
        routing table sends gene resolution to the owner).  An
        ``epoch`` in the body is the caller's merge target: answering
        from a different epoch is refused with 409 so a mid-swap shard
        can never leak rows from another iteration into a merge."""
        self._require_shard()
        model = self._model_or_503()
        # max_k + 1 headroom: a front-door gene query fetches k+1 so
        # dropping the self-hit still leaves k — k=max_k through the
        # scatter must not 400 here when it is valid on a replica
        k = body.get("k", 10)
        if not isinstance(k, int) or not 1 <= k <= self.config.max_k + 1:
            raise ApiError(
                400, f"k must be an int in [1, {self.config.max_k + 1}]"
            )
        want_epoch = body.get("epoch")
        if want_epoch is not None and want_epoch != model.epoch:
            raise ApiError(
                409,
                f"epoch mismatch: serving {model.epoch}, caller wants "
                f"{want_epoch}",
            )
        vectors = body.get("vectors")
        genes = body.get("genes")
        if (genes is None) == (vectors is None):
            raise ApiError(
                400, "provide exactly one of 'genes' or 'vectors'"
            )
        queries: List[np.ndarray] = []
        if vectors is not None:
            if not isinstance(vectors, list) or not vectors:
                raise ApiError(400, "'vectors' must be a non-empty list")
            for v in vectors:
                if not isinstance(v, list) or len(v) != model.dim:
                    raise ApiError(
                        400, f"each vector must have dim {model.dim}"
                    )
                queries.append(np.asarray(v, dtype=np.float32))
        else:
            if not isinstance(genes, list) or not genes:
                raise ApiError(400, "'genes' must be a non-empty list")
            for g in genes:
                row = model.index.get(g)
                if row is None:
                    raise ApiError(
                        400,
                        f"gene {g!r} is not owned by shard "
                        f"{self.registry.shard[0]}",
                    )
                queries.append(model.emb[row])
        if len(queries) > self.config.max_queries_per_request:
            raise ApiError(
                400,
                f"at most {self.config.max_queries_per_request} queries "
                "per request",
            )
        with ambient_span(
            "shard_topk", n=len(queries), k=k,
            shard=self.registry.shard[0],
        ):
            scores, rows = self.engine.topk_rows(
                model, np.stack(queries), k
            )
        tokens = model.tokens
        base = int(getattr(model, "row_base", 0) or 0)
        return {
            "shard": self._shard_facts(model),
            "results": [
                {
                    "rows": [int(r) for r in row_ids],
                    "scores": [round(float(s), 6) for s in row_scores],
                    "tokens": [
                        tokens[int(r) - base] for r in row_ids
                    ],
                }
                for row_scores, row_ids in zip(scores, rows)
            ],
        }

    def shard_vectors(self, body: dict) -> dict:
        """Resolve OWNED genes to their raw embedding vectors — the
        front door's gene→vector step before a vector scatter.  Genes
        outside this shard's range are the caller's routing bug →
        400."""
        self._require_shard()
        model = self._model_or_503()
        genes = body.get("genes")
        if not isinstance(genes, list) or not genes:
            raise ApiError(400, "'genes' must be a non-empty list")
        vectors = []
        for g in genes:
            row = model.index.get(g)
            if row is None:
                raise ApiError(
                    400,
                    f"gene {g!r} is not owned by shard "
                    f"{self.registry.shard[0]}",
                )
            vectors.append([float(v) for v in model.emb[row]])
        return {
            "shard": self._shard_facts(model),
            "vectors": vectors,
        }

    def shard_stage(self, body: dict) -> dict:
        """Stage (load + CRC-verify, do NOT serve) one iteration — the
        coordinator calls this on every shard before any shard flips.
        Failure → 503 so the coordinator aborts the swap."""
        self._require_shard()
        dim = body.get("dim")
        iteration = body.get("iteration")
        if not isinstance(dim, int) or not isinstance(iteration, int):
            raise ApiError(400, "'dim' and 'iteration' must be ints")
        try:
            staged = self.registry.stage(dim, iteration)
        except Exception as e:
            raise ApiError(
                503, f"stage of dim={dim} iter={iteration} failed: {e!r}"
            ) from e
        return {
            "staged": {
                "dim": staged.dim,
                "iteration": staged.iteration,
                "rows": len(staged),
                "total_rows": staged.total_rows,
            },
        }

    def shard_flip(self, body: dict) -> dict:
        """Atomically swap the staged iteration in under the fleet's
        epoch token — the coordinator issues this only after EVERY
        shard staged.  409 when nothing matching is staged (the
        coordinator re-stages)."""
        self._require_shard()
        epoch = body.get("epoch")
        if not isinstance(epoch, int):
            raise ApiError(400, "'epoch' must be an int")
        try:
            model = self.registry.flip(epoch)
        except RuntimeError as e:
            raise ApiError(409, str(e)) from e
        return {"shard": self._shard_facts(model)}

    @staticmethod
    def _int_param(query: Dict[str, List[str]], name: str,
                   default: int) -> int:
        raw = query.get(name, [str(default)])[0]
        try:
            return int(raw)
        except ValueError:
            raise ApiError(
                400, f"{name} must be an integer, got {raw!r}"
            ) from None

    def genes(self, query: Dict[str, List[str]]) -> dict:
        model = self._model_or_503()
        limit = self._int_param(query, "limit", 100)
        offset = self._int_param(query, "offset", 0)
        if limit < 0 or offset < 0:
            raise ApiError(400, "limit/offset must be >= 0")
        return {
            "total": len(model),
            "genes": list(model.tokens[offset : offset + limit]),
        }

    def profile_kernels(self, k: int = 16) -> Dict[str, Dict]:
        """Warm-time per-bucket kernel attribution: AOT-compile the
        active index mode's kernel at every batch bucket against the
        served model and publish the static costs + compile seconds as
        ``kernel_*`` gauges (``publish_engine_metrics``).  No-op (empty
        dict) when no model is loaded or the mode needs an ANN index
        the snapshot doesn't carry — a mid-rollout replica must not
        crash over its own telemetry."""
        if not self.registry.loaded:
            return {}
        model = self.registry.model
        ann_index = getattr(model, "ann", None)
        if self.engine.index_mode != "exact" and ann_index is None:
            return {}
        try:
            costs = self.engine.profile_buckets(
                model.unit, valid=len(model), k=k, ann_index=ann_index,
            )
        except Exception:
            return {}
        self.publish_engine_metrics()
        return costs

    def publish_engine_metrics(self) -> None:
        """Export the engine's per-index-mode jit-cache entry counts as
        ``engine_jit_cache_entries{mode=...}`` — refreshed at each
        ``/metrics`` scrape, so a recompile leak in any mode (the
        hazard class ``hlo-cache-stability`` gates at analysis time)
        is also observable on a live replica."""
        for mode, size in self.engine.cache_sizes().items():
            if size is not None:
                self.metrics.gauge(
                    "engine_jit_cache_entries",
                    labels={"mode": mode, **(self._mlabels or {})},
                ).set(size)
        # per-bucket kernel attribution (profile_kernels), as the same
        # kernel_* gauge family run snapshots use — bounded: buckets x
        # modes stays far under the registry's label-cardinality cap
        for name, costs in self.engine.kernel_costs().items():
            labels = {"kernel": name}
            for field, metric in (
                ("flops", "kernel_flops"),
                ("bytes_accessed", "kernel_bytes_accessed"),
                ("peak_memory_bytes", "kernel_peak_memory_bytes"),
                ("lower_s", "kernel_lower_seconds"),
                ("compile_s", "kernel_compile_seconds"),
            ):
                v = costs.get(field)
                if v is not None:
                    self.metrics.gauge(metric, labels=labels).set(
                        float(v)
                    )
        # compile events observed since the last scrape -> monotone
        # counter (counters survive the aggregator's reset-rebasing;
        # the raw watcher count would read as a gauge and lose deltas)
        if self._compile_watcher is not None:
            delta = (
                self._compile_watcher.count
                - self._compile_events_published
            )
            if delta > 0:
                self.metrics.counter(
                    "jit_compile_events_total",
                    "jax compilation events seen by this process",
                ).inc(delta)
                self._compile_events_published = (
                    self._compile_watcher.count
                )
        # served-model freshness facts, refreshed per scrape: the fleet
        # aggregator lifts these into fleet_model_iteration{target=} /
        # fleet_model_age_seconds{target=} and the default staleness
        # alert rule watches the fleet-wide max — a fleet silently
        # stuck on an old iteration (quarantined candidate, wedged
        # promotion) must fire, not linger (docs/CONTINUOUS.md)
        if self.registry.loaded:
            model = self.registry.model
            self.registry._gauge_labeled(
                "model_age_seconds",
                max(0.0, time.time() - model.created_unix),
            )

    def livez(self) -> dict:
        """Liveness: the process answers HTTP.  Never inspects the
        registry — a replica mid-load (or quarantined with no fallback)
        is alive-but-not-ready, and restarting it would only lose the
        load progress."""
        return {
            "status": "alive",
            "uptime_s": round(time.monotonic() - self._started, 3),
        }

    def healthz(self) -> Tuple[int, dict]:
        """Readiness: 200 with model facts once a model is served; 503
        ``not_ready`` until then, so fleet routers and external probes
        never send traffic to an empty replica."""
        ready = self.registry.loaded
        out = {
            "status": "ok" if ready else "not_ready",
            "uptime_s": round(time.monotonic() - self._started, 3),
            "queue_depth": len(self.batcher._q),
            "max_queue": self.config.max_queue,
        }
        if not ready:
            quarantined = getattr(self.registry, "quarantined", {})
            out["reason"] = (
                "every discovered checkpoint is quarantined"
                if quarantined else "no model loaded yet"
            )
            return 503, out
        m = self.registry.model
        out["model"] = {
            "dim": m.dim,
            "iteration": m.iteration,
            "vocab_size": len(m),
            "source": m.source,
        }
        if self.model_name != "default":
            out["model"]["name"] = self.model_name
        if self.catalog_apps is not None:
            out["catalog"] = sorted(self.catalog_apps)
        out["index"] = self.engine.index_mode
        if self.registry.shard is not None:
            out["shard"] = self._shard_facts(m)
        if self.tenants is not None:
            out["tenancy"] = {
                "default_rate": self.tenants.policy.default.rate,
                "default_burst": self.tenants.policy.default.burst,
                "overrides": sorted(self.tenants.policy.overrides),
            }
        if m.ann is not None:
            from gene2vec_tpu.serve.ann import index_stats

            out["ann"] = index_stats(m.ann)
        return 200, out

    def _timeout_s(self, body: dict) -> Optional[float]:
        t = body.get("timeout_ms")
        if t is None:
            return None
        if not isinstance(t, (int, float)) or t <= 0:
            raise ApiError(400, "timeout_ms must be a positive number")
        return float(t) / 1000.0

    # -- dispatch ----------------------------------------------------------

    def _dispatch(
        self, method: str, route: str, query: Dict[str, List[str]],
        body: Optional[dict], tenant: str = DEFAULT_TENANT,
    ) -> Tuple[int, dict]:
        if method == "GET" and route == "/livez":
            return 200, self.livez()
        if method == "GET" and route == "/healthz":
            status, doc = self.healthz()
            return status, doc
        if method == "GET" and route == "/v1/genes":
            return 200, self.genes(query)
        if method == "GET" and route == "/v1/similar":
            gene = query.get("gene", [None])[0]
            if gene is None:
                raise ApiError(400, "missing ?gene= parameter")
            k = self._int_param(query, "k", 10)
            return 200, self.similar(
                {"genes": [gene], "k": k}, tenant=tenant
            )
        if method == "POST" and route == "/v1/similar":
            return 200, self.similar(body or {}, tenant=tenant)
        if method == "POST" and route == "/v1/embedding":
            return 200, self.embedding(body or {})
        if method == "POST" and route == "/v1/interaction":
            return 200, self.interaction(body or {})
        if method == "POST" and route == "/v1/shard/topk":
            return 200, self.shard_topk(body or {})
        if method == "POST" and route == "/v1/shard/vectors":
            return 200, self.shard_vectors(body or {})
        if method == "POST" and route == "/v1/shard/stage":
            return 200, self.shard_stage(body or {})
        if method == "POST" and route == "/v1/shard/flip":
            return 200, self.shard_flip(body or {})
        if route == JOBS_ROUTE or route.startswith(JOBS_ROUTE + "/"):
            from gene2vec_tpu.batch.jobs import dispatch_jobs

            return dispatch_jobs(self.jobs, method, route, query, body)
        return 404, {"error": f"no route {method} {route}"}

    def handle(
        self, method: str, path: str, body: Optional[dict],
        traceparent: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> Tuple[int, dict]:
        """(status, payload) for one request.  ``/metrics`` is the only
        non-JSON route and is dispatched by the handler directly.

        ``traceparent`` is the caller's propagated trace context: a
        sampled one makes this request (and its batcher/engine hops) a
        child span of the sender's attempt; without one, the server's
        own sampler may start a root.  Untraced requests pay one header
        parse and nothing else.

        ``tenant`` is the request's (already sanitized) tenant id —
        the adapter enforces the token-bucket quota BEFORE calling
        here; inside, the id only routes the batcher's weighted-fair
        lane."""
        url = urlparse(path)
        route = url.path.rstrip("/") or "/"
        # -- multi-model catalog dispatch (serve/catalog.py) -----------
        # /v1/<name>/similar etc. resolves against the catalog table:
        # a sibling app serves it (its OWN registry, engine, cache,
        # labels), this app's own name is an alias for its unprefixed
        # routes, and an unknown name 404s BEFORE any label is minted —
        # model= cardinality is bounded by the catalog, not by traffic.
        name, canonical = split_model_route(route)
        if name is not None:
            target = self if name == self.model_name else (
                self.catalog_apps.get(name)
                if self.catalog_apps is not None else None
            )
            if target is None:
                self.metrics.counter("serve_http_404_total").inc()
                return 404, {"error": f"unknown model {name!r}"}
            if target is not self:
                suffix = f"?{url.query}" if url.query else ""
                return target.handle(
                    method, canonical + suffix, body,
                    traceparent=traceparent, tenant=tenant,
                )
            route = canonical
        query = parse_qs(url.query)
        tenant = tenant if tenant else DEFAULT_TENANT
        incoming = TraceContext.from_header(traceparent)
        ctx = incoming.child() if incoming is not None else (
            self.sampler.maybe_new_trace()
            if self.sampler is not None else None
        )
        t0 = time.monotonic()
        status = 500
        hops: Dict[str, float] = {}
        try:
            with tracecontext.use(ctx), flight_mod.collect_hops() as hops:
                with ambient_span("serve_request", route=route) as span:
                    status, doc = self._dispatch(
                        method, route, query, body, tenant=tenant
                    )
                    span["status"] = status
            return status, doc
        except ApiError as e:
            self.metrics.counter(
                f"serve_http_{e.status}_total"
            ).inc()
            status = e.status
            return e.status, {"error": str(e)}
        except Exception as e:  # route crash -> 500, server stays up
            self.metrics.counter("serve_http_500_total").inc()
            status = 500
            return 500, {"error": f"internal error: {e!r}"}
        finally:
            dur = time.monotonic() - t0
            self.metrics.histogram("serve_handle_seconds").observe(dur)
            self.metrics.histogram(
                "serve_route_seconds",
                buckets=_ROUTE_BUCKETS,
                labels=self._route_labels(route),
            ).observe(dur)
            burst = self.flight.record(
                route, status, dur,
                trace_id=ctx.trace_id if ctx is not None else None,
                hops=hops,
            )
            if burst and self.flight_dir:
                try:
                    self.flight.dump(self.flight_dir, "5xx-burst")
                except OSError:
                    pass  # a full disk must not take the handler down


#: pre-encoded front-end bodies (the event loop never runs json.dumps)
_POOL_FULL_BODY = b'{"error": "handler pool saturated; shed load"}'
_DEADLINE_BODY = b'{"error": "request deadline exceeded"}'
_TENANT_QUOTA_BODY = (
    b'{"error": "tenant quota exhausted; retry after backoff"}'
)


class ServeAdapter:
    """The event-loop handler for one :class:`ServeApp`.

    Called on the loop thread for every parsed request.  The hot read
    path (untraced, fault-free ``GET /v1/similar``) is answered inline
    from the response-bytes cache or coalesced onto one batcher ticket;
    everything else defers to the bounded worker pool, which runs the
    unchanged :meth:`ServeApp.handle` pipeline (spans, flight recorder,
    status mapping, fault injection)."""

    def __init__(self, app: ServeApp):
        self.app = app
        self.pool = HandlerPool(
            app.config.http_workers, app.config.http_queue,
            name="serve-http",
        )
        self._queue_full_body = (
            b'{"error": "queue full (%d waiting requests)"}'
            % app.config.max_queue
        )

    def close(self) -> None:
        self.pool.stop()

    # -- accounting (hot path only; ServeApp.handle does its own) ---------

    def _account(self, route: str, status: int, dur: float,
                 app: Optional[ServeApp] = None) -> None:
        app = self.app if app is None else app
        app.metrics.histogram("serve_handle_seconds").observe(dur)
        app.metrics.histogram(
            "serve_route_seconds",
            buckets=_ROUTE_BUCKETS,
            labels=app._route_labels(route),
        ).observe(dur)
        if status >= 400:
            app.metrics.counter(f"serve_http_{status}_total").inc()
        burst = app.flight.record(route, status, dur)
        if burst and app.flight_dir:
            # dump on a pool worker: _account runs on the loop thread
            # for the fast path, and a 5xx burst is the worst moment to
            # stall the loop behind flight-dump file I/O.  Dropped when
            # the pool is saturated — the burst window re-arms and the
            # next 5xx re-triggers the dump.
            self.pool.submit(self._dump_flight)

    def _dump_flight(self) -> None:
        app = self.app
        try:
            app.flight.dump(app.flight_dir, "5xx-burst")
        except OSError:
            pass

    def account_protocol_error(self, status: int) -> None:
        """Loop-generated 400/408 responses (malformed request line,
        slow-loris reap) keep their counters."""
        self.app.metrics.counter(f"serve_http_{status}_total").inc()

    # -- entry point (loop thread) ----------------------------------------

    def __call__(self, req: HTTPRequest,
                 peer: ConnHandle) -> Optional[Response]:
        app = self.app
        tenant = DEFAULT_TENANT
        if app.tenants is not None:
            # per-tenant token-bucket quota, decided HERE at the front
            # door: an over-quota request costs one O(1) bucket take
            # and a pre-encoded 429 — it never reaches the worker pool,
            # the batcher queue, or the response cache.  The resolved
            # label (bounded; minted ids collapse into "other") is what
            # flows into the batcher's fair lanes.
            if req.target.startswith("/v1/"):
                ok, tenant = app.tenants.admit(
                    sanitize_tenant(req.headers.get("x-tenant"))
                )
                if not ok:
                    app.metrics.counter("serve_http_429_total").inc()
                    return Response(429, _TENANT_QUOTA_BODY)
        if (
            req.method == "GET"
            and app.faults is None
            and app.sampler is None
            and "traceparent" not in req.headers
        ):
            # resolve which app's hot path this GET belongs to:
            # unprefixed -> this (default) app, /v1/<name>/similar? ->
            # the named sibling — each with its OWN response cache and
            # coalescing table, so two models can never share bytes
            fast = None
            query_str = ""
            if req.target.startswith("/v1/similar?"):
                fast = app
                query_str = req.target[len("/v1/similar?"):]
            elif req.target.startswith("/v1/"):
                name, sep, tail = (
                    req.target[len("/v1/"):].partition("/")
                )
                if sep and tail.startswith("similar?"):
                    fast = (
                        app.catalog_apps.get(name)
                        if app.catalog_apps is not None
                        else (app if name == app.model_name else None)
                    )
                    query_str = tail[len("similar?"):]
            if fast is not None:
                out = self._similar_get_fast(
                    fast, query_str, peer, tenant
                )
                if out is not _SLOW_PATH:
                    return out
        if not self.pool.submit(
            lambda: self._run_full(req, peer, tenant)
        ):
            self.app.metrics.counter("serve_http_429_total").inc()
            return Response(429, _POOL_FULL_BODY)
        return None

    # -- the full pipeline (worker pool thread) ----------------------------

    def _run_full(self, req: HTTPRequest, peer: ConnHandle,
                  tenant: str = DEFAULT_TENANT) -> None:
        app = self.app
        route = urlparse(req.target).path.rstrip("/") or "/"
        if app.faults is not None and self._apply_fault(req, peer, route):
            return
        if req.method == "GET" and route == "/metrics":
            app.publish_engine_metrics()
            if app.catalog_apps is not None:
                # one scrape refreshes EVERY cataloged model's engine
                # and freshness gauges (shared metrics registry)
                for sibling in app.catalog_apps.values():
                    if sibling is not app:
                        sibling.publish_engine_metrics()
            peer.respond(Response(
                200,
                app.metrics.prometheus_text().encode("utf-8"),
                b"text/plain; version=0.0.4",
            ))
            return
        if req.method == "GET" and route == "/debug/flight":
            # the SIGQUIT-equivalent flight dump, over the wire: the
            # incident manager solicits every live replica's ring when
            # a rule fires (docs/OBSERVABILITY.md#alerting); needs no
            # model, so a not-ready replica still testifies
            peer.respond(Response(
                200,
                json.dumps(app.flight.snapshot_doc("debug"))
                .encode("utf-8"),
            ))
            return
        if req.method not in ("GET", "POST"):
            peer.respond(Response(
                404,
                json.dumps(
                    {"error": f"no route {req.method} {route}"}
                ).encode("utf-8"),
            ))
            return
        body: Optional[dict] = None
        if req.method == "POST":
            body, err = parse_json_body(req)
            if err is not None:
                peer.respond(err)
                return
        status, doc = app.handle(
            req.method, req.target, body,
            traceparent=req.headers.get("traceparent"),
            tenant=tenant,
        )
        peer.respond(Response(
            status, json.dumps(doc).encode("utf-8")
        ))

    def _apply_fault(self, req: HTTPRequest, peer: ConnHandle,
                     route: str) -> bool:
        """Port of the threaded front end's fault hook: True when the
        fault terminated the request.  Runs on a pool thread, so the
        delay/blackhole sleeps never touch the event loop."""
        decision = self.app.faults.decide(route)
        if decision is None:
            return False
        if decision.delay_s:
            time.sleep(decision.delay_s)
        if decision.kind is None:
            return False  # pure added latency; proceed normally
        if decision.kind == "error":
            peer.respond(Response(
                int(decision.arg),
                b'{"error": "injected fault (resilience drill)"}',
                close=True,
            ))
        elif decision.kind == "reset":
            peer.reset()
        elif decision.kind == "blackhole":
            # hold the socket open, answer nothing; the client's read
            # timeout is the only way out (bounded so pool threads
            # drain)
            time.sleep(decision.arg)
            peer.close()
        return True

    # -- the hot read path (loop thread; must never block) -----------------

    def _similar_get_fast(self, app: ServeApp, query_str: str,
                          peer: ConnHandle,
                          tenant: str = DEFAULT_TENANT):
        """``GET /v1/similar?gene=...&k=...`` without the full pipeline:
        response-bytes cache hit -> reused bytes; miss -> coalesce onto
        one batcher ticket.  ``app`` is the resolved target (the
        default app, or a catalog sibling for a model-prefixed GET) —
        its cache, coalescing table, batcher, and labels.  Returns
        ``_SLOW_PATH`` for anything the fast path cannot answer with
        identical semantics (unknown params, bad k, unknown gene, no
        model) so the full pipeline produces its exact error shapes."""
        gene: Optional[str] = None
        k = 10
        try:
            for part in query_str.split("&"):
                name, sep, value = part.partition("=")
                if not sep:
                    return _SLOW_PATH
                if name == "gene":
                    gene = (
                        unquote_plus(value)
                        if "%" in value or "+" in value else value
                    )
                elif name == "k":
                    k = int(value)
                else:
                    return _SLOW_PATH
        except ValueError:
            return _SLOW_PATH
        if gene is None or not 1 <= k <= app.config.max_k:
            return _SLOW_PATH
        registry = app.registry
        if not registry.loaded:
            return _SLOW_PATH  # 503 with the registry's own message
        model = registry.model
        t0 = time.monotonic()
        key = (model.version, gene, k)
        body = app.response_cache.get(key)
        if body is not None:
            app.metrics.counter("serve_response_cache_hits_total").inc()
            self._account(
                "/v1/similar", 200, time.monotonic() - t0, app=app
            )
            return Response(200, body)
        if gene not in model.index:
            return _SLOW_PATH  # 400 with the canonical unknown-gene text
        deadline = t0 + app.config.timeout_ms / 1000.0
        with app._coalesce_lock:
            waiters = app._coalesce.get(key)
            if waiters is not None:
                # someone is already computing this exact answer: join
                # their ticket — a hot gene costs ONE engine slot no
                # matter the fan-in
                waiters.append((peer, deadline, t0))
                app.metrics.counter("serve_coalesced_total").inc()
                return None
            app._coalesce[key] = [(peer, deadline, t0)]
        # submit_async invokes on_done SYNCHRONOUSLY on a batcher-LRU
        # cache hit — that would run the response encode on the loop
        # thread (the exact blocking the event-loop contract forbids),
        # so a completion firing before submit_async returns is bounced
        # onto the worker pool instead
        in_submit = [True]

        def done(result, error):
            if in_submit[0]:
                if not self.pool.submit(
                    lambda: self._finish_similar_get(
                        app, key, model, gene, result, error
                    )
                ):
                    self._fail_coalesced(
                        app, key, 429, _POOL_FULL_BODY
                    )
                return
            self._finish_similar_get(app, key, model, gene, result, error)

        try:
            app.batcher.submit_async(
                {"gene": gene, "k": k}, k,
                cache_key=(model.version, "similar", gene, k),
                timeout_s=app.config.timeout_ms / 1000.0,
                on_done=done, tenant=tenant,
            )
        except (RejectedError, RuntimeError):
            # queue full (or batcher not started): fail everyone waiting
            # on this key with explicit backpressure (_account owns the
            # 429 counter — one increment per rejected request)
            self._fail_coalesced(app, key, 429, self._queue_full_body)
        in_submit[0] = False
        return None

    def _fail_coalesced(self, app: ServeApp, key, status: int,
                        body: bytes) -> None:
        """Fail every waiter coalesced on ``key`` (in ``app``'s table)
        with one pre-encoded error body (thread-safe)."""
        with app._coalesce_lock:
            waiters = app._coalesce.pop(key, [])
        now = time.monotonic()
        for w_peer, _dl, w_t0 in waiters:
            w_peer.respond(Response(status, body))
            self._account("/v1/similar", status, now - w_t0, app=app)

    def _finish_similar_get(self, app: ServeApp, key, model, gene: str,
                            result, error) -> None:
        """Batcher completion (worker thread): build + cache the
        response bytes ONCE, then fan out to every coalesced waiter."""
        with app._coalesce_lock:
            waiters = app._coalesce.pop(key, [])
        now = time.monotonic()
        status = 200
        if error is not None:
            if isinstance(error, DeadlineExceeded):
                status, body = 504, json.dumps(
                    {"error": str(error)}
                ).encode("utf-8")
            else:
                status, body = 500, json.dumps(
                    {"error": f"internal error: {error!r}"}
                ).encode("utf-8")
        elif isinstance(result, dict) and "error" in result:
            status, body = 400, json.dumps(
                {"error": result["error"]}
            ).encode("utf-8")
        else:
            doc = {
                "model": app._model_doc(model),
                "results": [
                    {"query": gene, "neighbors": result["neighbors"]}
                ],
            }
            # stamp the iteration the batcher ACTUALLY computed
            # against: a hot swap between admission and compute must
            # not label new neighbors with the old iteration (or vice
            # versa) — that is the mixed-iteration answer the chaos
            # drills gate at zero
            if result.get("iteration") is not None:
                doc["model"]["iteration"] = result["iteration"]
            body = json.dumps(doc).encode("utf-8")
            app.response_cache.put(key, body)
        for peer, w_deadline, w_t0 in waiters:
            if status == 200 and now > w_deadline:
                # this waiter's own deadline passed mid-compute: the
                # batcher contract says it gets a 504, not a late answer
                app.metrics.counter("serve_deadline_expired_total").inc()
                peer.respond(Response(504, _DEADLINE_BODY))
                self._account("/v1/similar", 504, now - w_t0, app=app)
            else:
                peer.respond(Response(status, body))
                self._account("/v1/similar", status, now - w_t0, app=app)


#: sentinel: the fast path punts this request to the full pipeline
_SLOW_PATH = object()


def make_server(
    app: ServeApp, host: str = "127.0.0.1", port: int = 0
) -> EventLoopHTTPServer:
    """The event-loop front end bound to (host, port) — port 0 picks an
    ephemeral one (``server.server_address[1]`` has it).  The caller
    owns the serve loop (``serve_forever`` on a thread for tests,
    blocking in cli/serve.py) and shutdown ordering:
    ``server.shutdown()`` then ``app.stop()``."""
    adapter = ServeAdapter(app)
    cfg = app.config
    return EventLoopHTTPServer(
        adapter,
        host,
        port,
        config=EventLoopConfig(
            read_timeout_s=cfg.read_timeout_s,
            idle_timeout_s=cfg.idle_timeout_s,
            max_conn_requests=cfg.max_conn_requests,
            acceptors=cfg.acceptors,
        ),
        on_protocol_error=adapter.account_protocol_error,
    )
