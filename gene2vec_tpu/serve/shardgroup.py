"""Fleet-sharded index serving: scatter-gather top-k over row shards.

PR 10 proved 1M rows on one replica; the next order of magnitude does
not fit one host.  ``cli.fleet --shard-by-rows N`` assigns each replica
a CONTIGUOUS row range of the table (and its IVF inverted lists —
``serve/registry.py`` loads only the shard's slice), and this module is
the front door's half of the story:

* :class:`RoutingTable` — gene→shard routing derived from the export
  manifest: the newest verified checkpoint's vocab order IS the global
  row order, and ``parallel/sharding.py:shard_ranges`` maps rows to
  shards.  The front door answers ``/v1/genes`` from it and routes
  gene→vector resolution to the owning shard.

* :class:`ShardGroup` — scatter-gather ``/v1/similar``: fan each query
  to every shard with a PER-SHARD deadline through per-shard
  :class:`~gene2vec_tpu.serve.client.ResilientClient` instances (per-
  REPLICA circuit breakers; ONE shared retry token bucket across the
  whole fan-out, so a dead shard cannot amplify attempts fleet-wide),
  then merge the shard-local top-k candidate sets with
  ``parallel/sharding.py:merge_shard_topk`` — the ``two_stage_topk``
  merge lifted from cross-device to cross-process.  With
  ``--replicas-per-shard R`` each shard is a replica GROUP: the leg's
  client round-robins the live siblings and fails over between them
  within the leg's deadline, so a single replica death produces zero
  degraded answers (docs/SERVING.md#replicated-shards).  Cross-shard
  ``/v1/interaction`` resolves each gene's vector from its owner
  group and scores at the front door
  (``serve/interaction.py:CrossShardScorer``).

  **Robustness is the contract.**  A shard that is dead or misses its
  deadline yields a *partial* answer: the response carries
  ``degraded: true`` plus ``shards.answered/shards.total`` (and the
  answered shard indexes) — never a 5xx, never a silently complete
  answer — counted as ``fleet_degraded_responses_total``.  Recall
  degrades by roughly the dead shard's row fraction and recovers when
  the supervisor restarts it.  Responses are merged ONLY from shards
  reporting the same epoch: a query observing mixed epochs is
  re-scattered once (``fleet_mixed_epoch_rescatter_total``) and, if
  still mixed, merged from the newest epoch's shards only with the
  laggards counted as unanswered.  ``fleet_mixed_epoch_merges_total``
  is structurally zero — the chaos drill's swap-under-load phase
  verifies the observable corollary (zero mixed-iteration answers).

* :class:`SwapCoordinator` — shard-atomic hot swap.  Replicas in shard
  mode never self-swap (``cli.serve`` disables the registry watcher);
  instead the coordinator polls the export dir, and for a new verified
  iteration STAGES it on every live (shard, replica) CELL
  (``POST /v1/shard/stage`` — the load path is manifest-CRC-verified),
  then FLIPS all cells under a single epoch token
  (``POST /v1/shard/flip``; the token is the iteration number).  No
  cell flips unless every cell staged; a cell that restarts mid-swap
  is repaired (re-staged + flipped) on the next tick.  A swap is
  deferred while any whole replica GROUP is down — a half-fleet flip
  could never be atomic — but a dead replica with a live sibling does
  not defer (the sibling flips with the fleet; the dead cell repairs
  on return).

Everything here runs in the fleet front-door process (``cli.fleet``)
and is stdlib+numpy only; the heavy tables live in the shard replicas.

Row sharding and the multi-model catalog (``serve/catalog.py``) are
DIFFERENT fleet partitions and deliberately exclusive: shards split one
model's table by row range, a catalog splits replicas by model — both
CLIs reject the combination rather than route a (model, shard) grid
nothing merges yet.  The autoscaler already speaks both axes
(``serve/autoscale.py`` keys pools by ``(model, shard)``), so lifting
the restriction is a routing problem, not a scaling one.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from gene2vec_tpu.obs import tracecontext
from gene2vec_tpu.obs.trace import ambient_span
from gene2vec_tpu.serve.batcher import LRUCache
from gene2vec_tpu.parallel.sharding import (
    merge_shard_topk,
    shard_ranges,
)
from gene2vec_tpu.serve.client import (
    InFlightTracker,
    ResilientClient,
    RetryPolicy,
    TokenBucket,
)


#: ambient per-thread extras attached to every scatter leg issued
#: while set — the trace-context pattern, applied to headers.  The
#: batch plane's ShardGroupBackend tags its legs ``X-Tenant: batch``
#: this way, so each replica's FairQueue drains background
#: sub-requests at the batch weight without a header argument
#: threaded through every verb in the scatter call graph.
_SCATTER_HEADERS = threading.local()


class scatter_headers:
    """Context manager installing ambient headers for scatter legs
    issued on this thread (legs fork worker threads, but ``_scatter``
    captures the headers before forking)."""

    def __init__(self, headers: Optional[Dict[str, str]]):
        self._headers = headers

    def __enter__(self):
        self._prev = getattr(_SCATTER_HEADERS, "value", None)
        _SCATTER_HEADERS.value = self._headers
        return self

    def __exit__(self, *exc):
        _SCATTER_HEADERS.value = self._prev
        return False


@dataclasses.dataclass(frozen=True)
class ShardGroupConfig:
    """Scatter policy knobs (cli/fleet.py flags)."""

    num_shards: int = 2
    #: per-shard scatter-leg deadline: a slow shard costs at most this
    #: much of the request before the merge proceeds without it
    shard_deadline_s: float = 2.0
    #: default whole-request budget when the body carries no timeout_ms
    default_timeout_s: float = 5.0
    max_k: int = 256
    max_queries_per_request: int = 64
    #: bounded gene→unit-vector cache (keyed by epoch): a hot query
    #: gene resolves once per epoch, and a gene whose OWNER shard died
    #: still answers from cache instead of failing
    qvec_cache_size: int = 4096
    #: re-scatter once when a gather observes mixed epochs
    rescatter_on_mixed_epoch: bool = True


@dataclasses.dataclass(frozen=True)
class _RoutingSnapshot:
    """One immutable routing state — swapped by a single reference
    assignment like the registry's LoadedModel, so a reader can never
    observe a new index paired with old ranges mid-reload."""

    dim: Optional[int]
    iteration: Optional[int]
    tokens: Tuple[str, ...]
    index: Dict[str, int]
    ranges: List[Tuple[int, int]]


_EMPTY_ROUTING = _RoutingSnapshot(None, None, (), {}, [])


class RoutingTable:
    """gene → global row → owning shard, derived from the export
    manifest: the newest verified checkpoint's vocab order is the
    global row order (``serve/registry.py`` slices the same order), so
    the front door can route without ever loading the table itself."""

    def __init__(self, export_dir: str, num_shards: int,
                 dim: Optional[int] = None):
        self.export_dir = export_dir
        self.num_shards = int(num_shards)
        self.dim_filter = dim
        self._snap: _RoutingSnapshot = _EMPTY_ROUTING

    def reload(self) -> bool:
        """Re-derive the table from the newest verified checkpoint.
        Returns whether anything loadable was found; reload failures
        keep the previous table (the front door must not lose routing
        because one poll raced an export)."""
        from gene2vec_tpu.serve.registry import discover_newest

        newest = discover_newest(self.export_dir, self.dim_filter)
        if newest is None:
            return False
        dim, iteration, path = newest
        snap = self._snap
        if (dim, iteration) == (snap.dim, snap.iteration):
            return True
        try:
            tokens = self._read_tokens(path)
        except (OSError, ValueError):
            return False
        # one reference assignment IS the swap (the registry lesson)
        self._snap = _RoutingSnapshot(
            dim=dim,
            iteration=iteration,
            tokens=tuple(tokens),
            index={tok: i for i, tok in enumerate(tokens)},
            ranges=shard_ranges(len(tokens), self.num_shards),
        )
        return True

    # readers go through ONE snapshot reference; the properties keep
    # the attribute-style surface tests and cli.fleet use
    @property
    def dim(self) -> Optional[int]:
        return self._snap.dim

    @property
    def iteration(self) -> Optional[int]:
        return self._snap.iteration

    @property
    def tokens(self) -> Tuple[str, ...]:
        return self._snap.tokens

    @property
    def index(self) -> Dict[str, int]:
        return self._snap.index

    @property
    def ranges(self) -> List[Tuple[int, int]]:
        return self._snap.ranges

    @staticmethod
    def _read_tokens(ckpt_path: str) -> List[str]:
        if ckpt_path.endswith(".npz"):
            # sidecar-aware: a vocab-tail-extended iteration routes by
            # ITS vocab, not the dir's frozen vocab.tsv (the loop's
            # new-gene promotion case, io/checkpoint.py vocab_path_for)
            from gene2vec_tpu.io.checkpoint import vocab_path_for

            vocab_path = vocab_path_for(ckpt_path)
            tokens: List[str] = []
            with open(vocab_path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.rstrip("\n")
                    if line:
                        tokens.append(line.split("\t")[0])
            return tokens
        from gene2vec_tpu.io.emb_io import read_word2vec_format

        tokens, _ = read_word2vec_format(ckpt_path)
        return list(tokens)

    @property
    def total_rows(self) -> int:
        return len(self._snap.tokens)

    def owner(self, gene: str) -> Optional[int]:
        """Owning shard index, or None for an unknown gene.  Reads
        ONE snapshot: the row and the ranges it is checked against
        always belong to the same reload."""
        snap = self._snap
        row = snap.index.get(gene)
        if row is None:
            return None
        for i, (start, end) in enumerate(snap.ranges):
            if start <= row < end:
                return i
        return None  # pragma: no cover - ranges always cover the vocab

    def genes_doc(self, limit: int, offset: int) -> dict:
        snap = self._snap
        return {
            "total": len(snap.tokens),
            "genes": list(snap.tokens[offset:offset + limit]),
        }


class ApiReject(Exception):
    """Scatter-level request failure with an HTTP status (the shard
    group's analogue of server.ApiError, kept import-light)."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


class ShardGroup:
    """The front door's scatter-gather engine over N shard replica
    GROUPS.

    ``url_for(i)`` returns shard *i*'s current live base URL(s): a
    list (the replica group — ``cli.fleet`` wires the supervisor's UP
    slots of that shard in), a single URL, or None while the whole
    group is down.  Each shard's :class:`ResilientClient` round-robins
    the group and FAILS OVER between siblings within the leg's
    deadline (retry-safe failover + per-replica breakers), so a single
    replica death produces zero degraded answers — the shard counts as
    unanswered only when no sibling can answer in time.
    All per-shard clients share ONE retry token bucket and the proxy's
    :class:`InFlightTracker`, so the drain contract and the retry-
    amplification bound both hold across the fan-out."""

    def __init__(
        self,
        config: ShardGroupConfig,
        url_for: Callable[[int], Union[Optional[str], Sequence[str]]],
        metrics=None,
        policy: Optional[RetryPolicy] = None,
        inflight: Optional[InFlightTracker] = None,
        routing: Optional[RoutingTable] = None,
        transport: Optional[Callable] = None,
        ggipnn_checkpoint: Optional[str] = None,
    ):
        self.config = config
        self.url_for = url_for
        self.metrics = metrics
        self.routing = routing
        self.policy = policy if policy is not None else RetryPolicy(
            max_attempts=2,
            connect_timeout_s=1.0,
            default_timeout_s=config.shard_deadline_s,
        )
        #: ONE budget across the whole fan-out (the satellite
        #: contract): every shard's retries draw it down together
        self.budget = TokenBucket(
            self.policy.retry_budget_ratio,
            self.policy.retry_budget_burst,
        )
        self.inflight = inflight
        self._transport = transport
        self._clients: Dict[int, ResilientClient] = {}
        self._clients_lock = threading.Lock()
        #: last epoch each shard was SEEN serving (scatter answers +
        #: coordinator probes feed this; /healthz renders it)
        self._epochs: Dict[int, Optional[int]] = {}
        #: last epoch each replica CELL (by URL) was seen serving —
        #: scatter answers carry the answering target, the swap
        #: coordinator probes every cell; /healthz renders the grid.
        #: BOUNDED (LRU): every respawn binds a fresh ephemeral port,
        #: so a plain dict keyed by URL would leak one entry per
        #: restart for the front door's whole lifetime
        self._replica_epochs = LRUCache(256)
        #: the fleet's current logical version (the coordinator owns
        #: writes; None until the first tick adopts the boot state)
        self.current_epoch: Optional[int] = None
        # gene → raw query vector, keyed (epoch, gene) — the epoch in
        # the key is load-bearing: a cached iteration-1 vector scored
        # against iteration-2 shards would be a wrong answer the epoch
        # check cannot see.  Reuses the batcher's bounded LRU.
        self._qvecs = LRUCache(config.qvec_cache_size)
        #: models/ggipnn_obs head checkpoint backing cross-shard
        #: /v1/interaction (cli.fleet --ggipnn-checkpoint); without it
        #: the head keeps its deterministic random init and
        #: ``trained_head`` is echoed false, like a replica's scorer
        self.ggipnn_checkpoint = ggipnn_checkpoint
        self._interaction_scorer = None
        self._scorer_lock = threading.Lock()

    # -- plumbing ----------------------------------------------------------

    def urls_of(self, shard: int) -> List[str]:
        """Shard *i*'s live replica group, normalized to a list
        (``url_for`` may return a list, one URL, or None)."""
        u = self.url_for(shard)
        if u is None:
            return []
        if isinstance(u, str):
            return [u]
        return [x for x in u if x]

    def client(self, shard: int) -> ResilientClient:
        with self._clients_lock:
            c = self._clients.get(shard)
            if c is None:
                kwargs = {}
                if self._transport is not None:
                    kwargs["transport"] = self._transport
                c = ResilientClient(
                    lambda s=shard: self.urls_of(s),
                    policy=self.policy,
                    metrics=self.metrics,
                    inflight=self.inflight,
                    budget=self.budget,
                    **kwargs,
                )
                self._clients[shard] = c
            return c

    def _count(self, name: str, amount: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.counter(name).inc(amount)

    def note_epoch(self, shard: int, epoch,
                   url: Optional[str] = None) -> None:
        self._epochs[shard] = epoch
        if url is not None:
            self._replica_epochs.put(url.rstrip("/"), epoch)

    def replica_epoch(self, url: Optional[str]):
        """Last epoch one replica cell was seen serving (None before
        any scatter answer or coordinator probe touched it)."""
        if url is None:
            return None
        return self._replica_epochs.get(url.rstrip("/"))

    def shard_states(
        self,
        up_for: Optional[Callable[[int], bool]] = None,
        replicas_for: Optional[Callable[[int], List[dict]]] = None,
    ) -> List[dict]:
        """Per-shard facts for the front door's /healthz: row range,
        rotation state, last-seen epoch — plus the replica GROUP
        (``replicas: [{index, up, epoch}]``) when the caller can
        enumerate it (the proxy passes the supervisor's grid)."""
        ranges = self.routing.ranges if self.routing is not None else []
        out = []
        for i in range(self.config.num_shards):
            urls = self.urls_of(i)
            doc = {
                "index": i,
                "rows": list(ranges[i]) if i < len(ranges) else None,
                "up": bool(up_for(i)) if up_for is not None else (
                    bool(urls)
                ),
                "epoch": self._epochs.get(i),
                "url": urls[0] if urls else None,
            }
            if replicas_for is not None:
                doc["replicas"] = replicas_for(i)
            out.append(doc)
        return out

    # -- the scatter -------------------------------------------------------

    def _scatter(
        self,
        path: str,
        bodies: Dict[int, dict],
        deadline: float,
    ) -> Dict[int, dict]:
        """POST ``bodies[shard]`` to each listed shard concurrently
        under the per-shard deadline (capped by the request's overall
        remaining budget).  Returns shard → parsed 2xx doc; a shard
        that fails, 409s, or times out simply has no entry — the
        caller degrades."""
        # captured on the CALLER's thread before the legs fork, so the
        # ambient batch-tenant tag rides into every sub-request
        extra_headers = getattr(_SCATTER_HEADERS, "value", None)
        results: Dict[int, dict] = {}
        lock = threading.Lock()
        # the scatter runs on fresh threads: carry the caller's ambient
        # trace context over explicitly, so every shard leg's
        # client_attempt shows up as a SIBLING child span under the one
        # proxy_scatter span (cli.obs trace renders the fan-out)
        ctx = tracecontext.current()

        def leg(shard: int, body: dict) -> None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._count("fleet_shard_leg_deadline_total")
                return
            with tracecontext.use(ctx):
                r = self.client(shard).request(
                    path, body,
                    timeout_s=min(
                        self.config.shard_deadline_s, remaining
                    ),
                    headers=extra_headers,
                )
            if r.error_class == "deadline":
                self._count("fleet_shard_leg_deadline_total")
            if r.ok:
                doc = r.doc
                if isinstance(doc, dict):
                    epoch = (doc.get("shard") or {}).get("epoch")
                    self.note_epoch(shard, epoch, url=r.target)
                    with lock:
                        results[shard] = doc

        threads = [
            threading.Thread(
                target=leg, args=(shard, body), daemon=True,
                name=f"scatter-shard-{shard}",
            )
            for shard, body in bodies.items()
        ]
        for t in threads:
            t.start()
        join_deadline = deadline + 1.0
        for t in threads:
            t.join(timeout=max(0.0, join_deadline - time.monotonic()))
        return results

    def _drop_malformed(self, answers: Dict[int, dict],
                        n_queries: int) -> Dict[int, dict]:
        """Filter 2xx legs whose result shape cannot be merged (wrong
        result count, scores/rows length mismatch — a version-skewed
        or buggy shard).  Dropping them HERE, before the degraded flag
        and ``shards.answered`` are computed, keeps the contract
        honest: a lost leg is a *visible* partial answer, never a
        silently complete one."""
        out: Dict[int, dict] = {}
        for s, doc in answers.items():
            res = doc.get("results")
            ok = isinstance(res, list) and len(res) == n_queries
            if ok:
                lens = set()
                for r in res:
                    rows = r.get("rows")
                    scores = r.get("scores")
                    if not (
                        isinstance(rows, list)
                        and isinstance(scores, list)
                        and len(rows) == len(scores)
                    ):
                        ok = False
                        break
                    lens.add(len(rows))
                # ragged per-query candidate counts cannot stack into
                # the (Q, lk) matrices the merge concatenates
                ok = ok and len(lens) <= 1
            if ok:
                out[s] = doc
            else:
                self._count("fleet_shard_malformed_total")
        return out

    # -- gene → vector resolution ------------------------------------------

    def _resolve_vectors(
        self, genes: Sequence[str], deadline: float,
        epoch_hint,
    ) -> Tuple[List[Optional[List[float]]], List, bool]:
        """Query vectors for gene queries: qvec cache (keyed by
        ``epoch_hint``) first, then one ``/v1/shard/vectors`` round to
        each owning shard.  Returns (vectors, per-query resolution
        epochs, any-unresolved): the caller fences the SCATTER against the
        resolution epochs — a swap landing between resolution and
        scatter must not score an old iteration's query vector against
        new tables.  A gene whose owner is unreachable resolves to None
        (the caller emits an empty, degraded result for it); an unknown
        gene raises 400 — exactly the single-replica error shape."""
        routing = self.routing
        assert routing is not None
        out: List[Optional[List[float]]] = [None] * len(genes)
        epochs: List[Optional[int]] = [None] * len(genes)
        by_owner: Dict[int, List[int]] = {}
        for qi, gene in enumerate(genes):
            owner = routing.owner(gene)
            if owner is None:
                raise ApiReject(
                    400,
                    f"unknown gene(s) [{gene!r}] (model iteration "
                    f"{routing.iteration})",
                )
            cached = self._qvecs.get((epoch_hint, gene))
            if cached is not None:
                out[qi] = cached
                epochs[qi] = epoch_hint
                self._count("fleet_qvec_cache_hits_total")
            else:
                by_owner.setdefault(owner, []).append(qi)
        degraded = False
        if by_owner:
            bodies = {
                owner: {"genes": [genes[qi] for qi in qis]}
                for owner, qis in by_owner.items()
            }
            answers = self._scatter("/v1/shard/vectors", bodies, deadline)
            for owner, qis in by_owner.items():
                doc = answers.get(owner)
                vectors = (doc or {}).get("vectors")
                if not isinstance(vectors, list) or (
                    len(vectors) != len(qis)
                ):
                    # owner dead/slow and no cache: these queries stay
                    # unresolved — degraded, never a 5xx
                    degraded = True
                    self._count(
                        "fleet_qvec_unresolved_total", len(qis)
                    )
                    continue
                resolved_epoch = (doc.get("shard") or {}).get("epoch")
                for qi, vec in zip(qis, vectors):
                    out[qi] = vec
                    epochs[qi] = resolved_epoch
                    self._qvecs.put((resolved_epoch, genes[qi]), vec)
        return out, epochs, degraded

    # -- the public entry points -------------------------------------------

    def similar(self, body: dict) -> Tuple[int, dict]:
        """Scatter-gather ``/v1/similar``: same request/response schema
        as a single replica, plus the degradation facts (``degraded``,
        ``shards``).  Returns ``(status, doc)``; client errors are 400,
        an all-shards-dead scatter is the one non-partial case and
        returns 503."""
        try:
            return self._similar(body)
        except ApiReject as e:
            self._count(f"fleet_http_{e.status}_total")
            return e.status, {"error": str(e)}

    def _validate(self, body: dict):
        k = body.get("k", 10)
        if not isinstance(k, int) or k < 1 or k > self.config.max_k:
            raise ApiReject(
                400, f"k must be an int in [1, {self.config.max_k}]"
            )
        genes = body.get("genes")
        vectors = body.get("vectors")
        if (genes is None) == (vectors is None):
            raise ApiReject(
                400, "provide exactly one of 'genes' or 'vectors'"
            )
        queries = genes if genes is not None else vectors
        if not isinstance(queries, list) or not queries:
            raise ApiReject(
                400,
                "'genes' must be a non-empty list" if genes is not None
                else "'vectors' must be a non-empty list",
            )
        if len(queries) > self.config.max_queries_per_request:
            raise ApiReject(
                400,
                f"at most {self.config.max_queries_per_request} "
                "queries per request",
            )
        timeout = body.get("timeout_ms")
        if timeout is not None and (
            not isinstance(timeout, (int, float)) or timeout <= 0
        ):
            raise ApiReject(400, "timeout_ms must be a positive number")
        dim = self.routing.dim if self.routing is not None else None
        if genes is None and dim is not None:
            for v in vectors:
                if not isinstance(v, list) or len(v) != dim:
                    raise ApiReject(
                        400, f"each vector must have dim {dim}"
                    )
        return genes, vectors, k, (
            float(timeout) / 1000.0 if timeout is not None
            else self.config.default_timeout_s
        )

    def _similar(self, body: dict) -> Tuple[int, dict]:
        genes, vectors, k, timeout_s = self._validate(body)
        deadline = time.monotonic() + timeout_s
        n_shards = self.config.num_shards
        self._count("fleet_scatter_requests_total")

        # TWO epoch fences guard a swap racing this request: (1) the
        # gather merges only shards reporting one epoch (mixed gather →
        # one re-scatter pinned to the newest); (2) gene queries whose
        # VECTOR was resolved under a different epoch than the gather's
        # are retried once against the new epoch and, if still racing,
        # dropped to unresolved — an old iteration's query vector must
        # never be scored against new tables and labeled as new.
        degraded = False
        unresolved = False
        answers: Dict[int, dict] = {}
        qvecs: List[Optional[List[float]]] = []
        res_epochs: List[Optional[int]] = []
        merged_epoch = None
        epoch_hint = self.current_epoch
        for fence_try in (0, 1):
            if genes is not None:
                qvecs, res_epochs, unresolved = self._resolve_vectors(
                    genes, deadline, epoch_hint
                )
                # gene queries ask one extra so dropping the self-hit
                # still leaves k neighbors (the single-replica contract)
                k_fetch = k + 1
            else:
                qvecs = [list(map(float, v)) for v in vectors]
                res_epochs = [None] * len(qvecs)
                k_fetch = k
            live_idx = [
                qi for qi, v in enumerate(qvecs) if v is not None
            ]
            answers = {}
            if live_idx:
                scatter_body = {
                    "vectors": [qvecs[qi] for qi in live_idx],
                    "k": k_fetch,
                }
                # the scatter gets its OWN child trace context: the
                # proxy_scatter span becomes a distinct node in the
                # cross-process tree, and every shard leg's
                # client_attempt parents to it as a sibling — cli.obs
                # trace renders the fan-out instead of flattening it
                # into the request span
                cur_ctx = tracecontext.current()
                scatter_ctx = (
                    cur_ctx.child() if cur_ctx is not None else None
                )
                with tracecontext.use(
                    scatter_ctx if scatter_ctx is not None else cur_ctx
                ), ambient_span(
                    "proxy_scatter", shards=n_shards,
                    queries=len(live_idx), k=k,
                ) as span:
                    bodies = {
                        i: scatter_body for i in range(n_shards)
                    }
                    answers = self._drop_malformed(
                        self._scatter(
                            "/v1/shard/topk", bodies, deadline
                        ),
                        len(live_idx),
                    )
                    epochs = {
                        (d.get("shard") or {}).get("epoch")
                        for d in answers.values()
                    }
                    if len(epochs) > 1:
                        # mixed epochs observed: a swap is in flight.
                        # Re-scatter ONCE pinned to the MAJORITY epoch
                        # (ties toward the newer one) and merge only
                        # matching answers — majority, not max: one
                        # restarted shard that self-loaded a brand-new
                        # export must degrade the fleet by 1/N, not
                        # collapse every answer to its lone shard for
                        # the whole staging window.
                        self._count("fleet_mixed_epoch_scatters_total")
                        votes: Dict = {}
                        for d in answers.values():
                            e = (d.get("shard") or {}).get("epoch")
                            if e is not None:
                                votes[e] = votes.get(e, 0) + 1
                        target = max(
                            votes.items(), key=lambda kv: (kv[1], kv[0])
                        )[0] if votes else None
                        if self.config.rescatter_on_mixed_epoch:
                            self._count(
                                "fleet_mixed_epoch_rescatter_total"
                            )
                            pinned = dict(scatter_body, epoch=target)
                            answers = self._drop_malformed(
                                self._scatter(
                                    "/v1/shard/topk",
                                    {i: pinned
                                     for i in range(n_shards)},
                                    deadline,
                                ),
                                len(live_idx),
                            )
                        answers = {
                            s: d for s, d in answers.items()
                            if (d.get("shard") or {}).get("epoch")
                            == target
                        }
                    span["shards_answered"] = len(answers)
            merged_epoch = next(
                ((d.get("shard") or {}).get("epoch")
                 for d in answers.values()), None,
            )
            if (
                genes is not None and answers and fence_try == 0
                and any(
                    e is not None and e != merged_epoch
                    for e in res_epochs
                )
            ):
                # the resolution/scatter epoch race: retry once with
                # the gather's epoch as the cache hint — the owners
                # have flipped by now and re-resolve consistently
                self._count("fleet_epoch_race_retries_total")
                epoch_hint = merged_epoch
                continue
            break
        stale_qis = set()
        if genes is not None:
            for qi, e in enumerate(res_epochs):
                if (
                    qvecs[qi] is not None and e is not None
                    and e != merged_epoch
                ):
                    # still racing after the retry (a second swap mid-
                    # request): refuse to emit a stale-vector answer —
                    # this query degrades to unresolved instead (the
                    # scatter-time live_idx stays untouched so the
                    # merge's column mapping cannot desync)
                    stale_qis.add(qi)
                    unresolved = True
                    self._count("fleet_qvec_unresolved_total")
        degraded |= unresolved

        if not answers and live_idx:
            # nothing answered at all: not partial, not recoverable —
            # the one case the scatter surfaces as unavailability
            self._count("fleet_scatter_unanswered_total")
            return 503, {
                "error": "no shard answered the scatter",
                "shards": {"total": n_shards, "answered": 0},
            }

        answered = sorted(answers)
        if len(answered) < n_shards:
            degraded = True
            self._count(
                "fleet_shard_unanswered_total",
                n_shards - len(answered),
            )
        epoch = next(
            ((answers[s].get("shard") or {}).get("epoch")
             for s in answered), self.current_epoch,
        )
        # an all-unresolved (empty) answer still declares the logical
        # version the fleet serves: epoch == iteration by convention,
        # so current_epoch is the honest fallback
        iteration = next(
            ((answers[s].get("shard") or {}).get("iteration")
             for s in answered), self.current_epoch,
        )

        results = self._merge(
            answers, answered, genes, qvecs, live_idx, k,
        )
        for qi in stale_qis:
            results[qi] = {
                "query": genes[qi], "neighbors": [], "degraded": True,
            }
        if degraded:
            self._count("fleet_degraded_responses_total")
        doc = {
            "model": {
                "dim": (
                    self.routing.dim if self.routing is not None
                    else None
                ),
                "iteration": iteration,
            },
            "results": results,
            "degraded": degraded,
            "shards": {
                "total": n_shards,
                "answered": len(answered),
                "indexes": answered,
                "epoch": epoch,
            },
        }
        return 200, doc

    def _merge(
        self,
        answers: Dict[int, dict],
        answered: List[int],
        genes: Optional[Sequence[str]],
        qvecs: List[Optional[List[float]]],
        live_idx: List[int],
        k: int,
    ) -> List[dict]:
        """Cross-process merge of the shard-local top-k sets, per
        query, preserving lax.top_k selection semantics (see
        ``merge_shard_topk``); token lookup rides the candidates each
        shard already returned."""
        n_queries = len(qvecs)
        # per answered shard: (Q_live, lk) score/row matrices + a
        # row→token map from the candidates themselves
        parts: List[Tuple[np.ndarray, np.ndarray]] = []
        tokens_by_row: Dict[int, str] = {}
        for s in answered:
            res = answers[s].get("results")
            if not isinstance(res, list) or len(res) != len(live_idx):
                continue  # malformed leg: treat as unanswered
            scores = np.asarray(
                [r.get("scores", []) for r in res], dtype=np.float32
            )
            rows = np.asarray(
                [r.get("rows", []) for r in res], dtype=np.int64
            )
            if scores.ndim != 2 or scores.shape != rows.shape:
                continue
            for r in res:
                for row, tok in zip(r.get("rows", []),
                                    r.get("tokens", [])):
                    tokens_by_row[int(row)] = tok
            parts.append((scores, rows))
        out: List[dict] = []
        merged: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        if parts and live_idx:
            m_scores, m_rows = merge_shard_topk(
                parts, k + 1 if genes is not None else k
            )
            merged = {
                qi: (m_scores[j], m_rows[j])
                for j, qi in enumerate(live_idx)
            }
        for qi in range(n_queries):
            gene = genes[qi] if genes is not None else None
            if qi not in merged:
                out.append({
                    "query": gene,
                    "neighbors": [],
                    "degraded": True,
                })
                continue
            scores, rows = merged[qi]
            neighbors = []
            for s, r in zip(scores, rows):
                tok = tokens_by_row.get(int(r), str(int(r)))
                if gene is not None and tok == gene:
                    continue  # drop the self-hit, like the replica does
                neighbors.append(
                    {"gene": tok, "score": round(float(s), 6)}
                )
                if len(neighbors) >= k:
                    break
            out.append({"query": gene, "neighbors": neighbors})
        return out

    def embedding(self, body: dict) -> Tuple[int, dict]:
        """Point lookups routed to the owning shards.  No partial
        semantics: a gene whose owner cannot answer fails the request
        (503) — callers asking for raw vectors need all of them."""
        genes = body.get("genes")
        if not isinstance(genes, list) or not genes:
            return 400, {"error": "'genes' must be a non-empty list"}
        if len(genes) > self.config.max_queries_per_request:
            return 400, {
                "error": (
                    f"at most {self.config.max_queries_per_request} "
                    "genes per request"
                ),
            }
        routing = self.routing
        assert routing is not None
        by_owner: Dict[int, List[str]] = {}
        for g in genes:
            owner = routing.owner(g)
            if owner is None:
                return 400, {
                    "error": (
                        f"unknown gene {g!r} (model iteration "
                        f"{routing.iteration})"
                    ),
                }
            by_owner.setdefault(owner, []).append(g)
        deadline = time.monotonic() + self.config.default_timeout_s
        answers = self._scatter(
            "/v1/shard/vectors",
            {o: {"genes": gs} for o, gs in by_owner.items()},
            deadline,
        )
        vecs: Dict[str, List[float]] = {}
        for owner, gs in by_owner.items():
            doc = answers.get(owner)
            vectors = (doc or {}).get("vectors")
            if not isinstance(vectors, list) or len(vectors) != len(gs):
                return 503, {
                    "error": (
                        f"shard {owner} (owning {len(gs)} requested "
                        "gene(s)) did not answer"
                    ),
                }
            vecs.update(zip(gs, vectors))
        return 200, {
            "model": {
                "dim": routing.dim,
                "iteration": routing.iteration,
            },
            "embeddings": [
                {"gene": g, "vector": vecs[g]} for g in genes
            ],
        }

    # -- cross-shard /v1/interaction ---------------------------------------

    def _scorer(self):
        """The front-door GGIPNN pair scorer, built lazily on first use
        (it imports jax; the fleet process stays light until the route
        is actually exercised).  Vectors come from the shards, so the
        scorer needs only the dim and the head checkpoint."""
        with self._scorer_lock:
            if self._interaction_scorer is None:
                from gene2vec_tpu.serve.interaction import (
                    CrossShardScorer,
                )

                dim = self.routing.dim if self.routing is not None else None
                if dim is None:
                    raise ApiReject(
                        503, "no routing table loaded; cannot score"
                    )
                self._interaction_scorer = CrossShardScorer(
                    dim,
                    checkpoint_path=self.ggipnn_checkpoint,
                    max_pairs=self.config.max_queries_per_request,
                )
            return self._interaction_scorer

    def interaction(self, body: dict) -> Tuple[int, dict]:
        """Cross-shard GGIPNN pair scoring — the paper's extrinsic
        workload on a sharded fleet.  Each gene's raw vector is
        resolved from its OWNER shard's replica group
        (``/v1/shard/vectors``, qvec-cached per epoch) and the MLP head
        runs at the front door, so a pair spanning shards scores
        exactly like on a single replica.  Degraded-contract honesty:
        a pair whose owner group is fully down gets ``score: null`` +
        ``degraded: true`` in a 200 — never a 5xx, never a silently
        missing pair."""
        try:
            return self._interaction(body)
        except ApiReject as e:
            self._count(f"fleet_http_{e.status}_total")
            return e.status, {"error": str(e)}
        except Exception as e:
            # a scorer that cannot build (e.g. a head checkpoint
            # trained at a different dim) or a scoring crash must
            # ANSWER — the proxy's handler pool swallows exceptions,
            # so raising here would hang the client until its timeout
            # with no counter and no trace status
            self._count("fleet_interaction_errors_total")
            return 500, {
                "error": f"interaction scoring failed: {e!r}",
            }

    def _interaction(self, body: dict) -> Tuple[int, dict]:
        pairs = body.get("pairs")
        if not isinstance(pairs, list) or not pairs or not all(
            isinstance(p, list) and len(p) == 2
            and all(isinstance(g, str) for g in p)
            for p in pairs
        ):
            # string-ness is part of the 400 contract: a non-string
            # element would TypeError in the dedup set below and turn
            # a client mistake into a 500 server-error signal
            raise ApiReject(
                400,
                "'pairs' must be a non-empty list of [gene, gene] "
                "name pairs",
            )
        if len(pairs) > self.config.max_queries_per_request:
            raise ApiReject(
                400,
                f"at most {self.config.max_queries_per_request} pairs "
                "per request",
            )
        timeout = body.get("timeout_ms")
        if timeout is not None and (
            not isinstance(timeout, (int, float)) or timeout <= 0
        ):
            raise ApiReject(400, "timeout_ms must be a positive number")
        timeout_s = (
            float(timeout) / 1000.0 if timeout is not None
            else self.config.default_timeout_s
        )
        scorer = self._scorer()
        deadline = time.monotonic() + timeout_s
        # one resolution per distinct gene; unknown genes 400 exactly
        # like the single-replica scorer's KeyError path
        genes = []
        seen = set()
        for a, b in pairs:
            for g in (a, b):
                if g not in seen:
                    seen.add(g)
                    genes.append(g)
        epoch_hint = self.current_epoch
        for fence_try in (0, 1):
            vecs, epochs, unresolved = self._resolve_vectors(
                genes, deadline, epoch_hint
            )
            resolved_epochs = {e for e in epochs if e is not None}
            if len(resolved_epochs) > 1 and fence_try == 0:
                # a swap landed mid-resolution: retry once pinned to
                # the newest epoch — scoring a pair from two different
                # iterations' tables would be a wrong answer
                self._count("fleet_epoch_race_retries_total")
                epoch_hint = max(resolved_epochs)
                continue
            break
        merged_epoch = (
            max(resolved_epochs) if resolved_epochs else self.current_epoch
        )
        by_gene = {}
        for g, v, e in zip(genes, vecs, epochs):
            # still racing after the retry: the minority-epoch vector
            # degrades to unresolved rather than crossing iterations
            if v is not None and e is not None and e != merged_epoch:
                self._count("fleet_qvec_unresolved_total")
                unresolved = True
                v = None
            by_gene[g] = v
        scorable = [
            (i, p) for i, p in enumerate(pairs)
            if by_gene[p[0]] is not None and by_gene[p[1]] is not None
        ]
        if all(v is None for v in by_gene.values()):
            # no owner group answered anything: the one non-partial case
            self._count("fleet_scatter_unanswered_total")
            return 503, {
                "error": "no owner shard answered the vector scatter",
                "shards": {"total": self.config.num_shards,
                           "answered": 0},
            }
        scores = scorer.score_vectors(
            [
                (np.asarray(by_gene[a], np.float32),
                 np.asarray(by_gene[b], np.float32))
                for _, (a, b) in scorable
            ]
        )
        out: List[dict] = [
            {"pair": list(p), "score": None, "degraded": True}
            for p in pairs
        ]
        for (i, p), s in zip(scorable, scores):
            out[i] = {"pair": list(p), "score": round(float(s), 6)}
        degraded = bool(unresolved)
        if degraded:
            self._count("fleet_degraded_responses_total")
        self._count("fleet_interaction_pairs_total", len(pairs))
        return 200, {
            "model": {
                "dim": (
                    self.routing.dim if self.routing is not None
                    else None
                ),
                "iteration": merged_epoch,
            },
            "trained_head": scorer.trained,
            "scores": out,
            "degraded": degraded,
            "shards": {"total": self.config.num_shards},
        }


class SwapCoordinator:
    """Drives the shard-atomic hot swap from the front-door process.

    Polls the export dir (manifest-verified discovery, the registry's
    own rules); on a new iteration: STAGE on every shard → only if all
    staged, FLIP all under one epoch token.  Also repairs shards that
    restarted into a different epoch.  All HTTP here is plain urllib
    with generous timeouts — staging loads a table."""

    def __init__(
        self,
        export_dir: str,
        group: ShardGroup,
        dim: Optional[int] = None,
        interval_s: float = 2.0,
        stage_timeout_s: float = 180.0,
        metrics=None,
    ):
        self.export_dir = export_dir
        self.group = group
        self.dim = dim
        self.interval_s = interval_s
        self.stage_timeout_s = stage_timeout_s
        self.metrics = metrics
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- plumbing ----------------------------------------------------------

    def _count(self, name: str, amount: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.counter(name).inc(amount)

    def _post(self, url: str, path: str, body: dict,
              timeout_s: float) -> Optional[dict]:
        data = json.dumps(body).encode("utf-8")
        req = urllib.request.Request(
            url + path, data=data,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except Exception:
            return None

    def _probe_epoch(self, url: str) -> Optional[int]:
        try:
            with urllib.request.urlopen(
                url + "/healthz", timeout=5.0
            ) as resp:
                doc = json.loads(resp.read().decode("utf-8"))
            return (doc.get("shard") or {}).get("epoch")
        except Exception:
            return None

    # -- the protocol ------------------------------------------------------

    def tick(self) -> None:
        from gene2vec_tpu.serve.registry import discover_newest

        newest = discover_newest(self.export_dir, self.dim)
        if newest is None:
            return
        dim, iteration, _path = newest
        group = self.group
        if group.routing is not None and group.routing.dim is None:
            group.routing.reload()
        if group.current_epoch is None:
            # boot: every shard loaded the then-newest iteration on its
            # own; adopt it as the fleet epoch (the repair pass below
            # converges any shard that raced a concurrent export)
            group.current_epoch = iteration
        if iteration != group.current_epoch:
            self._swap(dim, iteration)
        else:
            self._repair(dim, iteration)

    def _cells(self) -> List[Tuple[int, str]]:
        """Every live (shard, replica-URL) cell of the grid — the swap
        protocol's unit.  With ``--replicas-per-shard 1`` this is the
        PR-13 one-URL-per-shard list, unchanged."""
        out: List[Tuple[int, str]] = []
        for i in range(self.group.config.num_shards):
            for url in self.group.urls_of(i):
                out.append((i, url))
        return out

    def _swap(self, dim: int, iteration: int) -> None:
        """STAGE every (shard, replica) cell, then FLIP all under one
        token.  Deferred while any shard GROUP is fully down: flipping
        half a fleet can never be atomic, and the supervisor's restart
        is coming.  A single dead replica with a live sibling does NOT
        defer — the sibling flips with the fleet, and the dead cell is
        repaired (re-staged + flipped) when it returns."""
        cells = self._cells()
        covered = {i for i, _ in cells}
        if any(
            i not in covered
            for i in range(self.group.config.num_shards)
        ):
            self._count("fleet_swap_deferred_total")
            return
        threads = []
        results: Dict[Tuple[int, str], Optional[dict]] = {}

        def stage(i: int, url: str) -> None:
            results[(i, url)] = self._post(
                url, "/v1/shard/stage",
                {"dim": dim, "iteration": iteration},
                self.stage_timeout_s,
            )

        for i, url in cells:
            t = threading.Thread(
                target=stage, args=(i, url), daemon=True,
                name=f"swap-stage-{i}",
            )
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=self.stage_timeout_s + 10.0)
        staged = [
            isinstance(results.get(cell), dict)
            and "staged" in results[cell]
            for cell in cells
        ]
        if not all(staged):
            # NO cell flips: the fleet keeps serving the old epoch as
            # one logical version; retry next tick
            self._count("fleet_swap_stage_failures_total")
            return
        flips_ok = True
        for i, url in cells:
            doc = self._post(
                url, "/v1/shard/flip", {"epoch": iteration}, 30.0
            )
            if doc is None:
                flips_ok = False
            else:
                self.group.note_epoch(
                    i, (doc.get("shard") or {}).get("epoch"), url=url
                )
        # the fleet's logical version moves forward once the flip wave
        # has been ISSUED: stragglers (a cell that died mid-flip) are
        # epoch-fenced out of merges and repaired next tick
        self.group.current_epoch = iteration
        if self.group.routing is not None:
            self.group.routing.reload()
        self._count("fleet_swap_flips_total")
        if not flips_ok:
            self._count("fleet_swap_flip_failures_total")

    def _repair(self, dim: int, iteration: int) -> None:
        """Converge cells serving a different epoch than the fleet's
        (typically a replica the supervisor restarted mid-history):
        stage + flip just those."""
        for i, url in self._cells():
            epoch = self._probe_epoch(url)
            self.group.note_epoch(i, epoch, url=url)
            if epoch == iteration or epoch is None:
                continue
            doc = self._post(
                url, "/v1/shard/stage",
                {"dim": dim, "iteration": iteration},
                self.stage_timeout_s,
            )
            if isinstance(doc, dict) and "staged" in doc:
                flipped = self._post(
                    url, "/v1/shard/flip", {"epoch": iteration}, 30.0
                )
                if flipped is not None:
                    self.group.note_epoch(
                        i, (flipped.get("shard") or {}).get("epoch"),
                        url=url,
                    )
                    self._count("fleet_swap_repairs_total")

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "SwapCoordinator":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:
                    # coordination must outlive surprises; the fleet
                    # keeps serving its current epoch either way
                    self._count("fleet_swap_tick_errors_total")

        self._thread = threading.Thread(
            target=loop, name="shard-swap-coordinator", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=10.0)
        self._thread = None
