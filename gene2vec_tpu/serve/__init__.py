"""Online serving: batched embedding queries over training checkpoints.

Layering (docs/SERVING.md):

* :mod:`~gene2vec_tpu.serve.registry` — checkpoint discovery + atomic
  hot swap of the device-resident L2-normalized table;
* :mod:`~gene2vec_tpu.serve.engine` — the jitted bucketed top-k engine
  (exact | quant | ivf index modes);
* :mod:`~gene2vec_tpu.serve.ann` — approximate retrieval: int8
  per-row-quantized scoring tables and the IVF two-stage index, both
  with an exact-rescore tail;
* :mod:`~gene2vec_tpu.serve.batcher` — micro-batching with max-delay /
  max-batch admission, bounded-queue backpressure, deadlines, LRU;
* :mod:`~gene2vec_tpu.serve.interaction` — GGIPNN pair scoring;
* :mod:`~gene2vec_tpu.serve.eventloop` — the non-blocking HTTP/1.1
  front end (selectors event loop, keep-alive, zero-copy writes,
  optional SO_REUSEPORT multi-acceptor);
* :mod:`~gene2vec_tpu.serve.server` — the JSON route layer + the
  event-loop adapter (response-bytes cache, coalesced GETs);
* :mod:`~gene2vec_tpu.serve.client` — the resilient caller (retries
  with deadline propagation + budgets, hedging, circuit breakers);
* :mod:`~gene2vec_tpu.serve.fleet` — replica supervision and the
  front-door round-robin proxy;
* :mod:`~gene2vec_tpu.serve.tenancy` — multi-tenant admission:
  per-tenant token-bucket quotas (``X-Tenant``) and the weighted-fair
  queue the batcher drains;
* :mod:`~gene2vec_tpu.serve.autoscale` — the SLO-driven elastic
  scaler: hysteresis policy over the fleet aggregator's snapshot,
  zero-drop scale-down drains.

``python -m gene2vec_tpu.cli.serve`` runs one replica,
``python -m gene2vec_tpu.cli.fleet`` a supervised fleet;
``scripts/serve_loadgen.py`` measures either.
"""

from gene2vec_tpu.serve.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    RejectedError,
)
from gene2vec_tpu.serve.client import (
    CircuitBreaker,
    ClientResponse,
    ResilientClient,
    RetryPolicy,
)
from gene2vec_tpu.serve.ann import AnnIndex, build_index
from gene2vec_tpu.serve.autoscale import (
    AutoscaleConfig,
    AutoscalePolicy,
    ElasticController,
)
from gene2vec_tpu.serve.engine import BucketedTopKEngine, SimilarityEngine
from gene2vec_tpu.serve.eventloop import (
    EventLoopConfig,
    EventLoopHTTPServer,
)
from gene2vec_tpu.serve.fleet import FleetConfig, FleetProxy, FleetSupervisor
from gene2vec_tpu.serve.registry import LoadedModel, ModelRegistry
from gene2vec_tpu.serve.server import ServeApp, ServeConfig, make_server
from gene2vec_tpu.serve.tenancy import (
    FairQueue,
    RateBucket,
    TenantAdmission,
    TenantPolicy,
)

__all__ = [
    "AnnIndex",
    "AutoscaleConfig",
    "AutoscalePolicy",
    "BucketedTopKEngine",
    "build_index",
    "CircuitBreaker",
    "ClientResponse",
    "DeadlineExceeded",
    "ElasticController",
    "EventLoopConfig",
    "EventLoopHTTPServer",
    "FairQueue",
    "FleetConfig",
    "FleetProxy",
    "FleetSupervisor",
    "LoadedModel",
    "MicroBatcher",
    "ModelRegistry",
    "RateBucket",
    "RejectedError",
    "ResilientClient",
    "RetryPolicy",
    "ServeApp",
    "ServeConfig",
    "TenantAdmission",
    "TenantPolicy",
    "SimilarityEngine",
    "make_server",
]
