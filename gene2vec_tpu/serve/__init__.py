"""Online serving: batched embedding queries over training checkpoints.

Layering (docs/SERVING.md):

* :mod:`~gene2vec_tpu.serve.registry` — checkpoint discovery + atomic
  hot swap of the device-resident L2-normalized table;
* :mod:`~gene2vec_tpu.serve.engine` — the jitted bucketed top-k cosine
  kernel;
* :mod:`~gene2vec_tpu.serve.batcher` — micro-batching with max-delay /
  max-batch admission, bounded-queue backpressure, deadlines, LRU;
* :mod:`~gene2vec_tpu.serve.interaction` — GGIPNN pair scoring;
* :mod:`~gene2vec_tpu.serve.server` — the stdlib JSON HTTP API.

``python -m gene2vec_tpu.cli.serve`` runs the stack;
``scripts/serve_loadgen.py`` measures it.
"""

from gene2vec_tpu.serve.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    RejectedError,
)
from gene2vec_tpu.serve.engine import SimilarityEngine
from gene2vec_tpu.serve.registry import LoadedModel, ModelRegistry
from gene2vec_tpu.serve.server import ServeApp, ServeConfig, make_server

__all__ = [
    "DeadlineExceeded",
    "LoadedModel",
    "MicroBatcher",
    "ModelRegistry",
    "RejectedError",
    "ServeApp",
    "ServeConfig",
    "SimilarityEngine",
    "make_server",
]
