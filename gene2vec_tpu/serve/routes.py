"""The public /v1 route surface, as one dependency-light constant.

Both the replica server (``serve/server.py``) and the front-door proxy
(``serve/fleet.py``) label per-route latency over this exact set, so
the two allowlists cannot drift — and the proxy process (which never
loads a model) can import it without pulling numpy and the whole
serving stack.
"""

from __future__ import annotations

V1_ROUTES = frozenset((
    "/v1/genes", "/v1/similar", "/v1/embedding", "/v1/interaction",
))

#: the shard-replica control/scatter surface (serve/shardgroup.py):
#: ``topk`` and ``vectors`` are the scatter data plane, ``stage`` and
#: ``flip`` the coordinator's two-step shard-atomic hot swap.  Kept
#: separate from V1_ROUTES so an unsharded fleet's label set is
#: unchanged; the replica server unions both for its latency labels.
SHARD_ROUTES = frozenset((
    "/v1/shard/topk", "/v1/shard/vectors", "/v1/shard/stage",
    "/v1/shard/flip",
))

#: the batch-job lifecycle surface (gene2vec_tpu/batch/jobs.py),
#: mounted on whichever process owns the job store — a single replica
#: or the fleet front door (never forwarded, like /v1/shadow).  Routes
#: under it carry job ids (``/v1/jobs/<id>/artifact``); the label
#: helpers collapse them all to ``/v1/jobs`` so metric cardinality
#: stays bounded by the route TABLE, not by job history.
JOBS_ROUTE = "/v1/jobs"

#: overflow label for model names beyond the catalog (mirrors
#: tenancy.OVERFLOW_TENANT): an unknown or over-cap model name never
#: mints a new metric series.
OVERFLOW_MODEL = "other"

#: hard cap on catalog size — keeps the ``model=`` label space (and the
#: per-(model, shard) autoscale pool count) bounded the same way the
#: tenant table bounds ``tenant=``.
MAX_CATALOG_MODELS = 16


def collapse_jobs_route(route: str) -> str:
    """``/v1/jobs/<id>[/verb]`` -> ``/v1/jobs`` for metric labels;
    every other route unchanged."""
    if route == JOBS_ROUTE or route.startswith(JOBS_ROUTE + "/"):
        return JOBS_ROUTE
    return route


def split_model_route(path: str):
    """``/v1/<model>/similar`` -> ``("<model>", "/v1/similar")``; every
    non-model-prefixed path -> ``(None, path)`` unchanged.

    The split is recognized **only** when the remainder is a V1 route,
    so ``/v1/shard/topk`` and ``/v1/jobs/<id>/artifact`` — whose second
    segment is a verb or an id, not a model — are never misparsed as a
    model prefix.  Validation of the name itself (is it in the catalog?)
    is the caller's job; this is pure syntax.
    """
    if not path.startswith("/v1/"):
        return None, path
    rest = path[len("/v1/"):]
    name, sep, tail = rest.partition("/")
    if not sep or not name or not tail:
        return None, path
    candidate = "/v1/" + tail
    if collapse_jobs_route(candidate) in V1_ROUTES | {JOBS_ROUTE}:
        return name, candidate
    return None, path


def model_label(name, known) -> str:
    """Bounded ``model=`` label value: a catalog name passes through,
    anything else (unknown, oversized, over-cap) collapses into
    :data:`OVERFLOW_MODEL` — cardinality is capped by the catalog
    table, never by request traffic."""
    if name is None:
        return OVERFLOW_MODEL
    name = str(name)[:64]
    return name if name in known else OVERFLOW_MODEL
