"""The public /v1 route surface, as one dependency-light constant.

Both the replica server (``serve/server.py``) and the front-door proxy
(``serve/fleet.py``) label per-route latency over this exact set, so
the two allowlists cannot drift — and the proxy process (which never
loads a model) can import it without pulling numpy and the whole
serving stack.
"""

from __future__ import annotations

V1_ROUTES = frozenset((
    "/v1/genes", "/v1/similar", "/v1/embedding", "/v1/interaction",
))

#: the shard-replica control/scatter surface (serve/shardgroup.py):
#: ``topk`` and ``vectors`` are the scatter data plane, ``stage`` and
#: ``flip`` the coordinator's two-step shard-atomic hot swap.  Kept
#: separate from V1_ROUTES so an unsharded fleet's label set is
#: unchanged; the replica server unions both for its latency labels.
SHARD_ROUTES = frozenset((
    "/v1/shard/topk", "/v1/shard/vectors", "/v1/shard/stage",
    "/v1/shard/flip",
))

#: the batch-job lifecycle surface (gene2vec_tpu/batch/jobs.py),
#: mounted on whichever process owns the job store — a single replica
#: or the fleet front door (never forwarded, like /v1/shadow).  Routes
#: under it carry job ids (``/v1/jobs/<id>/artifact``); the label
#: helpers collapse them all to ``/v1/jobs`` so metric cardinality
#: stays bounded by the route TABLE, not by job history.
JOBS_ROUTE = "/v1/jobs"


def collapse_jobs_route(route: str) -> str:
    """``/v1/jobs/<id>[/verb]`` -> ``/v1/jobs`` for metric labels;
    every other route unchanged."""
    if route == JOBS_ROUTE or route.startswith(JOBS_ROUTE + "/"):
        return JOBS_ROUTE
    return route
