"""The public /v1 route surface, as one dependency-light constant.

Both the replica server (``serve/server.py``) and the front-door proxy
(``serve/fleet.py``) label per-route latency over this exact set, so
the two allowlists cannot drift — and the proxy process (which never
loads a model) can import it without pulling numpy and the whole
serving stack.
"""

from __future__ import annotations

V1_ROUTES = frozenset((
    "/v1/genes", "/v1/similar", "/v1/embedding", "/v1/interaction",
))

#: the shard-replica control/scatter surface (serve/shardgroup.py):
#: ``topk`` and ``vectors`` are the scatter data plane, ``stage`` and
#: ``flip`` the coordinator's two-step shard-atomic hot swap.  Kept
#: separate from V1_ROUTES so an unsharded fleet's label set is
#: unchanged; the replica server unions both for its latency labels.
SHARD_ROUTES = frozenset((
    "/v1/shard/topk", "/v1/shard/vectors", "/v1/shard/stage",
    "/v1/shard/flip",
))
