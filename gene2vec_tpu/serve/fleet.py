"""Replica fleet: supervision, health-based rotation, front-door proxy.

One ``cli.serve`` process is a single point of failure; production
embedding services run a *fleet* — N replicas over the same export dir
behind a router that treats replica death, wedging, and overload as
routine.  This module is that layer, stdlib-only like the rest of
``serve/``:

* :class:`FleetSupervisor` spawns N ``python -m gene2vec_tpu.cli.serve``
  children over one export dir, parses each child's one-line stdout JSON
  contract for its bound URL, and runs a monitor loop that

  - **health-checks** every replica's ``/healthz`` (the *readiness*
    probe — a replica that answers but has no model is ejected, not
    restarted);
  - **ejects** a replica from rotation after ``unhealthy_after``
    consecutive probe failures and **re-admits** it after
    ``readmit_after`` consecutive passes;
  - **restarts** crashed or wedged replicas with jittered exponential
    backoff, and permanently fails a slot that restarts more than
    ``storm_max_restarts`` times within ``storm_window_s`` (a
    restart-storm cap: a poisoned export must not grind the host with
    fork loops);
  - publishes fleet state via obs metrics: ``replica_up`` (gauge, in-
    rotation count), ``replica_restarts_total`` (counter), and
    per-replica ``replica_<i>_up`` gauges.

* :class:`FleetProxy` is the front door: a ``ThreadingHTTPServer`` that
  forwards ``/v1/*`` to the healthy set through a
  :class:`~gene2vec_tpu.serve.client.ResilientClient` (round-robin,
  retry-safe failover, per-replica circuit breakers, deadline
  propagation via the body's ``timeout_ms``).  ``/healthz`` reports
  fleet readiness (503 until at least one replica is in rotation),
  ``/livez`` process liveness, ``/metrics`` the fleet registry.

``python -m gene2vec_tpu.cli.fleet`` runs both and prints the same
one-line stdout contract as ``cli.serve`` (plus replica facts), so
loadgen and the chaos drill drive a fleet exactly like a single server.
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue as queue_mod
import random
import subprocess
import sys
import threading
import time
import urllib.request
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence
from urllib.parse import parse_qs, urlparse

from gene2vec_tpu.obs import tracecontext
from gene2vec_tpu.obs.aggregate import FleetAggregator
from gene2vec_tpu.obs.alerts import ALERTS_LOG_NAME, AlertEvaluator, RateLimiter
from gene2vec_tpu.obs.flight import FlightRecorder
from gene2vec_tpu.obs.incident import IncidentManager
from gene2vec_tpu.obs.trace import ambient_span
from gene2vec_tpu.obs.tracecontext import Sampler, TraceContext
from gene2vec_tpu.serve.client import (
    InFlightTracker,
    ResilientClient,
    RetryPolicy,
)
from gene2vec_tpu.serve.eventloop import (
    ConnHandle,
    EventLoopConfig,
    EventLoopHTTPServer,
    HandlerPool,
    HTTPRequest,
    Response,
    parse_json_body,
)
# the proxy labels per-route latency over the same /v1 surface the
# replicas label (one dependency-light constant, so the allowlists
# cannot drift and the proxy never imports the serving stack);
# everything else is "other" — no label cardinality from garbage paths
from gene2vec_tpu.serve.routes import (
    JOBS_ROUTE,
    V1_ROUTES,
    collapse_jobs_route,
    model_label,
    split_model_route,
)

#: routes the proxy labels latency under; job sub-routes collapse to
#: the table entry first (collapse_jobs_route)
_PROXY_ROUTES = V1_ROUTES | frozenset((JOBS_ROUTE,))


class ReplicaState:
    STARTING = "starting"    # spawned, waiting for contract line / health
    UP = "up"                # in rotation
    EJECTED = "ejected"      # alive but failing readiness; out of rotation
    BACKOFF = "backoff"      # dead, waiting out restart backoff
    FAILED = "failed"        # restart storm cap hit; given up
    DRAINING = "draining"    # leaving the fleet: out of rotation, alive
    #                          until its in-flight requests settle
    #                          (serve/autoscale.py scale-down)


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Supervision policy (cli/fleet.py flags)."""

    replicas: int = 3
    health_interval_s: float = 0.5
    health_timeout_s: float = 2.0
    unhealthy_after: int = 3     # consecutive probe failures -> eject
    readmit_after: int = 2       # consecutive passes -> back in rotation
    backoff_base_s: float = 0.5
    backoff_max_s: float = 30.0
    jitter_frac: float = 0.5     # uniform [1-j, 1+j] x the backoff
    storm_window_s: float = 60.0
    storm_max_restarts: int = 5
    contract_timeout_s: float = 120.0  # first stdout line deadline


class Replica:
    """One supervised ``cli.serve`` child and its rotation state.

    ``shard`` is the row shard this slot serves (None in an unsharded
    fleet).  With ``--replicas-per-shard`` several slots share one
    shard — the (shard, replica) grid — and the front door's scatter
    treats them as interchangeable siblings.  ``model`` is the catalog
    model this slot serves (None in a single-model fleet): a catalog
    fleet partitions its slots into per-model pools the same way a
    sharded fleet partitions them into per-shard pools, and the two
    never combine (cli.fleet rejects ``--catalog`` + ``--shard-by-rows``)."""

    def __init__(self, index: int, shard: Optional[int] = None,
                 model: Optional[str] = None):
        self.index = index
        self.shard = shard
        self.model = model
        self.proc: Optional[subprocess.Popen] = None
        self.url: Optional[str] = None
        self.state = ReplicaState.STARTING
        self.consecutive_failures = 0
        self.consecutive_passes = 0
        self.restarts = 0
        self.restart_times: Deque[float] = deque()
        self.next_restart_at = 0.0
        self.last_error: Optional[str] = None
        self.spawning = False  # a respawn thread is working on this slot

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


def read_contract_line(proc: subprocess.Popen, timeout_s: float) -> dict:
    """Parse a serve-family CLI's one stdout JSON contract line under a
    deadline — a child that wedges before printing must fail the caller,
    not hang it (the chaos-drill lesson, now shared)."""
    q: "queue_mod.Queue[Optional[str]]" = queue_mod.Queue()
    assert proc.stdout is not None

    def pump() -> None:
        q.put(proc.stdout.readline())

    threading.Thread(target=pump, daemon=True).start()
    try:
        line = q.get(timeout=timeout_s)
    except queue_mod.Empty:
        raise TimeoutError(
            f"child pid {proc.pid} printed no contract line within "
            f"{timeout_s}s"
        ) from None
    if not line:
        raise RuntimeError(
            f"child exited (rc={proc.poll()}) before printing its "
            "contract line (its stderr is above)"
        )
    return json.loads(line)


class FleetSupervisor:
    """Spawns, health-checks, ejects/re-admits, and restarts N replicas.

    ``serve_args`` go to every child verbatim; ``replica_args`` maps a
    replica index to extra per-replica flags (the drill uses it to turn
    fault injection on for exactly one replica).  ``rng`` seeds the
    restart jitter for reproducible drills.
    """

    def __init__(
        self,
        export_dir: str,
        config: FleetConfig = FleetConfig(),
        serve_args: Sequence[str] = (),
        replica_args: Optional[Dict[int, Sequence[str]]] = None,
        metrics=None,
        env: Optional[Dict[str, str]] = None,
        rng: Optional[random.Random] = None,
        shard_of: Optional[Dict[int, int]] = None,
        shard_args: Optional[Dict[int, Sequence[str]]] = None,
        model_of: Optional[Dict[int, str]] = None,
        model_args: Optional[Dict[str, Sequence[str]]] = None,
    ):
        self.export_dir = export_dir
        self.config = config
        self.serve_args = list(serve_args)
        self.replica_args = {
            int(k): list(v) for k, v in (replica_args or {}).items()
        }
        self.metrics = metrics
        self.env = env
        self._rng = rng if rng is not None else random.Random()
        # the (shard, replica) grid: slot index -> shard index, and the
        # per-SHARD extra flags every slot of that shard spawns with
        # (--shard-index/--num-shards) — keyed by shard, not slot, so an
        # elastically-added sibling inherits its shard's exact flags
        self._shard_of: Dict[int, int] = {
            int(k): int(v) for k, v in (shard_of or {}).items()
        }
        self._shard_args: Dict[int, List[str]] = {
            int(k): list(v) for k, v in (shard_args or {}).items()
        }
        # the (model, replica) grid (serve/catalog.py): slot index ->
        # catalog model name, and the per-MODEL extra flags every slot
        # of that pool spawns with (--export-dir override + --model-name
        # + the entry's extra_args) — keyed by name, not slot, so an
        # elastically-added pool member inherits its model's exact flags
        # (argparse last-wins lets the override shadow the defaults)
        self._model_of: Dict[int, str] = {
            int(k): str(v) for k, v in (model_of or {}).items()
        }
        self._model_args: Dict[str, List[str]] = {
            str(k): list(v) for k, v in (model_args or {}).items()
        }
        self.replicas = [
            Replica(i, shard=self._shard_of.get(i),
                    model=self._model_of.get(i))
            for i in range(config.replicas)
        ]
        #: next index for an elastically-added replica — indices are
        #: never reused, so per-replica metrics/log lines stay unambiguous
        self._next_index = config.replicas
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    # -- metrics -----------------------------------------------------------

    def _publish(self) -> None:
        if self.metrics is None:
            return
        with self._lock:
            replicas = list(self.replicas)
        up = sum(1 for r in replicas if r.state == ReplicaState.UP)
        self.metrics.gauge("replica_up").set(up)
        self.metrics.gauge("replica_count").set(len(replicas))
        for r in replicas:
            self.metrics.gauge(f"replica_{r.index}_up").set(
                1 if r.state == ReplicaState.UP else 0
            )

    def _count_restart(self) -> None:
        if self.metrics is not None:
            self.metrics.counter("replica_restarts_total").inc()

    # -- spawning ----------------------------------------------------------

    def _argv(self, index: int) -> List[str]:
        shard = self._shard_of.get(index)
        shard_flags = (
            self._shard_args.get(shard, []) if shard is not None else []
        )
        model = self._model_of.get(index)
        model_flags = (
            self._model_args.get(model, []) if model is not None else []
        )
        return [
            sys.executable, "-m", "gene2vec_tpu.cli.serve",
            "--export-dir", self.export_dir, "--port", "0",
            *self.serve_args, *shard_flags, *model_flags,
            *self.replica_args.get(index, []),
        ]

    def _spawn(self, replica: Replica) -> None:
        """Start (or restart) one replica and read its contract line.
        Raises on a child that dies or wedges before binding — and in
        that case KILLS the child first: a wedged-but-alive process left
        behind would make the slot look alive forever (``r.alive``
        gates the restart branch) while probing a stale URL."""
        env = dict(os.environ)
        # the contract line must not sit in a block buffer while the
        # supervisor waits on it
        env["PYTHONUNBUFFERED"] = "1"
        env.update(self.env or {})
        replica.url = None  # no probe may hit the previous incarnation
        replica.proc = subprocess.Popen(
            self._argv(replica.index),
            stdout=subprocess.PIPE, stderr=None, text=True, env=env,
        )
        try:
            info = read_contract_line(
                replica.proc, self.config.contract_timeout_s
            )
        except Exception:
            replica.proc.kill()
            try:
                replica.proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                pass
            raise
        replica.url = info["url"].rstrip("/")
        replica.consecutive_failures = 0
        replica.consecutive_passes = 0
        replica.state = ReplicaState.STARTING

    def start(self) -> "FleetSupervisor":
        """Spawn every replica, wait until each passes readiness once,
        then start the monitor loop.  A replica that cannot start at all
        fails ``start`` — a fleet that begins life degraded is a config
        error, not a runtime event.  ANY startup failure (a _spawn
        exception, a readiness timeout, a SIGTERM mid-start) tears down
        the replicas already launched — a failed start must not orphan
        N serving processes."""
        try:
            for r in self.replicas:
                self._spawn(r)
            deadline = time.monotonic() + self.config.contract_timeout_s
            for r in self.replicas:
                while time.monotonic() < deadline:
                    if self._probe(r):
                        r.state = ReplicaState.UP
                        break
                    time.sleep(0.1)
                if r.state != ReplicaState.UP:
                    raise TimeoutError(
                        f"replica {r.index} ({r.url}) never became ready"
                    )
        except BaseException:
            self.stop()
            raise
        self._publish()
        self._stop.clear()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="fleet-monitor", daemon=True
        )
        self._monitor.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=10.0)
            self._monitor = None
        with self._lock:
            replicas = list(self.replicas)
        for r in replicas:
            if r.proc is not None and r.proc.poll() is None:
                r.proc.terminate()
        for r in replicas:
            if r.proc is not None:
                try:
                    r.proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    r.proc.kill()
                    r.proc.wait(timeout=10.0)

    # -- health ------------------------------------------------------------

    def _probe(self, replica: Replica) -> bool:
        """One readiness probe.  False for connect failure, non-200, or
        a wedged replica (read timeout) alike — rotation only cares
        whether this replica can answer a real request right now."""
        if replica.url is None:
            return False
        try:
            with urllib.request.urlopen(
                f"{replica.url}/healthz",
                timeout=self.config.health_timeout_s,
            ) as resp:
                return resp.status == 200
        except Exception as e:
            replica.last_error = repr(e)[:200]
            return False

    def healthy_urls(self) -> List[str]:
        """The current rotation — what the proxy's client routes over."""
        with self._lock:
            return [
                r.url for r in self.replicas
                if r.state == ReplicaState.UP and r.url
            ]

    def live_urls(self) -> List[str]:
        """Every replica that is alive with a bound URL — the telemetry
        scrape set.  Wider than the rotation on purpose: an EJECTED
        replica's queue depth and error counters are exactly what the
        fleet view must not lose sight of."""
        with self._lock:
            return [r.url for r in self.replicas if r.alive and r.url]

    def states(self) -> List[Dict]:
        with self._lock:
            return [
                {
                    "index": r.index,
                    "state": r.state,
                    "url": r.url,
                    "pid": r.pid,
                    "shard": r.shard,
                    "model": r.model,
                    "restarts": r.restarts,
                    "last_error": r.last_error,
                }
                for r in self.replicas
            ]

    # -- the (shard, replica) grid -----------------------------------------

    def shard_urls(self, shard: int) -> List[str]:
        """Every UP replica of one shard — the target list the front
        door's per-shard client fails over across.  A dead sibling
        leaves this list on the next supervisor tick; until then the
        client's breakers and retry-safe failover absorb it."""
        with self._lock:
            return [
                r.url for r in self.replicas
                if r.shard == shard and r.state == ReplicaState.UP
                and r.url
            ]

    def shard_up_counts(self) -> Dict[int, int]:
        """UP replicas per shard — the redundancy view behind
        ``fleet_shard_replicas_up{shard=}`` and the
        ``shard-redundancy-lost`` alert."""
        with self._lock:
            out: Dict[int, int] = {}
            for r in self.replicas:
                if r.shard is None:
                    continue
                out.setdefault(r.shard, 0)
                if r.state == ReplicaState.UP:
                    out[r.shard] += 1
            return out

    def shard_redundancy_facts(self) -> Dict[int, Dict[str, int]]:
        """Per-shard ``{"up", "desired"}`` for the aggregator's
        ``shard_facts`` hook.  ``desired`` is the shard's CURRENT
        redundancy promise, not the boot-time ``--replicas-per-shard``:
        a slot the elastic controller is deliberately DRAINING has left
        the promise (scaling an idle pool down is policy, not an
        incident to page on), while a dead slot in backoff, an ejected
        replica, and a storm-abandoned FAILED slot all still count —
        those are the involuntary losses the ``shard-redundancy-lost``
        page exists for.  A brand-new slot joins the promise only once
        it has been admitted (STARTING with ``restarts == 0`` is the
        scale-up/boot spawn window, not a loss; a RESPAWNING slot keeps
        counting so the page holds until its sibling is truly back)."""
        with self._lock:
            out: Dict[int, Dict[str, int]] = {}
            for r in self.replicas:
                if r.shard is None:
                    continue
                f = out.setdefault(r.shard, {"up": 0, "desired": 0})
                if r.state == ReplicaState.UP:
                    f["up"] += 1
                if r.state == ReplicaState.DRAINING or (
                    r.state == ReplicaState.STARTING
                    and r.restarts == 0
                ):
                    continue
                f["desired"] += 1
            return out

    # -- the (model, replica) grid -----------------------------------------

    def model_urls(self, model: str) -> List[str]:
        """Every UP replica of one catalog model — the target list the
        front door's per-model client routes over.  The model-axis twin
        of :meth:`shard_urls`: a pool member leaves on the next tick,
        the client's breakers absorb it until then."""
        with self._lock:
            return [
                r.url for r in self.replicas
                if r.model == model and r.state == ReplicaState.UP
                and r.url
            ]

    def model_up_counts(self) -> Dict[str, int]:
        """UP replicas per catalog model — the per-model redundancy
        view behind ``fleet_model_replicas_up{model=}``."""
        with self._lock:
            out: Dict[str, int] = {}
            for r in self.replicas:
                if r.model is None:
                    continue
                out.setdefault(r.model, 0)
                if r.state == ReplicaState.UP:
                    out[r.model] += 1
            return out

    def model_of_url(self, url: str) -> Optional[str]:
        """The catalog model a replica URL serves (None when unknown or
        single-model) — the aggregator's hook for grouping per-target
        facts into per-model gauges without parsing label soup."""
        if not url:
            return None
        url = url.rstrip("/")
        with self._lock:
            for r in self.replicas:
                if r.url == url:
                    return r.model
        return None

    # -- elasticity (serve/autoscale.py ElasticController) -----------------

    def active_count(self, shard: Optional[int] = None,
                     model: Optional[str] = None) -> int:
        """Replica slots that count toward capacity: everything except
        abandoned (FAILED) and departing (DRAINING) slots — a dead slot
        in backoff still counts, because a restart is coming and
        scaling on top of it would double-provision.  ``shard``
        restricts the count to one shard's pool (the per-shard
        autoscaler's notion of "current"); ``model`` to one catalog
        model's pool — the (model, shard) autoscaler passes whichever
        axis the fleet actually partitions on."""
        with self._lock:
            return sum(
                1 for r in self.replicas
                if r.state not in (
                    ReplicaState.FAILED, ReplicaState.DRAINING
                ) and (shard is None or r.shard == shard)
                and (model is None or r.model == model)
            )

    def scale_up(self, shard: Optional[int] = None,
                 model: Optional[str] = None) -> Replica:
        """Spawn one NEW replica slot (never reusing an index).  Blocks
        on the child's startup contract line; the monitor loop admits
        it to rotation once readiness probes pass.  A spawn failure
        removes the slot again and propagates — the policy's cooldown
        decides when to try again.  ``shard`` spawns the slot into one
        shard's pool: it inherits that shard's flags and joins its
        scatter rotation on readiness.  ``model`` spawns it into one
        catalog model's pool: it inherits that model's export dir and
        name flags and joins that model's front-door rotation."""
        with self._lock:
            replica = Replica(self._next_index, shard=shard, model=model)
            if shard is not None:
                self._shard_of[replica.index] = shard
            if model is not None:
                self._model_of[replica.index] = model
            self._next_index += 1
            replica.spawning = True
            self.replicas.append(replica)
        try:
            self._spawn(replica)
        except Exception:
            with self._lock:
                if replica in self.replicas:
                    self.replicas.remove(replica)
            raise
        finally:
            replica.spawning = False
        if self._stop.is_set():
            # raced a fleet stop: this child slipped past stop()'s
            # terminate sweep — reap it here (the _respawn lesson)
            if replica.proc is not None:
                replica.proc.kill()
                replica.proc.wait(timeout=10.0)
            with self._lock:
                if replica in self.replicas:
                    self.replicas.remove(replica)
            return replica
        self._publish()
        return replica

    def pick_drain_victim(self, shard: Optional[int] = None,
                          model: Optional[str] = None
                          ) -> Optional[Replica]:
        """The replica a scale-down should remove: a dead/not-ready
        slot first (removing one is trivially zero-drop), else the
        NEWEST serving replica — and never the last one in rotation.
        A slot with a respawn in flight is not a candidate: draining
        it would race the spawn and orphan the freshly-forked child.
        ``shard`` scopes the choice to one shard's pool; "last in
        rotation" then means the last UP replica of THAT shard —
        draining it would un-serve the shard's rows.  ``model`` scopes
        it to one catalog model's pool with the same last-UP guard: a
        scale-down must never un-serve a whole model."""
        with self._lock:
            candidates = [
                r for r in self.replicas
                if r.state not in (
                    ReplicaState.FAILED, ReplicaState.DRAINING
                ) and not r.spawning
                and (shard is None or r.shard == shard)
                and (model is None or r.model == model)
            ]
            not_up = [
                r for r in candidates if r.state != ReplicaState.UP
            ]
            if not_up:
                return max(not_up, key=lambda r: r.index)
            ups = [r for r in candidates if r.state == ReplicaState.UP]
            if len(ups) > 1:
                return max(ups, key=lambda r: r.index)
            return None

    def begin_drain(self, replica: Replica) -> None:
        """Take the victim out of rotation: ``healthy_urls`` (the
        proxy's target callable) stops offering it on the very next
        pick, while ``live_urls`` keeps scraping it — its last-seconds
        telemetry still belongs in the fleet view."""
        with self._lock:
            replica.state = ReplicaState.DRAINING
        self._publish()

    def finish_drain(self, replica: Replica) -> None:
        """Terminate the drained victim (SIGTERM first — the same path
        ``stop`` uses — escalating to SIGKILL) and retire its slot.
        Call only after the front door's in-flight count on its URL
        has settled; the controller owns that wait."""
        proc = replica.proc
        if proc is not None and proc.poll() is None:
            proc.terminate()
        if proc is not None:
            try:
                proc.wait(timeout=15.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10.0)
        with self._lock:
            if replica in self.replicas:
                self.replicas.remove(replica)
        if self.metrics is not None:
            # retire the per-replica gauge with the slot: a long-lived
            # elastic fleet must not accrete one dead series per
            # departed replica
            self.metrics.remove(f"replica_{replica.index}_up")
        self._publish()

    # -- the monitor loop --------------------------------------------------

    def _schedule_restart(self, replica: Replica, now: float) -> None:
        """Death observed: apply the storm cap, then pick the jittered
        exponential backoff for the next spawn attempt."""
        window = self.config.storm_window_s
        while replica.restart_times and (
            now - replica.restart_times[0] > window
        ):
            replica.restart_times.popleft()
        if len(replica.restart_times) >= self.config.storm_max_restarts:
            replica.state = ReplicaState.FAILED
            replica.last_error = (
                f"restart storm: {len(replica.restart_times)} restarts "
                f"in {window:.0f}s — giving up on this slot"
            )
            if self.metrics is not None:
                self.metrics.counter("replica_storm_failures_total").inc()
            return
        n = len(replica.restart_times)
        backoff = min(
            self.config.backoff_base_s * (2 ** n),
            self.config.backoff_max_s,
        ) * (
            1.0 + self.config.jitter_frac * (2 * self._rng.random() - 1)
        )
        replica.state = ReplicaState.BACKOFF
        replica.next_restart_at = now + backoff

    def _respawn(self, replica: Replica) -> None:
        """One restart attempt, on its own thread: a respawn blocks on
        the child's whole startup (a jax import can take tens of
        seconds), and running it inside the monitor loop would blind
        supervision of every OTHER replica for that long."""
        try:
            if self._stop.is_set():
                return
            self._spawn(replica)
            with self._lock:
                retired = (
                    replica not in self.replicas
                    or replica.state == ReplicaState.DRAINING
                )
            if self._stop.is_set() or retired:
                # the fleet stopped — or a scale-down drained/removed
                # this slot — while we were spawning: this child raced
                # past the terminate sweep, reap it here (an orphaned
                # serving process on a bound port is the alternative)
                if replica.proc is not None:
                    replica.proc.kill()
                    replica.proc.wait(timeout=10.0)
                return
            replica.restarts += 1
            self._count_restart()
        except Exception as e:
            replica.last_error = repr(e)[:200]
            self._schedule_restart(replica, time.monotonic())
        finally:
            replica.spawning = False

    def _tick(self) -> None:
        now = time.monotonic()
        probe_list: List[Replica] = []
        with self._lock:
            replicas = list(self.replicas)
        for r in replicas:
            if r.state in (
                ReplicaState.FAILED, ReplicaState.DRAINING
            ) or r.spawning:
                # a DRAINING replica is leaving on purpose: no probes
                # (it is out of rotation already) and above all no
                # restart if it exits — the controller owns its death
                continue
            if not r.alive:
                if r.state == ReplicaState.BACKOFF:
                    if now >= r.next_restart_at:
                        # the ATTEMPT counts toward the storm window —
                        # a child that crashes before its contract line
                        # must still grow the backoff and trip the cap,
                        # or a bad flag becomes an eternal fork loop
                        r.restart_times.append(now)
                        r.spawning = True
                        threading.Thread(
                            target=self._respawn, args=(r,),
                            name=f"fleet-respawn-{r.index}", daemon=True,
                        ).start()
                else:
                    # freshly observed death (crash or wedge-kill)
                    self._schedule_restart(r, now)
                continue
            probe_list.append(r)
        # probes run CONCURRENTLY: one wedged replica (accepts TCP,
        # never answers — the blackhole class) costs its own
        # health_timeout_s, not everyone's detection cadence
        outcomes: Dict[int, bool] = {}
        probers = [
            threading.Thread(
                target=lambda r=r: outcomes.__setitem__(
                    r.index, self._probe(r)
                ),
                daemon=True,
            )
            for r in probe_list
        ]
        for t in probers:
            t.start()
        probe_deadline = (
            time.monotonic() + self.config.health_timeout_s + 1.0
        )
        for t in probers:
            t.join(timeout=max(0.0, probe_deadline - time.monotonic()))
        for r in probe_list:
            # a probe thread still stuck past the deadline counts as a
            # failed probe — exactly what a wedged replica deserves
            ok = outcomes.get(r.index, False)
            with self._lock:
                if ok:
                    r.consecutive_failures = 0
                    r.consecutive_passes += 1
                    if r.state in (
                        ReplicaState.STARTING, ReplicaState.EJECTED
                    ) and r.consecutive_passes >= self.config.readmit_after:
                        r.state = ReplicaState.UP
                else:
                    r.consecutive_passes = 0
                    r.consecutive_failures += 1
                    if (
                        r.state == ReplicaState.UP
                        and r.consecutive_failures
                        >= self.config.unhealthy_after
                    ):
                        r.state = ReplicaState.EJECTED
        self._publish()

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.config.health_interval_s):
            try:
                self._tick()
            except Exception as e:  # supervision must outlive surprises
                if self.metrics is not None:
                    self.metrics.counter(
                        "fleet_monitor_errors_total"
                    ).inc()
                print(f"fleet monitor error: {e!r}", file=sys.stderr)


# -- the front-door proxy ----------------------------------------------------


_LIVEZ_BODY = b'{"status": "alive"}'
_POOL_FULL_BODY = b'{"error": "proxy handler pool saturated; shed load"}'
_DEADLINE_BODY = (
    b'{"error": "fleet deadline exhausted before a replica answered"}'
)
_PROM_CT = b"text/plain; version=0.0.4"


class _ProxyAdapter:
    """Event-loop handler for the front door.  ``/livez`` answers
    inline from the loop; everything else runs on a bounded worker
    pool because forwarding blocks on replica round trips.  Successful
    replica responses pass through as **raw bytes** (the resilient
    client no longer parses 2xx bodies), so the proxy adds routing +
    resilience, not a JSON decode/encode cycle per request."""

    def __init__(self, proxy: "FleetProxy", workers: int,
                 max_queue: int = 2048):
        self.proxy = proxy
        self.pool = HandlerPool(workers, max_queue, name="fleet-proxy")

    def close(self) -> None:
        self.pool.stop()

    def account_protocol_error(self, status: int) -> None:
        """Loop-generated 400/408 responses (malformed request line,
        slow-loris reap) keep the proxy's error counters."""
        self.proxy.metrics.counter(f"fleet_http_{status}_total").inc()

    def __call__(self, req: HTTPRequest,
                 peer: ConnHandle) -> Optional[Response]:
        if req.method == "GET" and req.target in ("/livez", "/livez/"):
            return Response(200, _LIVEZ_BODY)
        if not self.pool.submit(lambda: self._run(req, peer)):
            self.proxy.metrics.counter("fleet_http_429_total").inc()
            return Response(429, _POOL_FULL_BODY)
        return None

    # -- worker-pool side --------------------------------------------------

    def _run(self, req: HTTPRequest, peer: ConnHandle) -> None:
        proxy = self.proxy
        route = urlparse(req.target).path.rstrip("/") or "/"
        if req.method == "GET" and route == "/healthz":
            status, doc = proxy.healthz()
            peer.respond(Response(
                status, json.dumps(doc).encode("utf-8")
            ))
            return
        if req.method == "GET" and route == "/metrics":
            peer.respond(Response(
                200, proxy.metrics.prometheus_text().encode("utf-8"),
                _PROM_CT,
            ))
            return
        if req.method == "GET" and route == "/debug/flight":
            # the proxy's own ring, same contract as a replica's
            # /debug/flight (serve/server.py)
            peer.respond(Response(
                200,
                json.dumps(
                    proxy.flight.snapshot_doc("debug")
                ).encode("utf-8"),
            ))
            return
        if req.method == "GET" and route == "/metrics/fleet":
            # the merged fleet-level SLO view (docs/OBSERVABILITY.md):
            # availability, per-route p50/p99, total queue depth,
            # rejection rate — the autoscaling inputs, one scrape
            if proxy.aggregator is None:
                peer.respond(Response(
                    404,
                    b'{"error": "fleet aggregation disabled '
                    b'(--scrape-interval 0)"}',
                ))
                return
            peer.respond(Response(
                200, proxy.aggregator.fleet_text().encode("utf-8"),
                _PROM_CT,
            ))
            return
        if route.startswith("/v1/shadow"):
            # the continuous-learning canary's admin surface
            # (loop/shadow.py): start/stop/report a shadow-traffic
            # window against a candidate replica.  Handled HERE — never
            # forwarded — so the candidate is driven by duplicated live
            # traffic, not by clients discovering an admin route.
            if proxy.shadow is None:
                peer.respond(Response(
                    404,
                    b'{"error": "shadow canary disabled '
                    b'(--enable-shadow)"}',
                ))
                return
            sbody: Optional[dict] = None
            if req.method == "POST":
                sbody, err = parse_json_body(req)
                if err is not None:
                    peer.respond(err)
                    return
            status, doc = proxy.shadow.admin(req.method, route, sbody)
            peer.respond(Response(
                status, json.dumps(doc).encode("utf-8")
            ))
            return
        if route == "/v1/jobs" or route.startswith("/v1/jobs/"):
            # the batch-job lifecycle surface (gene2vec_tpu/batch/):
            # handled HERE — never forwarded — because the front door
            # owns the job store and the fleet-wide query backend
            # (scatter-gather when sharded, the resilient client
            # otherwise); a replica never sees job routes.
            from gene2vec_tpu.batch.jobs import dispatch_jobs

            jbody: Optional[dict] = None
            if req.method == "POST":
                jbody, err = parse_json_body(req)
                if err is not None:
                    peer.respond(err)
                    return
            status, doc = dispatch_jobs(
                proxy.jobs, req.method, route,
                parse_qs(urlparse(req.target).query), jbody,
            )
            proxy.metrics.counter("fleet_proxy_responses_total").inc()
            peer.respond(Response(
                status, json.dumps(doc).encode("utf-8")
            ))
            return
        if not route.startswith("/v1/"):
            peer.respond(Response(
                404,
                json.dumps(
                    {"error": f"no route {req.method} {route}"}
                ).encode("utf-8"),
            ))
            return
        # catalog routing: /v1/<model>/* goes to the NAMED model's pool
        # (the prefixed target forwards verbatim — a replica accepts its
        # own name as an alias), unprefixed /v1/* to the default pool.
        # Unknown names 404 and over-quota models 429 HERE, before a
        # replica round trip — and before any metric label is minted
        # from the raw name (model= stays bounded by the catalog).
        name: Optional[str] = None
        model: Optional[str] = None
        canonical = route
        if proxy.catalog is not None:
            name, canonical = split_model_route(route)
            if name is not None and name not in proxy.model_clients:
                proxy.metrics.counter("fleet_http_404_total").inc()
                peer.respond(Response(
                    404,
                    json.dumps(
                        {"error": f"unknown model {name!r}"}
                    ).encode("utf-8"),
                ))
                return
            model = name if name is not None else proxy.catalog.default
            if (
                proxy.model_admission is not None
                and not proxy.model_admission.admit(model)
            ):
                proxy.metrics.counter(
                    "fleet_model_rejected_total",
                    labels={
                        "model": model_label(model, proxy.model_clients)
                    },
                ).inc()
                proxy.metrics.counter("fleet_http_429_total").inc()
                peer.respond(Response(
                    429,
                    json.dumps({
                        "error": (
                            f"model {model!r} over its request "
                            "budget; retry later"
                        )
                    }).encode("utf-8"),
                ))
                return
        body: Optional[dict] = None
        if req.method == "POST":
            body, err = parse_json_body(req)
            if err is not None:
                peer.respond(err)
                return
        if proxy.shard_group is not None:
            self._scatter_dispatch(req, peer, route, body)
            return
        self._forward(
            req, peer, canonical, body,
            client=proxy.model_clients.get(model) if model else None,
            model=model,
            shadow_ok=name is None,
        )

    # -- sharded mode: scatter-gather instead of round-robin ---------------

    def _scatter_dispatch(self, req: HTTPRequest, peer: ConnHandle,
                          route: str, body: Optional[dict]) -> None:
        """Route the /v1 surface through the shard group
        (serve/shardgroup.py): ``/v1/similar`` scatter-gathers every
        shard, ``/v1/embedding`` routes to the owning shards,
        ``/v1/genes`` answers from the manifest-derived routing table.
        Same trace ingress as the round-robin path — the scatter's
        per-shard attempts become sibling child spans under one
        ``proxy_scatter`` span."""
        proxy = self.proxy
        group = proxy.shard_group
        incoming = TraceContext.from_header(
            req.headers.get("traceparent")
        )
        ctx = incoming.child() if incoming is not None else (
            proxy.sampler.maybe_new_trace()
            if proxy.sampler is not None else None
        )
        t0 = time.monotonic()
        with tracecontext.use(ctx):
            with ambient_span("proxy_request", route=route) as span:
                if route == "/v1/similar":
                    if req.method == "GET":
                        q = parse_qs(urlparse(req.target).query)
                        gene = q.get("gene", [None])[0]
                        if gene is None:
                            status, doc = 400, {
                                "error": "missing ?gene= parameter"
                            }
                        else:
                            try:
                                k = int(q.get("k", ["10"])[0])
                            except ValueError:
                                k = -1  # rejected by validation below
                            status, doc = group.similar(
                                {"genes": [gene], "k": k}
                            )
                    else:
                        status, doc = group.similar(body or {})
                elif route == "/v1/embedding" and req.method == "POST":
                    status, doc = group.embedding(body or {})
                elif route == "/v1/genes" and req.method == "GET":
                    q = parse_qs(urlparse(req.target).query)
                    try:
                        limit = int(q.get("limit", ["100"])[0])
                        offset = int(q.get("offset", ["0"])[0])
                    except ValueError:
                        limit, offset = -1, -1
                    if limit < 0 or offset < 0:
                        status, doc = 400, {
                            "error": "limit/offset must be >= 0"
                        }
                    else:
                        status, doc = 200, group.routing.genes_doc(
                            limit, offset
                        )
                elif route == "/v1/interaction" and req.method == "POST":
                    # cross-shard pair scoring: each gene's vector is
                    # resolved from its OWNER shard's replica group and
                    # the GGIPNN head runs at the front door — same
                    # degraded contract as /v1/similar when an owner
                    # group is fully down (serve/shardgroup.py)
                    status, doc = group.interaction(body or {})
                else:
                    status, doc = 404, {
                        "error": f"no route {req.method} {route}"
                    }
                span["status"] = status
        dur = time.monotonic() - t0
        proxy.account(route, status, dur,
                      ctx.trace_id if ctx is not None else None)
        payload = json.dumps(doc).encode("utf-8")
        if (
            proxy.shadow is not None and route == "/v1/similar"
            and 200 <= status < 300
        ):
            # same canary hook as _forward: a --shard-by-rows fleet
            # must feed the shadow scorer too, or a canary against a
            # sharded fleet starves of evidence and demotes a healthy
            # candidate
            proxy.shadow.observe(
                req.method, req.target, body, payload, dur, ctx
            )
        peer.respond(Response(status, payload))

    def _forward(self, req: HTTPRequest, peer: ConnHandle, route: str,
                 body: Optional[dict],
                 client: Optional[ResilientClient] = None,
                 model: Optional[str] = None,
                 shadow_ok: bool = True) -> None:
        proxy = self.proxy
        if client is None:
            client = proxy.client
        # the proxy is the fleet's trace ingress: honor a propagated
        # context (child it), else maybe start a root; the resilient
        # client below picks the installed context up as its base, so
        # every replica attempt becomes a child span of this hop
        incoming = TraceContext.from_header(
            req.headers.get("traceparent")
        )
        ctx = incoming.child() if incoming is not None else (
            proxy.sampler.maybe_new_trace()
            if proxy.sampler is not None else None
        )
        # tenant pass-through: the replicas own quota enforcement
        # (per-replica token buckets, serve/tenancy.py); the proxy just
        # forwards the identity so a quota 429 lands on the right
        # tenant no matter which replica answers
        tenant = req.headers.get("x-tenant")
        extra = {"X-Tenant": tenant} if tenant else None
        t0 = time.monotonic()
        with tracecontext.use(ctx):
            with ambient_span("proxy_request", route=route) as span:
                resp = client.request(
                    req.target, body=body, method=req.method,
                    timeout_s=(
                        float(body["timeout_ms"]) / 1000.0
                        if body
                        and isinstance(
                            body.get("timeout_ms"), (int, float)
                        )
                        else None
                    ),
                    headers=extra,
                )
                span["attempts"] = resp.attempts
        if resp.ok and resp.raw is not None:
            # zero-copy passthrough: the replica's encoded body goes to
            # the client verbatim — no parse, no re-serialization
            status, payload = resp.status, resp.raw
        elif resp.doc is not None:
            status, payload = resp.status, (
                resp.raw if resp.raw else
                json.dumps(resp.doc).encode("utf-8")
            )
        elif resp.error_class == "deadline":
            status, payload = 504, _DEADLINE_BODY
        else:
            status, payload = 502, json.dumps(
                {"error": f"no replica answered ({resp.error_class})"}
            ).encode("utf-8")
        # account BEFORE the reply write can fail: a client gone mid-
        # reply (broken pipe during an incident) must still count in
        # the availability view and the flight ring
        dur = time.monotonic() - t0
        proxy.account(route, status, dur,
                      ctx.trace_id if ctx is not None else None,
                      model=model)
        if (
            proxy.shadow is not None and route == "/v1/similar"
            and shadow_ok and 200 <= status < 300
        ):
            # shadow-traffic canary (loop/shadow.py): maybe duplicate
            # this request to the candidate replica — fire-and-forget,
            # off this caller's latency path (one predicate + a
            # bounded enqueue), same trace id so the arms render as
            # siblings in cli.obs trace
            proxy.shadow.observe(
                req.method, req.target, body, payload, dur, ctx
            )
        peer.respond(Response(status, payload))


class FleetProxy:
    """The fleet's single public address.  Owns the resilient client
    whose target set is the supervisor's LIVE rotation (a callable, so
    ejections and re-admissions apply to the very next request)."""

    def __init__(
        self,
        supervisor: FleetSupervisor,
        metrics,
        policy: Optional[RetryPolicy] = None,
        read_timeout_s: float = 10.0,
        trace_sample: float = 0.0,
        scrape_interval_s: float = 2.0,
        telemetry_csv: Optional[str] = None,
        flight_dir: Optional[str] = None,
        proxy_workers: int = 16,
        idle_timeout_s: float = 30.0,
        acceptors: int = 1,
        alert_rules=None,
        shard_group=None,
        shadow=None,
        jobs=None,
        catalog=None,
        model_admission=None,
    ):
        self.supervisor = supervisor
        self.metrics = metrics
        #: serve/catalog.py CatalogSpec — set when the fleet serves a
        #: multi-model catalog (cli.fleet --catalog): slots partition
        #: into per-model pools, ``/v1/<model>/*`` routes to the named
        #: pool, unprefixed ``/v1/*`` keeps serving the default model
        self.catalog = catalog
        #: serve/catalog.py ModelAdmission — the front door's per-model
        #: token buckets; crossed with the replicas' per-tenant buckets
        #: (a request must clear both gates)
        self.model_admission = model_admission
        #: gene2vec_tpu/batch/jobs.py JobManager — set when the fleet
        #: runs with a job store (cli.fleet --jobs-dir); owns the
        #: /v1/jobs lifecycle surface, handled at the front door and
        #: never forwarded (like /v1/shadow)
        self.jobs = jobs
        #: loop/shadow.py ShadowManager — set when the fleet runs with
        #: the continuous-learning canary enabled (cli.fleet
        #: --enable-shadow); owns the /v1/shadow/* admin surface and
        #: the off-path duplication of sampled /v1/similar traffic
        self.shadow = shadow
        #: serve/shardgroup.py ShardGroup — set when the fleet serves
        #: row SHARDS of one table instead of N identical replicas;
        #: flips the /v1 surface from round-robin forwarding to
        #: scatter-gather (cli/fleet.py --shard-by-rows)
        self.shard_group = shard_group
        self.read_timeout_s = read_timeout_s
        self.proxy_workers = proxy_workers
        self.idle_timeout_s = idle_timeout_s
        self.acceptors = acceptors
        # per-replica in-flight accounting: the zero-drop contract for
        # elastic scale-down AND fleet-wide graceful shutdown — a
        # draining replica is terminated only once its count here
        # settles to zero (serve/autoscale.py, FleetProxy.drain)
        self.inflight = InFlightTracker()
        _policy = policy if policy is not None else RetryPolicy(
            max_attempts=3,
            connect_timeout_s=1.0,
            default_timeout_s=5.0,
        )
        if catalog is not None:
            # per-model pools: one resilient client per catalog model,
            # all sharing ONE in-flight tracker (the drain contract is
            # per-URL, not per-pool).  Unprefixed /v1/* routes over the
            # DEFAULT model's pool — a dim512 replica answering an
            # unprefixed request would silently serve the wrong model.
            self.model_clients: Dict[str, ResilientClient] = {
                name: ResilientClient(
                    (lambda n=name: supervisor.model_urls(n)),
                    policy=_policy,
                    metrics=metrics,
                    inflight=self.inflight,
                )
                for name in catalog.names
            }
            self.client = self.model_clients[catalog.default]
        else:
            self.model_clients = {}
            self.client = ResilientClient(
                supervisor.healthy_urls,
                policy=_policy,
                metrics=metrics,
                inflight=self.inflight,
            )
        self.sampler = Sampler(trace_sample) if trace_sample > 0 else None
        # the telemetry plane: scrape every LIVE replica (not just the
        # rotation) + this registry's own availability counters
        self.aggregator: Optional[FleetAggregator] = (
            FleetAggregator(
                supervisor.live_urls,
                proxy_registry=metrics,
                interval_s=scrape_interval_s,
                csv_path=telemetry_csv,
            )
            if scrape_interval_s > 0 else None
        )
        # ONE rate limiter for everything that writes forensics to disk
        # from this process: the proxy's own 5xx-burst flight dumps and
        # rule-triggered incident bundles share the budget
        self.limiter = RateLimiter()
        self.flight = FlightRecorder(limiter=self.limiter)
        self.flight_dir = flight_dir
        # the detection loop: alert rules evaluated on every scrape
        # tick; a rule transitioning to firing hands the incident
        # manager a bundle job on its own thread (obs/alerts.py,
        # obs/incident.py) — nothing here ever runs on the serve path
        self.evaluator: Optional[AlertEvaluator] = None
        self.incidents: Optional[IncidentManager] = None
        if alert_rules and self.aggregator is not None and flight_dir:
            self.incidents = IncidentManager(
                os.path.join(flight_dir, "incidents"),
                scan_roots=[supervisor.export_dir, flight_dir],
                targets=supervisor.live_urls,
                local_flight=self.flight,
                aggregator=self.aggregator,
                limiter=self.limiter,
                metrics=metrics,
            )
            self.evaluator = AlertEvaluator(
                alert_rules,
                registry=self.aggregator.view,
                log_path=os.path.join(flight_dir, ALERTS_LOG_NAME),
                on_fire=self.incidents.fire_async,
            )
            self.aggregator.evaluator = self.evaluator
        self._server: Optional[EventLoopHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def account(self, route: str, status: int, dur_s: float,
                trace_id: Optional[str],
                model: Optional[str] = None) -> None:
        """Per-forwarded-response bookkeeping: the availability
        counters the aggregator reads, the per-route latency series,
        and the proxy's flight-recorder ring.  ``route`` is always the
        CANONICAL route (a model prefix is normalized away before
        accounting); in catalog mode the model rides along as its own
        bounded ``model=`` label instead — a single-model fleet's label
        sets stay byte-identical."""
        self.metrics.counter("fleet_proxy_responses_total").inc()
        if 200 <= status < 300:
            self.metrics.counter("fleet_proxy_ok_total").inc()
        elif status == 429:
            # explicit backpressure (queue-full OR tenant quota) is
            # deliberate shedding, not an availability failure: the
            # aggregator exports this so the autoscaler can take 429s
            # out of its availability-burn window (queue pressure still
            # reaches it through the rejection-rate signal)
            self.metrics.counter("fleet_proxy_429_total").inc()
        label = collapse_jobs_route(route)
        label = label if label in _PROXY_ROUTES else "other"
        labels = {"route": label}
        if self.catalog is not None:
            labels["model"] = model_label(
                model if model is not None else self.catalog.default,
                self.model_clients,
            )
        self.metrics.histogram(
            "fleet_proxy_seconds", labels=labels
        ).observe(dur_s)
        burst = self.flight.record(route, status, dur_s, trace_id=trace_id)
        if burst and self.flight_dir:
            try:
                self.flight.dump(self.flight_dir, "5xx-burst")
            except OSError:
                pass

    def healthz(self) -> "tuple":
        states = self.supervisor.states()
        up = [s for s in states if s["state"] == ReplicaState.UP]
        doc = {
            "status": "ok" if up else "not_ready",
            "replicas_up": len(up),
            "replicas": states,
        }
        if self.catalog is not None:
            # the per-model grid: pool membership + UP count per
            # catalog model, so loadgen and the chaos drill learn the
            # whole (model, replica) layout from one probe.  A fleet
            # with SOME empty pool is "degraded", not down — the
            # default model's surface may still be fully up.
            counts = self.supervisor.model_up_counts()
            doc["default_model"] = self.catalog.default
            doc["models"] = {
                name: {
                    "up": counts.get(name, 0),
                    "replicas": [
                        {
                            "index": s["index"],
                            "up": s["state"] == ReplicaState.UP,
                            "pid": s["pid"],
                        }
                        for s in states if s.get("model") == name
                    ],
                }
                for name in self.catalog.names
            }
            if up and any(
                counts.get(n, 0) == 0 for n in self.catalog.names
            ):
                doc["status"] = "degraded"
        if self.shard_group is not None:
            # per-shard state: row range, replica-GROUP membership, and
            # the epoch each cell was last seen serving — the operator's
            # one-look view of a degraded or mid-swap fleet.  A shard is
            # "up" when ANY replica of its group is in rotation; the
            # per-replica rows let loadgen/--verify and the drill learn
            # the whole (shard, replica) grid from one probe.
            group = self.shard_group
            by_shard: Dict[int, List[Dict]] = {}
            for s in states:
                if s.get("shard") is None:
                    continue
                by_shard.setdefault(s["shard"], []).append({
                    "index": s["index"],
                    "up": s["state"] == ReplicaState.UP,
                    "pid": s["pid"],
                    "epoch": group.replica_epoch(s["url"]),
                })
            doc["shards"] = group.shard_states(
                up_for=lambda i: any(
                    r["up"] for r in by_shard.get(i, [])
                ),
                replicas_for=lambda i: by_shard.get(i, []),
            )
            doc["epoch"] = group.current_epoch
        return (200 if up else 503), doc

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> str:
        """Bind the event-loop front end and serve on a daemon thread;
        returns the base URL."""
        adapter = _ProxyAdapter(self, workers=self.proxy_workers)
        server = EventLoopHTTPServer(
            adapter,
            host,
            port,
            config=EventLoopConfig(
                read_timeout_s=self.read_timeout_s,
                idle_timeout_s=self.idle_timeout_s,
                acceptors=self.acceptors,
            ),
            on_protocol_error=adapter.account_protocol_error,
        )
        self._server = server
        self._thread = threading.Thread(
            target=server.serve_forever, name="fleet-proxy", daemon=True
        )
        self._thread.start()
        if self.aggregator is not None:
            self.aggregator.start()
        if self.jobs is not None:
            # recover + start the batch worker only once the front
            # door can actually answer the queries jobs will send
            self.jobs.start()
        bound_host, bound_port = server.server_address[:2]
        return f"http://{bound_host}:{bound_port}"

    def drain(self, timeout_s: float = 10.0,
              poll_s: float = 0.05) -> bool:
        """Wait for every in-flight replica forward to settle — the
        graceful-shutdown half of the zero-drop contract: call after
        :meth:`stop` (no new requests are being accepted) and BEFORE
        ``supervisor.stop()`` tears the replicas down, so a forward the
        proxy already dispatched completes against a living replica
        instead of dying with it.  True when the front door is empty,
        False on timeout (callers proceed either way; the wait is the
        courtesy, not a lock)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.inflight.total() == 0:
                return True
            time.sleep(poll_s)
        remaining = self.inflight.total()
        if remaining and self.metrics is not None:
            self.metrics.counter("fleet_drain_timeouts_total").inc()
        return remaining == 0

    def stop(self) -> None:
        if self.jobs is not None:
            # first: a running job must stop issuing queries before the
            # replicas it queries go away (it stays journal-"running"
            # and resumes from its committed cursor on next start)
            self.jobs.stop()
        if self.aggregator is not None:
            self.aggregator.stop()
        if self.shadow is not None:
            self.shadow.close()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        self._thread = None
