"""Multi-model serving catalog: named models behind ``/v1/<model>/*``.

A *catalog spec* is a small JSON document mapping model names onto
export directories plus per-model serving knobs::

    {
      "schema": "gene2vec-tpu/catalog/v1",
      "default": "dim200",
      "models": {
        "dim200": {"export_dir": "exports/dim200"},
        "dim512": {
          "export_dir": "exports/dim512",
          "dim": 512,
          "index": "exact",
          "ggipnn_checkpoint": null,
          "rate": 0.0, "burst": 0,
          "replicas": 1,
          "partition_rules": [["(^|/)(emb|ctx|unit)$", ["model", null]]],
          "extra_args": []
        }
      }
    }

Relative ``export_dir`` paths resolve against the spec file's own
directory, so a catalog travels with its exports.  Names are capped at
:data:`~gene2vec_tpu.serve.routes.MAX_CATALOG_MODELS` and validated
against the route grammar (a model may not be called ``similar`` or
``shard`` — the URL would be ambiguous), which is also what bounds the
``model=`` metric label space.

:class:`ModelCatalog` materializes the spec on a replica: one
:class:`~gene2vec_tpu.serve.registry.ModelRegistry` + one
:class:`~gene2vec_tpu.serve.server.ServeApp` (engine, micro-batcher,
response cache, jit cache) **per model**, all sharing one metrics
registry, one mesh, and one tenant-admission table.  Isolation is
structural: per-model registries mean hot swap, shadow canary, and
manifest-CRC verification never mix models (one watcher per entry),
per-model apps mean a swap invalidates only its own response cache,
and per-model engines mean one model's jit recompile never stalls
another's steady state.

The spec parser and :class:`ModelAdmission` (the front door's
per-model token buckets, crossing with per-tenant admission) are
dependency-light on purpose: the fleet proxy process reads the same
spec to learn names/rates/replica counts without importing numpy or
the model-loading stack — heavy imports happen only inside
:meth:`ModelCatalog.build`.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

from gene2vec_tpu.serve.routes import MAX_CATALOG_MODELS
from gene2vec_tpu.serve.tenancy import RateBucket

CATALOG_SCHEMA = "gene2vec-tpu/catalog/v1"

#: names that would collide with route segments under /v1/<name>/...
RESERVED_MODEL_NAMES = frozenset((
    "similar", "embedding", "interaction", "genes", "shard", "jobs",
    "shadow", "metrics", "healthz", "livez", "default",
))

_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]{0,63}$")


@dataclasses.dataclass(frozen=True)
class CatalogEntry:
    """One named model in the catalog."""

    name: str
    export_dir: str
    dim: Optional[int] = None
    index_mode: str = "exact"
    ggipnn_checkpoint: Optional[str] = None
    #: front-door token bucket (requests/s + burst); 0 = unlimited
    rate: float = 0.0
    burst: int = 0
    #: initial replicas for this model's fleet pool
    replicas: int = 1
    #: raw [[pattern, axes], ...] rules (parallel/partition_rules.py
    #: parse_rules); None -> the library defaults
    partition_rules: Optional[Tuple] = None
    #: extra cli.serve args appended to this model's replica argv
    extra_args: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class CatalogSpec:
    """Parsed, validated catalog: ordered entries + the default name."""

    entries: Tuple[CatalogEntry, ...]
    default: str
    path: str = ""

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(e.name for e in self.entries)

    def entry(self, name: str) -> CatalogEntry:
        for e in self.entries:
            if e.name == name:
                return e
        raise KeyError(f"model {name!r} not in catalog {self.names}")

    @property
    def default_entry(self) -> CatalogEntry:
        return self.entry(self.default)


def _validate_name(name: str) -> str:
    if not _NAME_RE.match(name or ""):
        raise ValueError(
            f"catalog model name {name!r} must match "
            f"{_NAME_RE.pattern} (it becomes a URL segment and a "
            "metric label)"
        )
    if name in RESERVED_MODEL_NAMES:
        raise ValueError(
            f"catalog model name {name!r} is reserved (collides with "
            "the /v1 route grammar)"
        )
    return name


def parse_catalog_spec(doc: Dict[str, Any], base_dir: str = "",
                       path: str = "") -> CatalogSpec:
    """Validate a catalog document into a :class:`CatalogSpec`.
    Every structural error is raised here, at spec-load time — a bad
    catalog never makes it to a half-started fleet."""
    if not isinstance(doc, dict) or not isinstance(
        doc.get("models"), dict
    ):
        raise ValueError("catalog spec must be {'models': {name: {...}}}")
    models = doc["models"]
    if not models:
        raise ValueError("catalog spec has no models")
    if len(models) > MAX_CATALOG_MODELS:
        raise ValueError(
            f"catalog has {len(models)} models; cap is "
            f"{MAX_CATALOG_MODELS} (the model= label bound)"
        )
    entries: List[CatalogEntry] = []
    for name, m in models.items():
        _validate_name(name)
        if not isinstance(m, dict) or not m.get("export_dir"):
            raise ValueError(
                f"catalog model {name!r} needs an 'export_dir'"
            )
        export_dir = str(m["export_dir"])
        if base_dir and not os.path.isabs(export_dir):
            export_dir = os.path.join(base_dir, export_dir)
        ggipnn = m.get("ggipnn_checkpoint")
        if ggipnn and base_dir and not os.path.isabs(ggipnn):
            ggipnn = os.path.join(base_dir, ggipnn)
        rules = m.get("partition_rules")
        if rules is not None:
            # validate eagerly (regex + shape), store the raw form —
            # PartitionSpec objects are built lazily on the replica
            from gene2vec_tpu.parallel.partition_rules import parse_rules

            parse_rules(rules)
            rules = tuple(tuple(r) for r in rules)
        replicas = int(m.get("replicas", 1))
        if replicas < 1:
            raise ValueError(
                f"catalog model {name!r}: replicas must be >= 1"
            )
        rate = float(m.get("rate", 0.0))
        burst = int(m.get("burst", 0))
        if rate < 0 or burst < 0:
            raise ValueError(
                f"catalog model {name!r}: rate/burst must be >= 0"
            )
        entries.append(CatalogEntry(
            name=name,
            export_dir=export_dir,
            dim=int(m["dim"]) if m.get("dim") else None,
            index_mode=str(m.get("index", "exact")),
            ggipnn_checkpoint=ggipnn,
            rate=rate,
            burst=burst,
            replicas=replicas,
            partition_rules=rules,
            extra_args=tuple(str(a) for a in m.get("extra_args", ())),
        ))
    default = doc.get("default") or entries[0].name
    if default not in {e.name for e in entries}:
        raise ValueError(
            f"catalog default {default!r} names no model "
            f"(have {[e.name for e in entries]})"
        )
    return CatalogSpec(entries=tuple(entries), default=default, path=path)


def load_catalog_spec(path: str) -> CatalogSpec:
    """Read + validate a catalog spec file."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    return parse_catalog_spec(
        doc, base_dir=os.path.dirname(os.path.abspath(path)), path=path
    )


class ModelAdmission:
    """Front-door per-model token buckets (the model-axis twin of
    ``TenantAdmission``): a hot model exhausts *its own* budget and is
    429'd, it cannot starve a cold model's queue.  Crossed with
    per-tenant admission — a request must clear both gates.  Bounded by
    the catalog table, so the ``model=`` label space never grows with
    traffic."""

    def __init__(self, spec: CatalogSpec, clock=None):
        import time as _time

        clock = clock or _time.monotonic
        self._buckets: Dict[str, RateBucket] = {
            e.name: RateBucket(e.rate, max(e.burst, 1), clock=clock)
            for e in spec.entries if e.rate > 0
        }

    def admit(self, model: Optional[str]) -> bool:
        """Take one token from ``model``'s bucket; unlimited (no
        bucket) and unknown names admit — unknown names 404 later, the
        quota gate is not a validity gate."""
        bucket = self._buckets.get(model or "")
        return bucket.take() if bucket is not None else True


class ModelCatalog:
    """The replica-side materialized catalog: name -> ServeApp."""

    def __init__(
        self,
        spec: CatalogSpec,
        config=None,
        metrics=None,
        mesh=None,
        fault_injector=None,
    ):
        self.spec = spec
        self.config = config
        self.metrics = metrics
        self.mesh = mesh
        self.fault_injector = fault_injector
        self.apps: Dict[str, Any] = {}
        self._lock = threading.Lock()

    # -- construction ------------------------------------------------------

    def build(self) -> "ModelCatalog":
        """Build one registry + app per entry.  The default model must
        load (a catalog that cannot serve its backward-compat surface
        is DOA); a non-default entry that cannot load yet starts empty
        and is picked up by its own watcher — per-model quarantine
        keeps it from poisoning its siblings."""
        from gene2vec_tpu.parallel.partition_rules import (
            DEFAULT_SERVE_RULES, parse_rules,
        )
        from gene2vec_tpu.serve.registry import ModelRegistry
        from gene2vec_tpu.serve.server import ServeApp

        for entry in self.spec.entries:
            rules = (
                parse_rules(entry.partition_rules)
                if entry.partition_rules is not None
                else DEFAULT_SERVE_RULES
            )
            registry = ModelRegistry(
                entry.export_dir,
                dim=entry.dim,
                metrics=self.metrics,
                index_mode=entry.index_mode,
                name=entry.name,
                partition_rules=rules,
                mesh=self.mesh,
            )
            loaded = False
            try:
                loaded = registry.refresh()
            except Exception:
                loaded = False
            if not loaded and entry.name == self.spec.default:
                raise RuntimeError(
                    f"catalog default model {entry.name!r} has no "
                    f"loadable checkpoint in {entry.export_dir!r}"
                )
            app = ServeApp(
                registry,
                config=self.config,
                metrics=self.metrics,
                ggipnn_checkpoint=entry.ggipnn_checkpoint,
                mesh=self.mesh,
                fault_injector=self.fault_injector,
                model_name=entry.name,
            )
            self.apps[entry.name] = app
        # every app can address every sibling (and itself by name):
        # /v1/<name>/* delegates through this shared table
        for app in self.apps.values():
            app.catalog_apps = self.apps
        default_app = self.apps[self.spec.default]
        shared_tenants = default_app.tenants
        for app in self.apps.values():
            app.tenants = shared_tenants
        return self

    @property
    def default_app(self):
        return self.apps[self.spec.default]

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(self.apps)

    # -- lifecycle ---------------------------------------------------------

    def start(self, watch_interval_s: float = 0.0) -> "ModelCatalog":
        for app in self.apps.values():
            app.start()
            if watch_interval_s > 0:
                # one watcher per registry entry: swaps never mix
                # models because no watcher can even see another
                # model's export dir
                app.registry.start_watcher(watch_interval_s)
        return self

    def stop(self) -> None:
        for app in self.apps.values():
            try:
                app.registry.stop_watcher()
            except Exception:
                pass
            app.stop()


__all__ = [
    "CATALOG_SCHEMA",
    "RESERVED_MODEL_NAMES",
    "CatalogEntry",
    "CatalogSpec",
    "parse_catalog_spec",
    "load_catalog_spec",
    "ModelAdmission",
    "ModelCatalog",
]
