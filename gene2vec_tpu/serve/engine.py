"""Jitted top-k cosine-similarity kernel with bucketed batch shapes.

One compiled program per (batch-bucket, k-bucket) pair serves every
query: batches pad up to the next power-of-two bucket and ``k`` rounds
up the same way, so the jit cache holds at most
``len(buckets) x len(k-buckets)`` executables no matter what request
mix arrives — graftcheck's ``hlo-cache-stability`` pass compiles this
exact entry point and asserts the cache stops growing once the buckets
are warm (``analysis/passes_hlo.py:build_serve``).

The kernel itself is one matmul plus ``jax.lax.top_k``: queries are
L2-normalized *inside* the traced function (zero rows stay zero), so
cosine scores come out of ``queries @ unitᵀ`` directly.  The matrix may
be row-sharded over a mesh axis (``parallel/sharding.py:row_sharding``)
— per-shard score columns compute locally and only the top-k selection
communicates, a per-query byte budget enforced by the ``serve`` section
of ``analysis/budgets.json``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from gene2vec_tpu.obs.trace import ambient_span


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << max(0, int(n) - 1).bit_length()


def _topk_cosine(unit, queries, k: int, valid: Optional[int]):
    """(B, D) queries x (V, D) unit rows -> (B, k) scores + row indices.
    ``k`` and ``valid`` are static; queries are renormalized so callers
    may pass raw vectors (already-unit gene rows pass through
    unchanged).  ``valid`` masks the zero rows a row-sharded matrix is
    padded with (registry pads V up to the shard multiple) to -inf so
    they can never outrank a real gene's negative cosine."""
    import jax
    import jax.numpy as jnp

    norms = jnp.sqrt(jnp.sum(queries * queries, axis=1, keepdims=True))
    qn = queries / jnp.maximum(norms, 1e-12)
    scores = qn @ unit.T
    if valid is not None and valid < unit.shape[0]:
        pad = jnp.arange(unit.shape[0]) >= valid
        scores = jnp.where(pad[None, :], -jnp.inf, scores)
    return jax.lax.top_k(scores, k)


def _make_topk_sharded(mesh, axis: str):
    """Two-stage distributed top-k over a row-sharded unit matrix:
    each shard computes its local score columns and local top-k, then
    only the (B, P*k) candidate sets gather — 1 KB/query at the
    full-vocab dim-512 geometry vs 98 KB/query for the single-shot
    ``lax.top_k`` the SPMD partitioner lowers (it all-gathers the whole
    (B, V) score matrix).  Exact: any global top-k row is in its own
    shard's top-k, so the candidate union always contains the answer."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    def _topk_cosine_sharded(unit, queries, k: int, valid: Optional[int]):
        import jax
        import jax.numpy as jnp

        norms = jnp.sqrt(jnp.sum(queries * queries, axis=1, keepdims=True))
        qn = queries / jnp.maximum(norms, 1e-12)
        total_rows = unit.shape[0]
        shard_rows = total_rows // mesh.shape[axis]
        lk = min(k, shard_rows)

        def local(unit_shard, qn_rep):
            scores = qn_rep @ unit_shard.T            # (B, V/P), local
            base = jax.lax.axis_index(axis) * shard_rows
            if valid is not None and valid < total_rows:
                rows = base + jnp.arange(shard_rows)
                scores = jnp.where(
                    (rows >= valid)[None, :], -jnp.inf, scores
                )
            ls, li = jax.lax.top_k(scores, lk)        # local candidates
            gi = li + base
            ls_all = jax.lax.all_gather(ls, axis, axis=1, tiled=True)
            gi_all = jax.lax.all_gather(gi, axis, axis=1, tiled=True)
            fs, fi = jax.lax.top_k(ls_all, k)
            return fs, jnp.take_along_axis(gi_all, fi, axis=1)

        return shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis, None), P(None, None)),
            out_specs=(P(None, None), P(None, None)),
            check_rep=False,
        )(unit, qn)

    return _topk_cosine_sharded


class SimilarityEngine:
    """Bucketed batched top-k over a device-resident unit matrix."""

    def __init__(self, max_batch: int = 64, mesh=None, axis: str = "model"):
        import jax

        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = next_pow2(max_batch)
        #: ascending padded batch shapes the jit cache may hold
        self.buckets: Tuple[int, ...] = tuple(
            1 << e for e in range(self.max_batch.bit_length())
        )
        self.mesh = mesh
        self.axis = axis
        kernel = (
            _make_topk_sharded(mesh, axis) if mesh is not None
            else _topk_cosine
        )
        # bound once — a per-call jax.jit(...) wrapper would miss the
        # cache every invocation (the graftcheck jit-recompile-hazard
        # class this engine is budgeted against)
        self._topk_fn = jax.jit(kernel, static_argnums=(2, 3))

    def _cache_size(self) -> Optional[int]:
        size = getattr(self._topk_fn, "_cache_size", None)
        return size() if size is not None else None

    def bucket(self, n: int) -> int:
        """Padded batch size for ``n`` queries."""
        if n > self.max_batch:
            raise ValueError(
                f"{n} queries exceed max_batch={self.max_batch}"
            )
        return next_pow2(max(1, n))

    def k_bucket(self, k: int, vocab_size: int) -> int:
        """Padded (static) k: next power of two, capped at the vocab."""
        return min(next_pow2(max(1, k)), vocab_size)

    def top_k(
        self, unit, queries: np.ndarray, k: int,
        valid: Optional[int] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Top-k cosine matches of ``queries`` (n, D) against ``unit``
        (V, D): (n, k) float32 scores and (n, k) int row indices, already
        cropped back from the padded device shapes.  ``valid`` is the
        real row count when ``unit`` carries sharding pad rows."""
        import jax.numpy as jnp

        queries = np.atleast_2d(np.asarray(queries, dtype=np.float32))
        n = queries.shape[0]
        vocab_size = int(valid if valid is not None else unit.shape[0])
        k = min(int(k), vocab_size)
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        b = self.bucket(n)
        if b != n:
            queries = np.concatenate(
                [queries, np.zeros((b - n, queries.shape[1]), np.float32)]
            )
        kb = self.k_bucket(k, vocab_size)
        valid_arg = (
            int(valid) if valid is not None and valid < int(unit.shape[0])
            else None
        )
        # one span per BATCH (host-side wrapper, never inside the trace);
        # the device->host copies below force the async dispatch, so the
        # span covers real compute, and it nests under serve_compute in
        # the worker thread — cli.obs trace links it to each batch_item
        with ambient_span("engine_topk", batch=b, k=kb):
            scores, idx = self._topk_fn(
                unit, jnp.asarray(queries), kb, valid_arg
            )
            scores = np.asarray(scores)
            idx = np.asarray(idx)
        return scores[:n, :k], idx[:n, :k]

    def similar_batch(
        self,
        model,
        queries: Sequence[np.ndarray],
        k: int,
    ) -> List[List[Tuple[str, float]]]:
        """Neighbor lists for raw query vectors against one
        :class:`~gene2vec_tpu.serve.registry.LoadedModel` snapshot:
        per query, ``k`` (token, cosine) pairs, best first."""
        if not queries:
            return []
        scores, idx = self.top_k(
            model.unit, np.stack(queries), k, valid=len(model)
        )
        tokens = model.tokens
        return [
            [(tokens[int(j)], float(s)) for j, s in zip(row_i, row_s)]
            for row_i, row_s in zip(idx, scores)
        ]
