"""Jitted top-k cosine-similarity kernels with bucketed batch shapes.

One compiled program per (index mode, batch-bucket, k-bucket) serves
every query: batches pad up to the next power-of-two bucket and ``k``
rounds up the same way, so each mode's jit cache holds at most
``len(buckets) x len(k-buckets)`` executables no matter what request
mix arrives — graftcheck's ``hlo-cache-stability`` pass compiles these
exact entry points and asserts each mode's cache stops growing once
the buckets are warm (``analysis/passes_hlo.py:serve_bucket_findings``).

Index modes (:data:`INDEX_MODES`, selected by ``cli.serve --index``):

* ``exact`` (default) — one matmul plus ``jax.lax.top_k`` over the full
  f32 unit matrix, bitwise-identical to the engine before index modes
  existed;
* ``quant`` — int8 (or bf16) compressed full-table scan with an
  exact-rescore tail (``serve/ann.py``);
* ``ivf`` — centroid scan → ``nprobe`` inverted lists → compressed
  candidate scan → exact-rescore tail.

The matrix may be row-sharded over a mesh axis
(``parallel/sharding.py:row_sharding``) — per-shard score columns
compute locally and only the top-k selection communicates
(``two_stage_topk``), a per-query byte budget enforced by the ``serve``
section of ``analysis/budgets.json``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from gene2vec_tpu.obs.trace import ambient_span
from gene2vec_tpu.serve.ann import INDEX_MODES, AnnIndex


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << max(0, int(n) - 1).bit_length()


def _topk_cosine(unit, queries, k: int, valid: Optional[int]):
    """(B, D) queries x (V, D) unit rows -> (B, k) scores + row indices.
    ``k`` and ``valid`` are static; queries are renormalized so callers
    may pass raw vectors (already-unit gene rows pass through
    unchanged).  ``valid`` masks the zero rows a row-sharded matrix is
    padded with (registry pads V up to the shard multiple) to -inf so
    they can never outrank a real gene's negative cosine."""
    import jax
    import jax.numpy as jnp

    norms = jnp.sqrt(jnp.sum(queries * queries, axis=1, keepdims=True))
    qn = queries / jnp.maximum(norms, 1e-12)
    scores = qn @ unit.T
    if valid is not None and valid < unit.shape[0]:
        pad = jnp.arange(unit.shape[0]) >= valid
        scores = jnp.where(pad[None, :], -jnp.inf, scores)
    return jax.lax.top_k(scores, k)


def _make_topk_sharded(mesh, axis: str):
    """Two-stage distributed top-k over a row-sharded unit matrix:
    each shard computes its local score columns and local top-k, then
    only the (B, P*k) candidate sets gather
    (``parallel/sharding.py:two_stage_topk`` — the merge the ANN
    kernels reuse).  Exact: any global top-k row is in its own shard's
    top-k, so the candidate union always contains the answer."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from gene2vec_tpu.parallel.sharding import two_stage_topk

    def _topk_cosine_sharded(unit, queries, k: int, valid: Optional[int]):
        import jax
        import jax.numpy as jnp

        norms = jnp.sqrt(jnp.sum(queries * queries, axis=1, keepdims=True))
        qn = queries / jnp.maximum(norms, 1e-12)
        total_rows = unit.shape[0]
        shard_rows = total_rows // mesh.shape[axis]

        def local(unit_shard, qn_rep):
            scores = qn_rep @ unit_shard.T            # (B, V/P), local
            base = jax.lax.axis_index(axis) * shard_rows
            if valid is not None and valid < total_rows:
                rows = base + jnp.arange(shard_rows)
                scores = jnp.where(
                    (rows >= valid)[None, :], -jnp.inf, scores
                )
            return two_stage_topk(axis, scores, k, base=base)

        return shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis, None), P(None, None)),
            out_specs=(P(None, None), P(None, None)),
            check_rep=False,
        )(unit, qn)

    return _topk_cosine_sharded


class BucketedTopKEngine:
    """Bucketed batched top-k over a device-resident unit matrix, with
    an optional quantized/IVF approximate path (``index=``) whose
    candidates are always exact-rescored before anything is returned.

    ``nprobe`` (IVF lists probed per query) and ``rescore_mult``
    (exact-rescore tail size, ``r = rescore_mult * k``) are the two
    recall/latency knobs; ``--index exact`` bypasses both and is
    bitwise-identical to the pre-ANN engine."""

    def __init__(
        self,
        max_batch: int = 64,
        mesh=None,
        axis: str = "model",
        index: str = "exact",
        nprobe: int = 8,
        rescore_mult: int = 4,
    ):
        import jax

        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if index not in INDEX_MODES:
            raise ValueError(
                f"index must be one of {INDEX_MODES}, got {index!r}"
            )
        if nprobe < 1 or rescore_mult < 1:
            raise ValueError("nprobe and rescore_mult must be >= 1")
        self.max_batch = next_pow2(max_batch)
        #: ascending padded batch shapes the jit cache may hold
        self.buckets: Tuple[int, ...] = tuple(
            1 << e for e in range(self.max_batch.bit_length())
        )
        self.mesh = mesh
        self.axis = axis
        self.index_mode = index
        self.nprobe = int(nprobe)
        self.rescore_mult = int(rescore_mult)
        kernel = (
            _make_topk_sharded(mesh, axis) if mesh is not None
            else _topk_cosine
        )
        # bound once — a per-call jax.jit(...) wrapper would miss the
        # cache every invocation (the graftcheck jit-recompile-hazard
        # class this engine is budgeted against)
        self._topk_fn = jax.jit(kernel, static_argnums=(2, 3))
        # per-mode jitted ANN kernels, bound lazily on first use so an
        # exact-only server never traces them
        self._ann_fns: Dict[str, object] = {}
        #: static kernel costs per profiled bucket (profile_buckets)
        self._kernel_costs: Dict[str, Dict] = {}

    # -- jit-cache accounting ---------------------------------------------

    @staticmethod
    def _fn_cache_size(fn) -> Optional[int]:
        size = getattr(fn, "_cache_size", None)
        return size() if size is not None else None

    def _cache_size(self) -> Optional[int]:
        # kept under its historical name: analysis/passes_hlo.py and the
        # bucket-stability tests read it for the EXACT kernel
        return self._fn_cache_size(self._topk_fn)

    def cache_sizes(self) -> Dict[str, Optional[int]]:
        """Jit-cache entry count per index mode (only modes that have
        actually traced appear beyond ``exact``); ``None`` when this
        jax version exposes no cache introspection."""
        out: Dict[str, Optional[int]] = {"exact": self._cache_size()}
        for mode, fn in self._ann_fns.items():
            out[mode] = self._fn_cache_size(fn)
        return out

    def cache_size(self, mode: Optional[str] = None) -> Optional[int]:
        """Public jit-cache size — one mode, or the sum over all modes
        (``/metrics`` exports this per mode as
        ``engine_jit_cache_entries``)."""
        sizes = self.cache_sizes()
        if mode is not None:
            return sizes.get(mode)
        known = [s for s in sizes.values() if s is not None]
        return sum(known) if known else None

    # -- bucketing ---------------------------------------------------------

    def bucket(self, n: int) -> int:
        """Padded batch size for ``n`` queries."""
        if n > self.max_batch:
            raise ValueError(
                f"{n} queries exceed max_batch={self.max_batch}"
            )
        return next_pow2(max(1, n))

    def k_bucket(self, k: int, vocab_size: int) -> int:
        """Padded (static) k: next power of two, capped at the vocab."""
        return min(next_pow2(max(1, k)), vocab_size)

    def r_bucket(self, kb: int, vocab_size: int) -> int:
        """Padded (static) rescore-tail size: ``rescore_mult * kb``
        rounded to the next power of two, capped at the vocab — a
        function of the k-bucket alone, so the ANN jit caches stay
        bounded by the same bucket grid as the exact kernel."""
        return min(next_pow2(max(kb, self.rescore_mult * kb)), vocab_size)

    def _pad_queries(self, queries: np.ndarray) -> Tuple[np.ndarray, int]:
        queries = np.atleast_2d(np.asarray(queries, dtype=np.float32))
        n = queries.shape[0]
        b = self.bucket(n)
        if b != n:
            queries = np.concatenate(
                [queries, np.zeros((b - n, queries.shape[1]), np.float32)]
            )
        return queries, n

    # -- exact path --------------------------------------------------------

    def top_k(
        self, unit, queries: np.ndarray, k: int,
        valid: Optional[int] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Top-k cosine matches of ``queries`` (n, D) against ``unit``
        (V, D): (n, k) float32 scores and (n, k) int row indices, already
        cropped back from the padded device shapes.  ``valid`` is the
        real row count when ``unit`` carries sharding pad rows."""
        import jax.numpy as jnp

        queries, n = self._pad_queries(queries)
        vocab_size = int(valid if valid is not None else unit.shape[0])
        k = min(int(k), vocab_size)
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        kb = self.k_bucket(k, vocab_size)
        valid_arg = (
            int(valid) if valid is not None and valid < int(unit.shape[0])
            else None
        )
        # one span per BATCH (host-side wrapper, never inside the trace);
        # the device->host copies below force the async dispatch, so the
        # span covers real compute, and it nests under serve_compute in
        # the worker thread — cli.obs trace links it to each batch_item
        with ambient_span("engine_topk", batch=queries.shape[0], k=kb):
            scores, idx = self._topk_fn(
                unit, jnp.asarray(queries), kb, valid_arg
            )
            scores = np.asarray(scores)
            idx = np.asarray(idx)
        return scores[:n, :k], idx[:n, :k]

    # -- approximate path --------------------------------------------------

    def _ann_fn(self, mode: str):
        fn = self._ann_fns.get(mode)
        if fn is None:
            import jax

            from gene2vec_tpu.serve import ann as ann_mod

            if mode == "quant":
                fn = jax.jit(
                    ann_mod.make_quant_kernel(self.mesh, self.axis),
                    static_argnums=(4, 5, 6),
                )
            elif mode == "ivf":
                fn = jax.jit(
                    ann_mod.make_ivf_kernel(self.mesh, self.axis),
                    static_argnums=(6, 7, 8, 9),
                )
            else:
                raise ValueError(f"no ANN kernel for mode {mode!r}")
            self._ann_fns[mode] = fn
        return fn

    def top_k_ann(
        self, index: AnnIndex, unit, queries: np.ndarray, k: int,
        valid: Optional[int] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Approximate top-k through a built :class:`AnnIndex` —
        same contract and padding discipline as :meth:`top_k`, one jit
        cache per index mode."""
        import jax.numpy as jnp

        queries, n = self._pad_queries(queries)
        vocab_size = int(valid if valid is not None else unit.shape[0])
        k = min(int(k), vocab_size)
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        kb = self.k_bucket(k, vocab_size)
        rb = self.r_bucket(kb, vocab_size)
        valid_arg = (
            int(valid) if valid is not None and valid < int(unit.shape[0])
            else None
        )
        qd = jnp.asarray(queries)
        with ambient_span(
            "engine_topk", batch=queries.shape[0], k=kb,
            index=index.mode,
        ):
            if index.mode == "quant":
                scores, idx = self._ann_fn("quant")(
                    index.table_q, index.scale, unit, qd, kb, rb,
                    valid_arg,
                )
            elif index.mode == "ivf":
                # enough probes that the candidate pool can cover kb
                # even on tiny tables; still static per (kb, geometry)
                nprobe = min(self.nprobe, index.n_clusters)
                if index.list_len:
                    need = -(-kb // index.list_len)  # ceil
                    nprobe = min(
                        max(nprobe, need), index.n_clusters
                    )
                scores, idx = self._ann_fn("ivf")(
                    index.centroids, index.lists, index.table_q,
                    index.scale, unit, qd, nprobe, kb, rb, valid_arg,
                )
            else:
                raise ValueError(
                    f"AnnIndex mode {index.mode!r} is not approximate"
                )
            scores = np.asarray(scores)
            idx = np.asarray(idx)
        return scores[:n, :k], idx[:n, :k]

    # -- kernel attribution -------------------------------------------------

    def profile_buckets(
        self,
        unit,
        valid: Optional[int] = None,
        k: int = 16,
        ann_index: Optional[AnnIndex] = None,
        buckets: Optional[Sequence[int]] = None,
    ) -> Dict[str, Dict]:
        """AOT lower+compile the active index mode's kernel for each
        batch bucket, recording per-bucket static costs (FLOPs, bytes
        accessed, peak memory) and lowering/compile wall seconds via
        :mod:`gene2vec_tpu.obs.profiler`.  Warm-time only — called once
        at model load/swap, never on the request path (AOT compiles do
        not populate the jit call cache, so bucket-stability accounting
        is unaffected).  Results accumulate on the engine
        (:meth:`kernel_costs`) keyed ``serve_topk_<mode>/b<bucket>``
        for ``/metrics`` publication."""
        from gene2vec_tpu.obs import profiler as prof

        import jax.numpy as jnp

        mode = self.index_mode
        if mode != "exact" and ann_index is None:
            raise ValueError(
                f"profile_buckets needs an AnnIndex for mode {mode!r}"
            )
        dim = int(unit.shape[1])
        vocab_size = int(valid if valid is not None else unit.shape[0])
        kb = self.k_bucket(max(1, min(int(k), vocab_size)), vocab_size)
        valid_arg = (
            int(valid) if valid is not None and valid < int(unit.shape[0])
            else None
        )
        p = prof.KernelProfiler(run_dir=None, registry=None)
        out: Dict[str, Dict] = {}
        for b in tuple(buckets) if buckets else self.buckets:
            b = int(b)
            q = jnp.zeros((b, dim), jnp.float32)
            if mode == "exact":
                fn, args = self._topk_fn, (unit, q, kb, valid_arg)
            elif mode == "quant":
                rb = self.r_bucket(kb, vocab_size)
                fn = self._ann_fn("quant")
                args = (
                    ann_index.table_q, ann_index.scale, unit, q, kb, rb,
                    valid_arg,
                )
            else:  # ivf
                rb = self.r_bucket(kb, vocab_size)
                nprobe = min(self.nprobe, ann_index.n_clusters)
                fn = self._ann_fn("ivf")
                args = (
                    ann_index.centroids, ann_index.lists,
                    ann_index.table_q, ann_index.scale, unit, q,
                    nprobe, kb, rb, valid_arg,
                )
            name = f"serve_topk_{mode}/b{b}"
            rec = p.attribute(name, fn, args)
            rec["bucket"] = b
            rec["k_bucket"] = kb
            rec["mode"] = mode
            out[name] = rec
        self._kernel_costs.update(out)
        return out

    def kernel_costs(self) -> Dict[str, Dict]:
        """Static costs recorded by :meth:`profile_buckets` so far,
        keyed by kernel name (copies — safe to mutate)."""
        return {k: dict(v) for k, v in self._kernel_costs.items()}

    # -- model-level entry points ------------------------------------------

    def topk_rows(
        self,
        model,
        queries: np.ndarray,
        k: int,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Shard-local top-k with GLOBAL row ids: ``(n, k')`` float32
        scores + int64 rows where ``k' = min(k, len(model))`` and rows
        are offset by the snapshot's ``row_base`` — what a shard
        replica returns for the front door's cross-process merge
        (``parallel/sharding.py:merge_shard_topk``).  Routed through
        the snapshot's ANN index exactly like :meth:`similar_batch`,
        so a sharded fleet keeps the quant/IVF capacity win per
        shard."""
        queries = np.atleast_2d(np.asarray(queries, dtype=np.float32))
        k = min(int(k), len(model))
        index = getattr(model, "ann", None)
        if self.index_mode != "exact" and index is not None:
            scores, idx = self.top_k_ann(
                index, model.unit, queries, k, valid=len(model)
            )
        else:
            scores, idx = self.top_k(
                model.unit, queries, k, valid=len(model)
            )
        rows = idx.astype(np.int64) + int(
            getattr(model, "row_base", 0) or 0
        )
        return scores, rows

    def similar_batch(
        self,
        model,
        queries: Sequence[np.ndarray],
        k: int,
    ) -> List[List[Tuple[str, float]]]:
        """Neighbor lists for raw query vectors against one
        :class:`~gene2vec_tpu.serve.registry.LoadedModel` snapshot:
        per query, ``k`` (token, cosine) pairs, best first.  Routed
        through the snapshot's ANN index when this engine runs an
        approximate mode AND the snapshot carries a matching index;
        otherwise the exact kernel (so ``--index exact``, a model
        loaded without an index, or a mid-rollout mixed fleet all stay
        correct)."""
        if not queries:
            return []
        index = getattr(model, "ann", None)
        if self.index_mode != "exact" and index is not None:
            scores, idx = self.top_k_ann(
                index, model.unit, np.stack(queries), k, valid=len(model)
            )
        else:
            scores, idx = self.top_k(
                model.unit, np.stack(queries), k, valid=len(model)
            )
        tokens = model.tokens
        return [
            [(tokens[int(j)], float(s)) for j, s in zip(row_i, row_s)]
            for row_i, row_s in zip(idx, scores)
        ]


#: historical name — PR-3..9 era callers and tests constructed
#: SimilarityEngine; the bucketed-index engine is the same object
SimilarityEngine = BucketedTopKEngine
