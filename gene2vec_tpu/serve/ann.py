"""Approximate + quantized retrieval: IVF two-stage top-k with int8
scoring and an exact-rescore tail.

The exact engine (``serve/engine.py``) brute-forces cosine over the
whole table every query — the right shape at vocab 24,447, the wrong
one for million-row tables (ROADMAP open item 2).  This module supplies
the two standard compressed-domain index structures (Jégou et al.'s
product-quantization lineage as scaled by FAISS):

* **Quantized scoring table** — int8 symmetric per-row quantization of
  the unit matrix (``q[i] = round(unit[i] / scale[i])``, ``scale[i] =
  max|unit[i]| / 127`` — the same symmetric-scale convention as the
  TPU quantization kernels) with a float32 scale vector, or a bf16
  table where the backend supports it.  Queries quantize in-trace; the
  approximate scan is one int8×int8 matmul accumulated in int32 (1/4
  of the f32 memory traffic), the approximate top-``r`` candidates are
  then **exactly rescored** against the float32 unit rows (``r =
  rescore_mult * k``), so quantization noise costs extra candidates,
  never wrong answers.
* **IVF two-stage index** — k-means centroids built offline over the
  table (cached next to the checkpoint, keyed by table CRC); each row
  lives in exactly one inverted list (capacity-capped; overflow spills
  to the row's next-nearest centroid so one mega-cluster cannot blow
  up every probe).  A query scans the centroids, probes the ``nprobe``
  nearest lists, int8-scores only those candidates, and exact-rescores
  the approximate top-``r`` — bytes touched per query drop from
  ``V*D*4`` to ``C*D*4 + nprobe*L*(D+8) + r*D*4``.

Both index shapes ride the model snapshot
(:class:`~gene2vec_tpu.serve.registry.LoadedModel` carries the index
built for exactly its table), so the registry's atomic hot swap swaps
table and index as ONE reference — a reader can never score against a
mismatched pair.  Sharded variants reuse the two-stage distributed
top-k merge in ``parallel/sharding.py`` (local candidate scan, then a
``(B, P*k)`` gather instead of an all-gather of the score matrix).

The kernels here are jit-TARGETS: ``serve/engine.py`` binds them with
``jax.jit`` once per index mode and buckets batch/k/rescore shapes to
powers of two, so the per-mode jit cache stays bounded
(``analysis/passes_hlo.py`` cycles every mode's buckets and asserts
the cache stops growing).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

INDEX_MODES = ("exact", "quant", "ivf")

#: quantized-table widths build_index accepts
QUANT_DTYPES = ("int8", "bf16")

_EPS = 1e-12


# -- host-side build ---------------------------------------------------------


def table_crc(unit: np.ndarray) -> int:
    """CRC32 of the table bytes — the cache key that pins a built index
    to exactly the table it was built from."""
    return zlib.crc32(np.ascontiguousarray(unit, dtype=np.float32)) & 0xFFFFFFFF


def quantize_rows(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-row int8 quantization: ``(q, scale)`` with
    ``q * scale[:, None] ~= x`` and ``scale = max|row| / 127`` (zero
    rows get an epsilon scale and stay zero)."""
    x = np.asarray(x, dtype=np.float32)
    scale = np.abs(x).max(axis=1) / 127.0
    scale = np.maximum(scale, _EPS).astype(np.float32)
    q = np.clip(np.rint(x / scale[:, None]), -127, 127).astype(np.int8)
    return q, scale


def default_clusters(rows: int) -> int:
    """Heuristic centroid count: ~4·sqrt(V) clamped to [8, 4096] (the
    FAISS guidance band), never more than rows // 4."""
    c = int(4.0 * np.sqrt(max(rows, 1)))
    return max(1, min(max(8, c), 4096, max(1, rows // 4)))


def kmeans_centroids(
    unit: np.ndarray,
    clusters: int,
    iters: int = 8,
    sample: int = 131072,
    seed: int = 0,
) -> np.ndarray:
    """Spherical k-means on (a sample of) the unit rows — returns
    L2-normalized (C, D) float32 centroids.  Sampled training is the
    FAISS convention: centroid quality saturates long before the full
    table is seen, and the full-table assignment pass happens once in
    :func:`build_lists` anyway."""
    rng = np.random.RandomState(seed)
    unit = np.asarray(unit, dtype=np.float32)
    rows = unit.shape[0]
    clusters = min(int(clusters), rows)
    xs = (
        unit[rng.choice(rows, sample, replace=False)]
        if 0 < sample < rows else unit
    )
    cent = xs[rng.choice(xs.shape[0], clusters, replace=False)].copy()
    for _ in range(max(1, int(iters))):
        cn = cent / np.maximum(
            np.linalg.norm(cent, axis=1, keepdims=True), _EPS
        )
        assign = np.argmax(xs @ cn.T, axis=1)
        sums = np.zeros_like(cent)
        np.add.at(sums, assign, xs)
        counts = np.bincount(assign, minlength=clusters).astype(np.float32)
        refreshed = xs[rng.randint(xs.shape[0], size=clusters)]
        cent = np.where(
            (counts == 0)[:, None],  # dead centroid: reseed from data
            refreshed,
            sums / np.maximum(counts, 1.0)[:, None],
        )
    return cent / np.maximum(np.linalg.norm(cent, axis=1, keepdims=True), _EPS)


def build_lists(
    unit: np.ndarray,
    centroids: np.ndarray,
    cap_mult: float = 2.0,
    choices: int = 4,
    chunk: int = 65536,
) -> np.ndarray:
    """(C, L) int32 inverted lists over the table rows, ``-1``-padded.

    Every row lands in exactly ONE list.  ``L`` is a power of two near
    ``cap_mult`` times the mean list size: rows overflowing their
    nearest centroid's capacity spill to the next-nearest centroid with
    space (up to ``choices`` candidates, then any list with room), so a
    pathological mega-cluster bounds the per-probe candidate count
    instead of inflating every query's scan.  The common case (row fits
    its nearest list) places vectorized; only the overflow tail pays a
    per-row pass."""
    unit = np.asarray(unit, dtype=np.float32)
    rows, C = unit.shape[0], centroids.shape[0]
    mean = max(1, rows // max(C, 1))
    cap = 1 << max(0, int(np.ceil(cap_mult * mean)) - 1).bit_length()
    while cap * C < rows:  # capacity must fit every row
        cap *= 2
    choices = min(max(1, int(choices)), C)
    assign = np.empty(rows, np.int64)
    for s in range(0, rows, chunk):
        assign[s : s + chunk] = np.argmax(
            unit[s : s + chunk] @ centroids.T, axis=1
        )
    lists = np.full((C, cap), -1, dtype=np.int32)
    # group rows by cluster; each row's rank within its cluster decides
    # whether it fits under the cap (stable order: low row ids first)
    order = np.argsort(assign, kind="stable")
    a_sorted = assign[order]
    counts = np.bincount(assign, minlength=C)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    within = np.arange(rows) - starts[a_sorted]
    fits = within < cap
    lists[a_sorted[fits], within[fits]] = order[fits].astype(np.int32)
    fill = np.minimum(counts, cap).astype(np.int64)
    overflow = order[~fits]
    if overflow.size:
        # spill each overflow row to its best-scoring centroid with
        # space (next-nearest first), then any list with room
        block = unit[overflow] @ centroids.T
        pref = np.argsort(-block, axis=1)[:, :choices]
        for j, i in enumerate(overflow):
            for c in pref[j]:
                if fill[c] < cap:
                    lists[c, fill[c]] = i
                    fill[c] += 1
                    break
            else:
                c = int(np.argmin(fill))
                lists[c, fill[c]] = i
                fill[c] += 1
    return lists


# -- the index ---------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AnnIndex:
    """One immutable built index, device-resident, pinned to one table.

    ``table_q`` is the quantized scoring table (int8, with ``scale``;
    or bf16, scale unused), row-padded exactly like the model's unit
    matrix when placed sharded.  ``centroids``/``lists`` are the IVF
    stage (``None`` in pure-quant mode).  ``version`` mirrors
    ``LoadedModel.version`` so readers can assert the pair cohere, and
    ``crc`` pins the index to the table bytes it was built from."""

    mode: str
    table_q: "object"            # jax.Array (V, D) int8 | bf16
    scale: "object"              # jax.Array (V,) f32
    centroids: Optional["object"]  # jax.Array (C, D) f32
    lists: Optional["object"]      # jax.Array (C, L) int32
    crc: int
    version: Optional[Tuple[int, int]] = None
    built_from_cache: bool = False
    build_seconds: float = 0.0

    @property
    def n_clusters(self) -> int:
        return 0 if self.centroids is None else int(self.centroids.shape[0])

    @property
    def list_len(self) -> int:
        return 0 if self.lists is None else int(self.lists.shape[1])


def _cache_name(tag: str, clusters: int, crc: int) -> str:
    return f"ivf_{tag}_c{clusters}_crc{crc:08x}.npz"


def _load_centroid_cache(
    path: str, crc: int
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(centroids, lists) from a cache file, or None when the file is
    missing, unreadable, or stamped with a different table CRC (a
    forged/stale file must trigger a rebuild, never a silent reuse)."""
    try:
        with np.load(path) as z:
            meta = json.loads(str(z["meta"]))
            if int(meta.get("crc", -1)) != crc:
                return None
            return (
                np.asarray(z["centroids"], dtype=np.float32),
                np.asarray(z["lists"], dtype=np.int32),
            )
    except Exception:
        # any unreadable cache (missing, truncated zip, rotted pickle,
        # wrong shape) means REBUILD — a bad cache file must never be
        # able to block loading a perfectly good checkpoint
        return None


def build_index(
    unit: np.ndarray,
    mode: str,
    *,
    clusters: Optional[int] = None,
    nprobe_hint: int = 8,
    seed: int = 0,
    quant_dtype: str = "int8",
    cache_dir: Optional[str] = None,
    tag: str = "table",
    version: Optional[Tuple[int, int]] = None,
    sharding=None,
    pad_rows: int = 0,
) -> AnnIndex:
    """Build (or load from cache) the index for one unit matrix.

    ``unit`` is the UNPADDED L2-normalized table — IVF lists only ever
    reference real rows.  ``pad_rows`` appends that many zero rows to
    the quantized table so a sharded placement (``sharding``) divides
    evenly, mirroring the registry's unit-matrix padding.  With
    ``cache_dir``, the k-means centroids + lists are cached under a
    name keyed by ``tag`` and the table CRC: a re-exported checkpoint
    with different bytes under the same name misses the cache and
    rebuilds (and a cache file whose stamped CRC disagrees with the
    table is ignored)."""
    import jax
    import jax.numpy as jnp

    if mode not in ("quant", "ivf"):
        raise ValueError(f"build_index mode must be quant|ivf, got {mode!r}")
    if quant_dtype not in QUANT_DTYPES:
        raise ValueError(
            f"quant_dtype must be one of {QUANT_DTYPES}, got {quant_dtype!r}"
        )
    t0 = time.monotonic()
    unit = np.asarray(unit, dtype=np.float32)
    crc = table_crc(unit)

    cent_np = lists_np = None
    from_cache = False
    if mode == "ivf":
        n_clusters = int(clusters or default_clusters(unit.shape[0]))
        cache_path = None
        if cache_dir:
            cache_path = os.path.join(
                cache_dir, _cache_name(tag, n_clusters, crc)
            )
            cached = _load_centroid_cache(cache_path, crc)
            if cached is not None:
                cent_np, lists_np = cached
                from_cache = True
        if cent_np is None:
            cent_np = kmeans_centroids(unit, n_clusters, seed=seed)
            lists_np = build_lists(unit, cent_np)
            if cache_path is not None:
                from gene2vec_tpu.resilience.snapshot import atomic_savez

                os.makedirs(cache_dir, exist_ok=True)
                atomic_savez(
                    cache_path,
                    centroids=cent_np,
                    lists=lists_np,
                    meta=json.dumps({
                        "crc": crc,
                        "clusters": int(cent_np.shape[0]),
                        "rows": int(unit.shape[0]),
                        "nprobe_hint": int(nprobe_hint),
                    }),
                )

    if quant_dtype == "bf16":
        tq_np = unit.astype(jnp.bfloat16)
        scale_np = np.ones(unit.shape[0], np.float32)
    else:
        tq_np, scale_np = quantize_rows(unit)
    if pad_rows:
        tq_np = np.concatenate(
            [tq_np, np.zeros((pad_rows, tq_np.shape[1]), tq_np.dtype)]
        )
        scale_np = np.concatenate(
            [scale_np, np.full(pad_rows, _EPS, np.float32)]
        )

    if sharding is not None:
        table_q = jax.device_put(jnp.asarray(tq_np), sharding)
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec0 = sharding.spec[0]
        scale = jax.device_put(
            jnp.asarray(scale_np), NamedSharding(sharding.mesh, P(spec0))
        )
    else:
        table_q = jnp.asarray(tq_np)
        scale = jnp.asarray(scale_np)
    centroids = jnp.asarray(cent_np) if cent_np is not None else None
    lists = jnp.asarray(lists_np) if lists_np is not None else None
    table_q.block_until_ready()
    return AnnIndex(
        mode=mode,
        table_q=table_q,
        scale=scale,
        centroids=centroids,
        lists=lists,
        crc=crc,
        version=version,
        built_from_cache=from_cache,
        build_seconds=time.monotonic() - t0,
    )


# -- bytes accounting --------------------------------------------------------


def bytes_per_query(
    mode: str,
    rows: int,
    dim: int,
    *,
    r: int = 0,
    clusters: int = 0,
    list_len: int = 0,
    nprobe: int = 0,
) -> float:
    """Analytic table bytes TOUCHED per single query — the memory-
    traffic side of the scaling story (docs/SERVING.md "Index modes &
    capacity planning" derives these).  exact: the full f32 table.
    quant: the full int8 table + scale vector + the r rescored f32
    rows.  ivf: the f32 centroids + the probed lists' int8 rows (ids +
    scales included) + the r rescored f32 rows."""
    if mode == "exact":
        return float(rows * dim * 4)
    if mode == "quant":
        return float(rows * dim + rows * 4 + r * dim * 4)
    if mode == "ivf":
        probed = min(nprobe * list_len, rows) if list_len else rows
        return float(
            clusters * dim * 4 + probed * (dim + 8) + r * dim * 4
        )
    raise ValueError(f"unknown mode {mode!r}")


# -- jit-target kernels ------------------------------------------------------


def _normalize(queries):
    import jax.numpy as jnp

    norms = jnp.sqrt(jnp.sum(queries * queries, axis=1, keepdims=True))
    return queries / jnp.maximum(norms, _EPS)


def _quantize_queries(qn):
    """In-trace symmetric per-row int8 quantization of the (already
    normalized) queries: (q_int8, scale_f32[:, None])."""
    import jax.numpy as jnp

    qs = jnp.maximum(jnp.max(jnp.abs(qn), axis=1) / 127.0, _EPS)
    qq = jnp.clip(jnp.round(qn / qs[:, None]), -127, 127).astype(jnp.int8)
    return qq, qs[:, None]


def _approx_scores(qn, table_q, scale):
    """(B, V) approximate cosine scores in the table's compressed
    domain: int8×int8 matmul accumulated in int32, rescaled by the
    query/row scales — or a bf16 matmul when the table is bf16."""
    import jax.numpy as jnp
    from jax import lax

    if table_q.dtype == jnp.int8:
        qq, qs = _quantize_queries(qn)
        acc = lax.dot_general(
            qq, table_q,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        return acc.astype(jnp.float32) * qs * scale[None, :]
    return lax.dot_general(
        qn.astype(table_q.dtype), table_q,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _gathered_approx(qn, table_q, scale, pos):
    """Approximate scores for explicit candidates: gather the (B, N)
    candidate rows and batch-contract — only the probed rows' bytes are
    touched, which is the whole point of the IVF stage."""
    import jax.numpy as jnp
    from jax import lax

    rows = table_q[pos]                       # (B, N, D)
    if table_q.dtype == jnp.int8:
        qq, qs = _quantize_queries(qn)
        acc = lax.dot_general(
            qq, rows,
            dimension_numbers=(((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.int32,
        )
        return acc.astype(jnp.float32) * qs * scale[pos]
    return lax.dot_general(
        qn.astype(table_q.dtype), rows,
        dimension_numbers=(((1,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )


def _rescore_topk(qn, unit, ri, approx_ok, k):
    """Exact-rescore tail: gather the candidate unit rows in f32, score
    exactly, and return the final (scores, row ids) top-k.  ``ri`` may
    carry invalid entries (list padding); ``approx_ok`` masks them."""
    import jax.numpy as jnp
    from jax import lax

    pos = jnp.where(approx_ok, ri, 0)
    cand_rows = unit[pos]                                   # (B, r, D) f32
    exact = lax.dot_general(
        qn, cand_rows,
        dimension_numbers=(((1,), (2,)), ((0,), (0,))),
    )
    exact = jnp.where(approx_ok, exact, -jnp.inf)
    fs, fi = lax.top_k(exact, min(k, exact.shape[1]))
    return fs, jnp.take_along_axis(pos, fi, axis=1)


def make_quant_kernel(mesh=None, axis: str = "model"):
    """``fn(table_q, scale, unit, queries, k, r, valid)`` — full-table
    compressed scan, approximate top-``r``, exact rescore, top-``k``.
    With a mesh, the scan runs shard-local over the row-sharded tables
    and only the per-shard exact top-k candidates gather
    (``parallel/sharding.py:two_stage_topk``)."""
    if mesh is None:
        def quant_topk(table_q, scale, unit, queries, k: int, r: int,
                       valid: Optional[int]):
            import jax.numpy as jnp
            from jax import lax

            qn = _normalize(queries)
            approx = _approx_scores(qn, table_q, scale)
            total = table_q.shape[0]
            ok = None
            if valid is not None and valid < total:
                ok = jnp.arange(total)[None, :] < valid
                approx = jnp.where(ok, approx, -jnp.inf)
            _, ri = lax.top_k(approx, min(r, total))
            ok_r = (
                jnp.take_along_axis(
                    jnp.broadcast_to(ok, approx.shape), ri, axis=1
                )
                if ok is not None
                else jnp.ones(ri.shape, bool)
            )
            return _rescore_topk(qn, unit, ri, ok_r, k)

        return quant_topk

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from gene2vec_tpu.parallel.sharding import two_stage_topk

    def quant_topk_sharded(table_q, scale, unit, queries, k: int, r: int,
                           valid: Optional[int]):
        import jax
        import jax.numpy as jnp
        from jax import lax

        total = table_q.shape[0]
        shard_rows = total // mesh.shape[axis]

        def local(tq_s, sc_s, un_s, q_rep):
            qn = _normalize(q_rep)
            approx = _approx_scores(qn, tq_s, sc_s)         # (B, V/P)
            base = jax.lax.axis_index(axis) * shard_rows
            ok = None
            if valid is not None and valid < total:
                ok = (base + jnp.arange(shard_rows))[None, :] < valid
                approx = jnp.where(ok, approx, -jnp.inf)
            rs, li = lax.top_k(approx, min(r, shard_rows))
            ok_r = jnp.isfinite(rs)
            exact, gids = _rescore_topk(
                qn, un_s, li, ok_r, min(r, shard_rows)
            )
            return two_stage_topk(axis, exact, k, ids=gids + base)

        return shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis, None), P(axis), P(axis, None), P(None, None)),
            out_specs=(P(None, None), P(None, None)),
            check_rep=False,
        )(table_q, scale, unit, queries)

    return quant_topk_sharded


def make_ivf_kernel(mesh=None, axis: str = "model"):
    """``fn(centroids, lists, table_q, scale, unit, queries, nprobe, k,
    r, valid)`` — centroid scan → probe ``nprobe`` lists → compressed
    candidate scan → approximate top-``r`` → exact rescore → top-``k``.
    Lists hold only real row ids (< valid) so no pad masking is needed
    beyond the ``-1`` list padding.  The sharded variant replicates
    centroids/lists, scans each shard's own candidate rows, and merges
    via the two-stage distributed top-k."""
    if mesh is None:
        def ivf_topk(centroids, lists, table_q, scale, unit, queries,
                     nprobe: int, k: int, r: int, valid: Optional[int]):
            import jax.numpy as jnp
            from jax import lax

            qn = _normalize(queries)
            cs = qn @ centroids.T                           # (B, C)
            _, ci = lax.top_k(cs, nprobe)                   # (B, nprobe)
            cand = lists[ci].reshape(qn.shape[0], -1)       # (B, N)
            ok = cand >= 0
            if valid is not None and valid < table_q.shape[0]:
                # registry-built lists never reference pad rows, but
                # the top_k contract lets any caller restrict to a
                # row prefix — honor it like the exact/quant kernels
                ok &= cand < valid
            pos = jnp.where(ok, cand, 0)
            approx = _gathered_approx(qn, table_q, scale, pos)
            approx = jnp.where(ok, approx, -jnp.inf)
            r_eff = min(r, approx.shape[1])
            rs, rpos = lax.top_k(approx, r_eff)
            ri = jnp.take_along_axis(pos, rpos, axis=1)
            return _rescore_topk(qn, unit, ri, jnp.isfinite(rs), k)

        return ivf_topk

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from gene2vec_tpu.parallel.sharding import two_stage_topk

    def ivf_topk_sharded(centroids, lists, table_q, scale, unit, queries,
                         nprobe: int, k: int, r: int,
                         valid: Optional[int]):
        import jax
        import jax.numpy as jnp
        from jax import lax

        total = table_q.shape[0]
        shard_rows = total // mesh.shape[axis]

        def local(cent, lst, tq_s, sc_s, un_s, q_rep):
            qn = _normalize(q_rep)
            cs = qn @ cent.T
            _, ci = lax.top_k(cs, nprobe)
            cand = lst[ci].reshape(qn.shape[0], -1)         # global ids
            base = jax.lax.axis_index(axis) * shard_rows
            mine = (cand >= base) & (cand < base + shard_rows)
            if valid is not None and valid < total:
                mine &= cand < valid
            pos = jnp.where(mine, cand - base, 0)
            approx = _gathered_approx(qn, tq_s, sc_s, pos)
            approx = jnp.where(mine, approx, -jnp.inf)
            r_eff = min(r, approx.shape[1])
            rs, rpos = lax.top_k(approx, r_eff)
            lpos = jnp.take_along_axis(pos, rpos, axis=1)
            exact, sel = _rescore_topk(
                qn, un_s, lpos, jnp.isfinite(rs), r_eff
            )
            return two_stage_topk(axis, exact, k, ids=sel + base)

        return shard_map(
            local,
            mesh=mesh,
            in_specs=(
                P(None, None), P(None, None), P(axis, None), P(axis),
                P(axis, None), P(None, None),
            ),
            out_specs=(P(None, None), P(None, None)),
            check_rep=False,
        )(centroids, lists, table_q, scale, unit, queries)

    return ivf_topk_sharded


# -- numpy oracle (tests / bench) --------------------------------------------


def exact_oracle(
    unit: np.ndarray, queries: np.ndarray, k: int, chunk: int = 128
) -> np.ndarray:
    """(Q, k) row indices of the exact cosine top-k — the recall
    reference the bench and the recall harness score against."""
    unit = np.asarray(unit, dtype=np.float32)
    qn = np.asarray(queries, dtype=np.float32)
    qn = qn / np.maximum(np.linalg.norm(qn, axis=1, keepdims=True), _EPS)
    out = np.empty((qn.shape[0], k), np.int64)
    for s in range(0, qn.shape[0], chunk):
        scores = qn[s : s + chunk] @ unit.T
        part = np.argpartition(-scores, k - 1, axis=1)[:, :k]
        order = np.argsort(
            -np.take_along_axis(scores, part, axis=1), axis=1
        )
        out[s : s + chunk] = np.take_along_axis(part, order, axis=1)
    return out


def recall_at_k(found_idx: np.ndarray, oracle_idx: np.ndarray) -> float:
    """Mean fraction of oracle rows recovered, per query."""
    hits = 0
    for f, o in zip(found_idx, oracle_idx):
        hits += len(set(int(i) for i in f) & set(int(i) for i in o))
    return hits / float(oracle_idx.size)


def index_stats(index: AnnIndex) -> Dict:
    """JSON-ready facts about one built index (bench + /healthz use)."""
    return {
        "mode": index.mode,
        "dtype": str(np.dtype("int8"))
        if str(index.table_q.dtype) == "int8" else str(index.table_q.dtype),
        "rows": int(index.table_q.shape[0]),
        "clusters": index.n_clusters,
        "list_len": index.list_len,
        "crc": index.crc,
        "built_from_cache": index.built_from_cache,
        "build_seconds": round(index.build_seconds, 3),
    }
