"""SLO-driven fleet autoscaling: hysteresis policy + elastic controller.

A fixed-N fleet over-provisions at night and sheds load during ramps.
This module closes the loop the alerting plane opened: the
:class:`~gene2vec_tpu.obs.aggregate.FleetAggregator` already computes
the SLO signals autoscaling needs on every scrape tick
(``fleet_queue_depth``, ``fleet_rejection_rate``, the raw
``fleet_ok``/``fleet_responses`` counter pair, per-route p99, and the
``_fresh_targets`` staleness facts) — the scaler consumes that same
snapshot and adjusts replica count between ``min_replicas`` and
``max_replicas``.

Two pieces, deliberately separated:

* :class:`AutoscalePolicy` — a **pure state machine**: one
  ``observe(snapshot, now, current) -> ScaleDecision`` call per scrape
  tick, no threads, no I/O, injectable clock values.  Asymmetric
  hysteresis is the core: a breach (queue depth per replica, windowed
  rejection rate, windowed availability burn, or route p99 over their
  ``up_*`` thresholds) must hold for ``up_after_ticks`` consecutive
  ticks to scale up (fast — a ramp is an emergency), while scale-down
  requires ``down_after_ticks`` consecutive ticks **fully clear** of
  the (lower) ``down_*`` thresholds (slow — idle capacity is cheap,
  flapping is not).  The middle band between the two threshold sets
  resets *both* streaks: ambiguous signals freeze the fleet where it
  is.  A ``cooldown_s`` window after every action suppresses the next
  one, and a **stale snapshot** (fewer than ``min_fresh_targets``
  fresh scrape targets) advances neither streak — frozen telemetry
  must neither grow nor shrink the fleet.  Rate signals are **windowed
  deltas** over the raw counters, never lifetime ratios: one historic
  rejection burst must not pin the cumulative rate above the clear
  threshold forever.

* :class:`ElasticController` — the impure shell: registered as an
  aggregator observer, it feeds the policy each tick and applies
  decisions on its own thread.  Scale-up spawns a fresh replica
  through the supervisor and waits for readiness.  Scale-down is
  **zero-drop by construction**: the victim leaves the rotation first
  (``FleetSupervisor.begin_drain`` — the proxy's target callable stops
  offering it on the very next pick), then the controller waits for
  the front door's per-replica in-flight count
  (:class:`~gene2vec_tpu.serve.client.InFlightTracker`) to hold at
  zero, and only then does the supervisor SIGTERM the child — the same
  terminate path ``FleetSupervisor.stop`` has always used.  A drain
  that never settles times out (counted) rather than wedging the
  control loop.

``python -m gene2vec_tpu.cli.fleet --max-replicas N`` turns the loop
on; the chaos drill's ``autoscale`` phase (ramp -> scale-up within
budgeted ticks; ramp-down -> zero-drop scale-down; steady state ->
zero actions) stamps ``BENCH_AUTOSCALE_r*.json``, gated by
``analysis/passes_autoscale.py`` against budgets.json ``autoscale``.
"""

from __future__ import annotations

import dataclasses
import sys
import threading
import time
from typing import Dict, Optional, Tuple

__all__ = [
    "AutoscaleConfig",
    "AutoscalePolicy",
    "ElasticController",
    "PoolAutoscalePolicy",
    "PoolElasticController",
    "ScaleDecision",
    "ShardAutoscalePolicy",
    "ShardElasticController",
    "pool_snapshot",
    "shard_snapshot",
]


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Scaler policy knobs (cli/fleet.py flags).  All ``*_ticks``
    values count aggregator scrape ticks — the policy's only clock is
    the snapshot cadence."""

    min_replicas: int = 1
    max_replicas: int = 4
    # -- breach thresholds: scale up when ANY signal exceeds its up_*
    # bound for up_after_ticks consecutive ticks ------------------------
    up_queue_per_replica: float = 8.0
    up_rejection_rate: float = 0.02
    up_availability: float = 0.95     # windowed Δok/Δresponses below this
    up_p99_s: float = 0.0             # 0 disables the p99 signal
    p99_route: str = "/v1/similar"
    up_after_ticks: int = 2
    # -- clear thresholds: scale down only when EVERY signal sits below
    # its down_* bound for down_after_ticks consecutive ticks — the gap
    # between up_* and down_* is the hysteresis band ---------------------
    down_queue_per_replica: float = 1.0
    down_rejection_rate: float = 0.0
    down_availability: float = 0.999
    down_p99_s: float = 0.0
    down_after_ticks: int = 30
    # -- damping ---------------------------------------------------------
    cooldown_s: float = 10.0          # no two actions closer than this
    min_fresh_targets: int = 1        # stale snapshot -> hold
    min_window_responses: float = 1.0  # evidence floor for rate deltas


@dataclasses.dataclass(frozen=True)
class ScaleDecision:
    """One tick's verdict.  ``target`` is the replica count the fleet
    should move to (== ``current`` on hold).  ``shard`` scopes the
    action to one shard's replica pool, ``model`` to one catalog
    model's pool (both None = the whole fleet — the single pool
    model); together they name a (model, shard) pool."""

    action: str                # "up" | "down" | "hold"
    target: int
    reason: str
    breach_ticks: int = 0
    clear_ticks: int = 0
    shard: Optional[int] = None
    model: Optional[str] = None


def _route_key(route: str) -> str:
    return f"fleet_route_p99_seconds{{route={route}}}"


class AutoscalePolicy:
    """The pure hysteresis state machine.  One instance per fleet;
    :meth:`observe` is called with the aggregator's flat snapshot once
    per scrape tick and never blocks, sleeps, or spawns."""

    def __init__(self, config: AutoscaleConfig):
        if config.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if config.max_replicas < config.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        self.config = config
        self._breach_ticks = 0
        self._clear_ticks = 0
        self._last_action_at: Optional[float] = None
        # counter baselines for the windowed rate signals; None until
        # the first snapshot seeds them (the first tick can never act)
        self._base: Optional[Dict[str, float]] = None

    # -- controller hooks --------------------------------------------------

    def note_action_done(self, now: float) -> None:
        """Re-arm the cooldown from the moment an action COMPLETED: a
        scale-up pays a full replica startup (tens of seconds of jax
        import), and cooling down from the decision instant would let
        the still-breaching window trigger a second spawn mid-first."""
        self._last_action_at = now

    # -- signal extraction -------------------------------------------------

    def _window(self, snapshot: Dict[str, float]) -> Dict[str, Optional[float]]:
        """Per-tick deltas of the monotone counters -> windowed rates.
        Returns None for a rate with no evidence this window.

        Deliberate shedding is NOT load the fleet should chase:
        tenant-quota rejections (``fleet_quota_rejected``, the
        tenant-labeled slice of the rejection counter) are subtracted
        from the rejection signal — an abusive tenant saturating its
        own bucket must not buy itself N x quota by scaling the fleet
        — and 429 responses (``fleet_throttled``) leave the
        availability-burn window entirely, since backpressure is a
        policy outcome, not a failure.  Queue-full (capacity)
        rejections still drive scale-up through the rejection rate."""
        cur = {
            k: float(snapshot.get(k, 0.0))
            for k in ("fleet_requests", "fleet_rejected",
                      "fleet_quota_rejected", "fleet_ok",
                      "fleet_responses", "fleet_throttled")
        }
        base, self._base = self._base, cur
        if base is None:
            return {"rejection": None, "availability": None}
        d = {k: max(0.0, cur[k] - base[k]) for k in cur}
        floor = self.config.min_window_responses
        capacity_rejected = max(
            0.0, d["fleet_rejected"] - d["fleet_quota_rejected"]
        )
        rejection = (
            capacity_rejected / d["fleet_requests"]
            if d["fleet_requests"] >= floor else None
        )
        answered = d["fleet_responses"] - d["fleet_throttled"]
        availability = (
            min(1.0, d["fleet_ok"] / answered)
            if answered >= floor else None
        )
        return {"rejection": rejection, "availability": availability}

    def _classify(self, snapshot: Dict[str, float],
                  current: int) -> "tuple[bool, bool, str]":
        """(breach, clear, detail) for one snapshot.  ``breach`` = any
        signal over its up_* bound; ``clear`` = every measurable signal
        under its down_* bound (quiet windows with no traffic count as
        clear — that is exactly when capacity should shrink)."""
        cfg = self.config
        rates = self._window(snapshot)
        queue_per = (
            float(snapshot.get("fleet_queue_depth", 0.0)) / max(current, 1)
        )
        p99 = snapshot.get(_route_key(cfg.p99_route))
        breaches = []
        if queue_per > cfg.up_queue_per_replica:
            breaches.append(f"queue/replica {queue_per:.1f}")
        r = rates["rejection"]
        if r is not None and r > cfg.up_rejection_rate:
            breaches.append(f"rejection {r:.3f}")
        a = rates["availability"]
        if a is not None and a < cfg.up_availability:
            breaches.append(f"availability {a:.3f}")
        if cfg.up_p99_s > 0 and p99 is not None and p99 > cfg.up_p99_s:
            breaches.append(f"p99 {p99:.3f}s")
        if breaches:
            return True, False, "+".join(breaches)
        clear = queue_per <= cfg.down_queue_per_replica
        if r is not None and r > cfg.down_rejection_rate:
            clear = False
        if a is not None and a < cfg.down_availability:
            clear = False
        if cfg.down_p99_s > 0 and p99 is not None and p99 > cfg.down_p99_s:
            clear = False
        return False, clear, "clear" if clear else "between thresholds"

    # -- the tick ----------------------------------------------------------

    def observe(self, snapshot: Dict[str, float], now: float,
                current: int) -> ScaleDecision:
        cfg = self.config

        def hold(reason: str) -> ScaleDecision:
            return ScaleDecision(
                "hold", current, reason,
                breach_ticks=self._breach_ticks,
                clear_ticks=self._clear_ticks,
            )

        fresh = snapshot.get("_fresh_targets")
        if fresh is not None and fresh < cfg.min_fresh_targets:
            # frozen telemetry: neither streak may advance — acting on
            # a stale snapshot would scale on data from before the
            # outage that froze it
            return hold("stale snapshot (fresh targets "
                        f"{int(fresh)} < {cfg.min_fresh_targets})")
        if self._base is None:
            # the very first snapshot only seeds the counter baselines:
            # no windowed rate exists yet, so neither streak advances
            self._window(snapshot)
            return hold("seeding counter baselines")
        breach, clear, detail = self._classify(snapshot, current)
        if breach:
            self._breach_ticks += 1
            self._clear_ticks = 0
        elif clear:
            self._clear_ticks += 1
            self._breach_ticks = 0
        else:
            # the hysteresis band: ambiguous — freeze both streaks
            self._breach_ticks = 0
            self._clear_ticks = 0
        in_cooldown = (
            self._last_action_at is not None
            and now - self._last_action_at < cfg.cooldown_s
        )
        if breach and self._breach_ticks >= cfg.up_after_ticks:
            if current >= cfg.max_replicas:
                return hold(f"breach ({detail}) but at max_replicas "
                            f"{cfg.max_replicas}")
            if in_cooldown:
                return hold(f"breach ({detail}) held by cooldown")
            decision = ScaleDecision(
                "up", min(cfg.max_replicas, current + 1),
                f"breach for {self._breach_ticks} ticks: {detail}",
                breach_ticks=self._breach_ticks,
            )
            self._breach_ticks = 0
            self._clear_ticks = 0
            self._last_action_at = now
            return decision
        if clear and self._clear_ticks >= cfg.down_after_ticks:
            if current <= cfg.min_replicas:
                return hold(f"clear but at min_replicas "
                            f"{cfg.min_replicas}")
            if in_cooldown:
                return hold("clear window complete but held by cooldown")
            decision = ScaleDecision(
                "down", max(cfg.min_replicas, current - 1),
                f"clear for {self._clear_ticks} ticks",
                clear_ticks=self._clear_ticks,
            )
            self._breach_ticks = 0
            self._clear_ticks = 0
            self._last_action_at = now
            return decision
        return hold(detail)


class ElasticController:
    """Applies :class:`AutoscalePolicy` decisions to a live fleet.

    Registered as a :class:`~gene2vec_tpu.obs.aggregate.FleetAggregator`
    observer — :meth:`observe` runs on the aggregator's scrape thread
    and must stay cheap, so actions run on their own daemon thread and
    at most ONE action is in flight at a time (ticks during an action
    are skipped outright: a 20-second replica spawn must not queue up
    twenty more decisions behind it)."""

    def __init__(
        self,
        supervisor,
        proxy,
        config: AutoscaleConfig,
        metrics=None,
        policy: Optional[AutoscalePolicy] = None,
        drain_timeout_s: float = 30.0,
        drain_poll_s: float = 0.05,
        drain_settle_polls: int = 3,
    ):
        self.supervisor = supervisor
        self.proxy = proxy
        self.config = config
        self.metrics = metrics
        self.policy = policy if policy is not None else (
            AutoscalePolicy(config)
        )
        self.drain_timeout_s = drain_timeout_s
        self.drain_poll_s = drain_poll_s
        # consecutive zero-in-flight polls required before the victim
        # is terminated: closes the pick-to-dispatch race window where
        # the client chose the victim just before it left the rotation
        self.drain_settle_polls = max(1, int(drain_settle_polls))
        self._lock = threading.Lock()
        self._busy = False
        self._stopped = False
        if metrics is not None:
            # pre-register the action counters at 0 so /metrics shows
            # them from the first scrape and the drill's steady-state
            # delta math never reads "absent" as "changed"
            metrics.counter("fleet_scale_up_total")
            metrics.counter("fleet_scale_down_total")
            metrics.gauge("fleet_replicas_active").set(
                supervisor.active_count()
            )

    # -- metrics -----------------------------------------------------------

    def _count(self, name: str) -> None:
        if self.metrics is not None:
            self.metrics.counter(name).inc()

    def _publish(self, decision: ScaleDecision, current: int) -> None:
        if self.metrics is None:
            return
        self.metrics.gauge("fleet_replicas_active").set(current)
        self.metrics.gauge("fleet_replicas_target").set(decision.target)
        self.metrics.gauge("fleet_scale_breach_ticks").set(
            decision.breach_ticks
        )
        self.metrics.gauge("fleet_scale_clear_ticks").set(
            decision.clear_ticks
        )

    # -- aggregator observer ----------------------------------------------

    def _decide(
        self, snapshot: Dict[str, float], now: float
    ) -> Tuple[ScaleDecision, int]:
        """Policy seam: this tick's decision + the capacity it was
        made against.  The shard subclass swaps in the per-shard grid
        here; everything else — the one-action gate, counters, drain
        path — is shared."""
        current = self.supervisor.active_count()
        decision = self.policy.observe(
            snapshot, now=now, current=current
        )
        return decision, current

    def _describe(self, decision: ScaleDecision) -> str:
        return (
            f"{decision.action} -> {decision.target} replicas "
            f"({decision.reason})"
        )

    def observe(self, snapshot: Dict[str, float], wall=None) -> None:
        del wall  # the policy runs on the monotonic clock
        with self._lock:
            if self._busy or self._stopped:
                return
        decision, current = self._decide(snapshot, time.monotonic())
        self._publish(decision, current)
        if decision.action == "hold":
            return
        with self._lock:
            if self._busy or self._stopped:
                return
            self._busy = True
        # counted at DECISION time: scale_up_detection_ticks in the
        # drill measures how fast the loop NOTICED, not how fast a jax
        # import finishes
        self._count(f"fleet_scale_{decision.action}_total")
        print(f"autoscale: {self._describe(decision)}", file=sys.stderr)
        threading.Thread(
            target=self._apply, args=(decision,),
            name=f"fleet-scale-{decision.action}", daemon=True,
        ).start()

    def stop(self) -> None:
        with self._lock:
            self._stopped = True

    # -- actions (their own thread) ----------------------------------------

    def _apply(self, decision: ScaleDecision) -> None:
        try:
            if decision.action == "up":
                self._scale_up(decision.shard, decision.model)
            else:
                self._scale_down(decision.shard, decision.model)
        except Exception as e:
            self._count("fleet_scale_failures_total")
            print(f"autoscale: {decision.action} failed: {e!r}",
                  file=sys.stderr)
        finally:
            # cooldown restarts from action COMPLETION — a long spawn
            # must not be immediately followed by another
            self.policy.note_action_done(time.monotonic())
            with self._lock:
                self._busy = False
            if self.metrics is not None:
                self.metrics.gauge("fleet_replicas_active").set(
                    self.supervisor.active_count()
                )

    def _scale_up(self, shard: Optional[int] = None,
                  model: Optional[str] = None) -> None:
        # keywords passed only when set: single-pool supervisors (and
        # the test fakes) keep their no-arg signature
        kwargs = {}
        if shard is not None:
            kwargs["shard"] = shard
        if model is not None:
            kwargs["model"] = model
        replica = self.supervisor.scale_up(**kwargs)
        # hold the action slot until the new replica actually serves
        # (or demonstrably cannot): the breach persists while it warms
        # up, and releasing early would spawn a second replica for the
        # same breach
        deadline = (
            time.monotonic() + self.supervisor.config.contract_timeout_s
        )
        from gene2vec_tpu.serve.fleet import ReplicaState

        while time.monotonic() < deadline:
            with self._lock:
                if self._stopped:
                    return
            if replica.state in (ReplicaState.UP, ReplicaState.FAILED):
                break
            if not replica.alive and not replica.spawning:
                break
            time.sleep(0.1)

    def _scale_down(self, shard: Optional[int] = None,
                    model: Optional[str] = None) -> None:
        kwargs = {}
        if shard is not None:
            kwargs["shard"] = shard
        if model is not None:
            kwargs["model"] = model
        victim = self.supervisor.pick_drain_victim(**kwargs)
        if victim is None:
            return
        self.supervisor.begin_drain(victim)
        url = victim.url
        tracker = getattr(self.proxy, "inflight", None)
        if tracker is not None and url is not None:
            deadline = time.monotonic() + self.drain_timeout_s
            settled = 0
            while time.monotonic() < deadline:
                with self._lock:
                    if self._stopped:
                        break
                if tracker.count(url) == 0:
                    settled += 1
                    if settled >= self.drain_settle_polls:
                        break
                else:
                    settled = 0
                time.sleep(self.drain_poll_s)
            else:
                self._count("fleet_drain_timeouts_total")
                print(
                    f"autoscale: drain of {url} timed out after "
                    f"{self.drain_timeout_s:g}s with "
                    f"{tracker.count(url)} request(s) in flight",
                    file=sys.stderr,
                )
        self.supervisor.finish_drain(victim)


# -- the (model, shard) pool model -------------------------------------------
#
# A fleet partitions its slots into POOLS — per row shard
# (--shard-by-rows), per catalog model (--catalog), or in principle
# both — and each pool scales independently inside
# [min_replicas, max_replicas].  The pool key is a (model, shard)
# tuple with the unused axis None; `pool_snapshot` projects one pool's
# signals out of the aggregator's flat snapshot, and
# `PoolAutoscalePolicy` runs one plain AutoscalePolicy per pool with
# hottest-signal-wins arbitration.  The shard classes below are the
# pre-catalog API, now thin delegations.


def _pool_queue_key(model: Optional[str], shard: Optional[int]) -> str:
    """The aggregator gauge one pool's queue pressure lives under:
    ``fleet_model_queue_depth{model=}`` for a model pool,
    ``fleet_shard_queue_depth{shard=}`` for a shard pool (a hybrid
    pool reads the model axis — the finer partition in practice)."""
    if model is not None:
        return f"fleet_model_queue_depth{{model={model}}}"
    return f"fleet_shard_queue_depth{{shard={shard}}}"


def _pool_desc(model: Optional[str], shard: Optional[int]) -> str:
    parts = []
    if model is not None:
        parts.append(f"model {model}")
    if shard is not None:
        parts.append(f"shard {shard}")
    return " ".join(parts) if parts else "fleet"


def pool_snapshot(snapshot: Dict[str, float], model: Optional[str],
                  shard: Optional[int],
                  p99_route: str) -> Dict[str, float]:
    """Project one (model, shard) pool's signals out of the
    aggregator's flat snapshot into the key names
    :class:`AutoscalePolicy` reads — the per-pool policies are plain
    AutoscalePolicy instances evaluating their own pool's queue depth
    (and per-shard scatter p99, when the pool has a shard axis).  The
    fleet-wide counter pairs are deliberately ABSENT: rejection/
    availability rates then carry no evidence (None) and neither
    breach nor block a clear, so a pool scales on ITS load, not on
    another pool's burn."""
    sub: Dict[str, float] = {}
    fresh = snapshot.get("_fresh_targets")
    if fresh is not None:
        sub["_fresh_targets"] = fresh
    q = snapshot.get(_pool_queue_key(model, shard))
    if q is None:
        # no queue evidence from ANY of this pool's replicas this
        # round (every scrape missed — the aggregator only publishes
        # the key from successful scrapes): the fleet-wide freshness
        # guard can't see a single dark pool, so zero THIS pool's
        # freshness — the policy must HOLD, not read "idle" and drain
        # capacity from exactly the pool it is blind to
        sub["_fresh_targets"] = 0.0
        sub["fleet_queue_depth"] = 0.0
    else:
        sub["fleet_queue_depth"] = float(q)
    if shard is not None:
        p99 = snapshot.get(f"fleet_shard_p99_seconds{{shard={shard}}}")
        if p99 is not None:
            sub[_route_key(p99_route)] = float(p99)
    return sub


def shard_snapshot(snapshot: Dict[str, float], shard: int,
                   p99_route: str) -> Dict[str, float]:
    """One shard pool's projection — ``pool_snapshot`` with no model
    axis (the pre-catalog name, kept for callers and tests)."""
    return pool_snapshot(snapshot, None, shard, p99_route)


class PoolAutoscalePolicy:
    """Per-pool model: one :class:`AutoscalePolicy` per (model, shard)
    pool, each fed its own pool's signals, deciding that pool's
    replica count inside [min_replicas, max_replicas].  Pure like the
    underlying policies; one :meth:`observe` per scrape tick returns
    at most ONE non-hold decision (scale-ups first, hottest-queue
    pool wins ties) because the controller applies one action at a
    time anyway — a pool whose decision lost the tie re-breaches and
    wins a later tick (its breach window re-accumulates under the
    fleet-wide cooldown, the same anti-flap the single pool has)."""

    def __init__(self, config: AutoscaleConfig, pools):
        pools = [
            (m, None if s is None else int(s)) for m, s in pools
        ]
        if not pools:
            raise ValueError("need at least one pool")
        if len(set(pools)) != len(pools):
            raise ValueError(f"duplicate pool keys in {pools}")
        self.config = config
        self.pools = pools
        self.pool_policies = {
            p: AutoscalePolicy(config) for p in pools
        }
        #: per-pool policy table; the shard subclass re-keys this view
        #: by shard index (the pre-catalog API) over the SAME instances
        self.policies = self.pool_policies

    def note_action_done(self, now: float) -> None:
        # cooldown is FLEET-wide: every pool re-arms, or two pools
        # could interleave actions faster than any one pool allows
        for p in self.pool_policies.values():
            p.note_action_done(now)

    def observe(
        self,
        snapshot: Dict[str, float],
        now: float,
        current_of: Dict[Tuple[Optional[str], Optional[int]], int],
    ) -> ScaleDecision:
        decisions: Dict[tuple, ScaleDecision] = {}
        for pool, policy in self.pool_policies.items():
            model, shard = pool
            sub = pool_snapshot(
                snapshot, model, shard, self.config.p99_route
            )
            decisions[pool] = policy.observe(
                sub, now=now, current=current_of.get(pool, 0)
            )

        def queue_of(pool: tuple) -> float:
            return float(snapshot.get(_pool_queue_key(*pool), 0.0))

        def tag(pool: tuple, d: ScaleDecision) -> ScaleDecision:
            return dataclasses.replace(
                d, model=pool[0], shard=pool[1],
                reason=f"{_pool_desc(*pool)}: {d.reason}",
            )

        for action in ("up", "down"):
            picked = [
                p for p, d in decisions.items() if d.action == action
            ]
            if picked:
                p = max(picked, key=queue_of) if action == "up" else (
                    min(picked, key=queue_of)
                )
                return tag(p, decisions[p])
        # all holds: surface the busiest pool's reason for telemetry
        p = max(decisions, key=queue_of)
        return tag(p, decisions[p])


class ShardAutoscalePolicy(PoolAutoscalePolicy):
    """Per-shard pool model — :class:`PoolAutoscalePolicy` over the
    shard-only pool keys, keeping the pre-catalog shard-keyed
    ``observe(current_of: {shard: count})`` signature."""

    def __init__(self, config: AutoscaleConfig, num_shards: int):
        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        super().__init__(
            config, [(None, s) for s in range(int(num_shards))]
        )
        self.num_shards = int(num_shards)
        self.policies = {
            s: self.pool_policies[(None, s)]
            for s in range(self.num_shards)
        }

    def observe(
        self,
        snapshot: Dict[str, float],
        now: float,
        current_of: Dict[int, int],
    ) -> ScaleDecision:
        return super().observe(
            snapshot, now=now,
            current_of={
                (None, s): n for s, n in current_of.items()
            },
        )


class ShardElasticController(ElasticController):
    """The elastic controller for a replicated-shard fleet: the same
    one-action-at-a-time shell, drain path, and metrics, driving a
    :class:`ShardAutoscalePolicy` — scale-up spawns a SIBLING into the
    hot shard's replica group (``FleetSupervisor.scale_up(shard=)``),
    scale-down drains the newest sibling of an idle shard and never
    its last UP replica (``pick_drain_victim(shard=)`` — the shard's
    rows must stay served)."""

    def __init__(self, supervisor, proxy, config: AutoscaleConfig,
                 num_shards: int, metrics=None, **kw):
        super().__init__(
            supervisor, proxy, config, metrics=metrics,
            # the grid IS the controller's policy: the base class's
            # note_action_done in _apply's finally re-arms every pool's
            # cooldown (ShardAutoscalePolicy fans it out), and _apply
            # already threads decision.shard through scale_up/drain
            policy=ShardAutoscalePolicy(config, num_shards),
            **kw,
        )
        self.shard_policy = self.policy
        self.num_shards = int(num_shards)
        # the deciding shard's pool size at _decide time, consumed by
        # _publish in the same tick (observe is single-threaded per
        # aggregator tick) to translate the pool target fleet-wide
        self._decision_pool = 0

    def _decide(
        self, snapshot: Dict[str, float], now: float
    ) -> Tuple[ScaleDecision, int]:
        current_of = {
            s: self.supervisor.active_count(shard=s)
            for s in range(self.num_shards)
        }
        decision = self.shard_policy.observe(
            snapshot, now=now, current_of=current_of,
        )
        self._decision_pool = current_of.get(decision.shard, 0)
        if self.metrics is not None:
            # every pool, every tick — publishing only the deciding
            # shard would freeze the other pools' gauges at whatever
            # size they had the last time they happened to decide
            for s, n in current_of.items():
                self.metrics.gauge(
                    "fleet_shard_replicas_active",
                    labels={"shard": str(s)},
                ).set(n)
        return decision, sum(current_of.values())

    def _publish(self, decision: ScaleDecision, current: int) -> None:
        # decision.target is the chosen SHARD pool's target; the
        # fleet_replicas_active/fleet_replicas_target gauge pair is
        # documented as comparable (docs/SERVING.md), so export the
        # post-action FLEET-wide total instead of one pool's target —
        # a hot-shard 2->3 on a 4x2 grid must read 8->9, not 8->3
        if decision.shard is not None:
            decision = dataclasses.replace(
                decision,
                target=current + (decision.target - self._decision_pool),
            )
        super()._publish(decision, current)

    def _describe(self, decision: ScaleDecision) -> str:
        return (
            f"{decision.action} shard {decision.shard} -> "
            f"{decision.target} replicas ({decision.reason})"
        )


class PoolElasticController(ElasticController):
    """The elastic controller for a multi-model catalog fleet: the
    same one-action-at-a-time shell, drain path, and metrics, driving
    a :class:`PoolAutoscalePolicy` over (model, shard) pool keys —
    scale-up spawns a new member into the hot model's pool
    (``FleetSupervisor.scale_up(model=)``), scale-down drains the
    newest member of an idle pool and never a model's last UP replica
    (``pick_drain_victim(model=)``)."""

    def __init__(self, supervisor, proxy, config: AutoscaleConfig,
                 pools, metrics=None, **kw):
        super().__init__(
            supervisor, proxy, config, metrics=metrics,
            policy=PoolAutoscalePolicy(config, pools),
            **kw,
        )
        self.pool_policy = self.policy
        self.pools = list(self.pool_policy.pools)
        # the deciding pool's size at _decide time, consumed by
        # _publish in the same tick (observe is single-threaded per
        # aggregator tick) to translate the pool target fleet-wide
        self._decision_pool = 0

    def _decide(
        self, snapshot: Dict[str, float], now: float
    ) -> Tuple[ScaleDecision, int]:
        current_of = {
            (m, s): self.supervisor.active_count(shard=s, model=m)
            for m, s in self.pools
        }
        decision = self.pool_policy.observe(
            snapshot, now=now, current_of=current_of,
        )
        self._decision_pool = current_of.get(
            (decision.model, decision.shard), 0
        )
        if self.metrics is not None:
            # every pool, every tick — publishing only the deciding
            # pool would freeze the other pools' gauges at whatever
            # size they had the last time they happened to decide
            for (m, s), n in current_of.items():
                if m is not None:
                    self.metrics.gauge(
                        "fleet_model_replicas_active",
                        labels={"model": m},
                    ).set(n)
                if s is not None:
                    self.metrics.gauge(
                        "fleet_shard_replicas_active",
                        labels={"shard": str(s)},
                    ).set(n)
        return decision, sum(current_of.values())

    def _publish(self, decision: ScaleDecision, current: int) -> None:
        # decision.target is the chosen POOL's target; the
        # fleet_replicas_active/fleet_replicas_target gauge pair is
        # documented as comparable (docs/SERVING.md), so export the
        # post-action FLEET-wide total instead of one pool's target
        if decision.model is not None or decision.shard is not None:
            decision = dataclasses.replace(
                decision,
                target=current + (decision.target - self._decision_pool),
            )
        super()._publish(decision, current)

    def _describe(self, decision: ScaleDecision) -> str:
        return (
            f"{decision.action} {_pool_desc(decision.model, decision.shard)} "
            f"-> {decision.target} replicas ({decision.reason})"
        )
