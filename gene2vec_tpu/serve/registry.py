"""Model registry: checkpoint discovery, device-resident tables, hot swap.

The registry watches one ``<export_dir>`` written by
:mod:`gene2vec_tpu.io.checkpoint` (``gene2vec_dim_<D>_iter_<N>.npz`` +
``vocab.tsv``), loads the newest iteration into an immutable
:class:`LoadedModel` — the raw f32 table for ``/v1/embedding`` plus an
L2-normalized device-resident copy for the cosine top-k engine — and
swaps it in atomically: readers take one reference
(:meth:`ModelRegistry.model`) and every field they then touch belongs to
the same iteration.  A new checkpoint never mutates a served model.

Export dirs produced by the reference scripts carry only the text
exports; the registry falls back to the word2vec-format twin
(``*_w2v.txt``) through the streaming preallocating reader in
``io/emb_io.py``.

Crash safety (docs/RESILIENCE.md): discovery runs manifest-verified, so
a torn or bit-rotted newest export is filtered before it is ever read;
a checkpoint that verifies but still fails to load (vocab mismatch,
rotted bytes whose stamp was forged, deleted mid-load) is retried with
exponential backoff and, after ``quarantine_after`` consecutive
failures, quarantined — the watcher keeps serving the last good
snapshot and falls back to the next-newest candidate instead of letting
one bad directory entry kill polling.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from gene2vec_tpu.io.checkpoint import iter_checkpoints_newest_first
from gene2vec_tpu.io.emb_io import read_word2vec_format
from gene2vec_tpu.obs.trace import ambient_span


def _trace_event(name: str, **attrs) -> None:
    from gene2vec_tpu.obs import trace

    tracer = trace.get_tracer()
    if tracer is not None:
        tracer.event(name, **attrs)


def l2_normalize(matrix: np.ndarray, eps: float = 1e-12) -> np.ndarray:
    """Unit-normalize rows (zero rows stay zero instead of dividing by 0
    — a gene with a zero vector simply never wins a cosine top-k)."""
    matrix = np.asarray(matrix, dtype=np.float32)
    norms = np.linalg.norm(matrix, axis=1, keepdims=True)
    return matrix / np.maximum(norms, eps)


def dim0_shards(sharding) -> int:
    """How many ways ``sharding`` splits dim 0 (1 for replicated or
    unrecognized specs) — the row-pad multiple the loader must honor."""
    try:
        spec0 = sharding.spec[0]
    except (AttributeError, IndexError):
        return 1
    if spec0 is None:
        return 1
    axes = (spec0,) if isinstance(spec0, str) else tuple(spec0)
    n = 1
    for a in axes:
        n *= sharding.mesh.shape[a]
    return n


@dataclasses.dataclass(frozen=True)
class LoadedModel:
    """One immutable loaded iteration.  ``unit`` is the L2-normalized
    device-resident matrix the engine matmuls against — row-padded with
    zeros up to the shard multiple when the registry places it sharded
    (``len(self)`` is the real row count; the engine masks the pad);
    ``emb`` is the raw, unpadded host table ``/v1/embedding`` serves."""

    dim: int
    iteration: int
    tokens: Tuple[str, ...]
    index: Dict[str, int]
    emb: np.ndarray
    unit: "object"  # jax.Array — typed loosely so the module imports jax lazily
    source: str
    meta: Dict
    #: serve/ann.py AnnIndex built for EXACTLY this table (None under
    #: --index exact).  Riding the snapshot is what makes hot swap
    #: atomic for the pair: one reference assignment swaps table AND
    #: index together, so a reader can never score a new table against
    #: an old index or vice versa.
    ann: Optional["object"] = None
    #: -- fleet-sharded serving (serve/shardgroup.py) -----------------
    #: global row offset of this shard's first row (0 unsharded);
    #: the engine's local indices + row_base are the GLOBAL ids the
    #: front door merges across shards
    row_base: int = 0
    #: full-table row count (== len(self) unsharded); the routing
    #: table's denominator
    total_rows: Optional[int] = None
    #: the shard-atomic swap token (== iteration; stamped only in
    #: shard mode).  The front door refuses to merge shard answers
    #: carrying different epochs — that is the whole no-mixed-
    #: iteration contract, made checkable per response.
    epoch: Optional[int] = None
    #: checkpoint artifact mtime at load — the numerator of the
    #: ``model_age_seconds`` gauge (a fleet silently wedged on an old
    #: iteration must be *visible*, docs/CONTINUOUS.md)
    created_unix: float = 0.0

    @property
    def version(self) -> Tuple[int, int]:
        return (self.dim, self.iteration)

    def __len__(self) -> int:
        return len(self.tokens)


def discover_candidates(
    export_dir: str, dim: Optional[int] = None, verified_only: bool = True
):
    """Lazy iterator of loadable ``(dim, iteration, path)`` candidates,
    newest first (highest iteration wins; among equal iterations the
    largest dim).  ``dim`` restricts the scan to one table width.
    ``verified_only`` filters through the checkpoint manifests — torn
    exports never appear as candidates at all, and because the filter
    is lazy, consumers that stop at the first acceptable candidate CRC
    one checkpoint, not the whole export history."""
    return iter_checkpoints_newest_first(
        export_dir, text_fallback=True, verified_only=verified_only, dim=dim
    )


def discover_newest(
    export_dir: str, dim: Optional[int] = None, verified_only: bool = True
) -> Optional[Tuple[int, int, str]]:
    """Newest verified ``(dim, iteration, path)`` in ``export_dir``."""
    return next(discover_candidates(export_dir, dim, verified_only), None)


def _read_vocab_tokens(ckpt_path: str) -> List[str]:
    """The vocab token list for a checkpoint — id order IS global row
    order (the routing-table contract).  Reads the per-iteration
    ``.vocab.tsv`` sidecar when the iteration's vocab tail-extended the
    shared vocab.tsv (io/checkpoint.py vocab_path_for)."""
    from gene2vec_tpu.io.checkpoint import vocab_path_for

    vocab_path = vocab_path_for(ckpt_path)
    tokens: List[str] = []
    # load-under-refresh-lock is deliberate: loads serialize on
    # _refresh_lock while serve reads go through the published _model
    # reference and never take it
    with open(vocab_path, "r", encoding="utf-8") as f:  # graftcheck: disable=blocking-while-locked
        for line in f:
            line = line.rstrip("\n")
            if line:
                tokens.append(line.split("\t")[0])
    return tokens


def _load_npz(path: str) -> Tuple[List[str], np.ndarray, Dict]:
    with np.load(path) as z:
        meta = json.loads(str(z["meta"])) if "meta" in z.files else {}
        emb = np.asarray(z["emb"], dtype=np.float32)
    tokens = _read_vocab_tokens(path)
    if len(tokens) != emb.shape[0]:
        raise ValueError(
            f"{path}: {emb.shape[0]} embedding rows vs {len(tokens)} "
            "vocab tokens in vocab.tsv"
        )
    return tokens, emb, meta


def _file_age_base(path: str) -> float:
    """Artifact creation wall time for the model-age gauge (mtime of
    the checkpoint file; 0.0 when unreadable — age then reads as
    since-epoch-huge, which errs loud, not silent)."""
    try:
        return os.path.getmtime(path)
    except OSError:
        return 0.0


class ModelRegistry:
    """Discovers, loads, and hot-swaps checkpoints from one export dir.

    ``sharding`` (a ``jax.sharding.Sharding``, e.g.
    :func:`gene2vec_tpu.parallel.sharding.row_sharding`) places the
    normalized matrix when given; default is the backend's default
    placement.  ``metrics`` (an obs ``MetricsRegistry``) receives
    ``model_iteration`` / ``model_vocab_size`` / ``model_quarantined``
    gauges and ``model_swaps_total`` / ``model_load_failures_total``
    counters.

    ``index_mode`` (exact|quant|ivf) builds a ``serve/ann.py`` index
    per loaded checkpoint: the index rides the immutable
    :class:`LoadedModel`, so the hot swap replaces table and index as
    ONE reference, and IVF centroids cache under
    ``<export_dir>/ann_cache`` keyed by the table CRC (a re-exported
    table with different bytes rebuilds; an unchanged one reloads in
    milliseconds).

    A candidate that fails to load is retried with exponential backoff
    (``retry_backoff_s`` doubling per consecutive failure, capped at
    5 min) and quarantined after ``quarantine_after`` failures;
    meanwhile ``refresh`` falls back to the next-newest verified
    candidate, and the served model — immutable, already resident —
    stays up regardless.

    ``name`` is the catalog model name this registry serves.  With
    multiple registries over sibling export dirs (serve/catalog.py) the
    name disambiguates what path-keyed state alone cannot: failure and
    quarantine metrics gain a ``{model=}`` series and every load/
    quarantine trace event carries ``model=`` context.  The default
    name keeps the historical unlabeled series as the only ones, so a
    single-model deployment's scrape is byte-identical to before.

    ``partition_rules`` (an ordered ``(regex, PartitionSpec)`` list —
    see :mod:`gene2vec_tpu.parallel.partition_rules`) makes placement
    declarative: the registry matches its table name
    (``"<name>/embedding/unit"``) against the rules, derives the
    ``NamedSharding`` under ``mesh``, and places loaded tables through
    one jit-compiled shard closure per registry (the pjit idiom) —
    replacing the imperative ``sharding=`` wiring, which remains as the
    explicit-override escape hatch.
    """

    def __init__(
        self,
        export_dir: str,
        dim: Optional[int] = None,
        sharding=None,
        metrics=None,
        retry_backoff_s: float = 2.0,
        quarantine_after: int = 3,
        index_mode: str = "exact",
        ann_clusters: Optional[int] = None,
        ann_seed: int = 0,
        shard: Optional[Tuple[int, int]] = None,
        name: str = "default",
        partition_rules=None,
        mesh=None,
    ):
        from gene2vec_tpu.serve.ann import INDEX_MODES

        if index_mode not in INDEX_MODES:
            raise ValueError(
                f"index_mode must be one of {INDEX_MODES}, got "
                f"{index_mode!r}"
            )
        if shard is not None:
            idx, n = int(shard[0]), int(shard[1])
            if n < 1 or not 0 <= idx < n:
                raise ValueError(
                    f"shard must be (index, num_shards) with "
                    f"0 <= index < num_shards, got {shard!r}"
                )
            shard = (idx, n)
        self.export_dir = export_dir
        self.dim = dim
        self.name = str(name)
        #: extra per-model label set for failure/quarantine series —
        #: None under the default name so a single-model deployment's
        #: metric names/labels are unchanged
        self._mlabels = (
            {"model": self.name} if self.name != "default" else None
        )
        self.partition_rules = partition_rules
        if partition_rules is not None and sharding is None:
            # declarative placement: the rules list decides how this
            # registry's table lands on the mesh (replicated unless a
            # rule row-shards it)
            from jax.sharding import NamedSharding

            from gene2vec_tpu.parallel.mesh import single_device_mesh
            from gene2vec_tpu.parallel.partition_rules import spec_for_name

            mesh = single_device_mesh() if mesh is None else mesh
            sharding = NamedSharding(
                mesh,
                spec_for_name(
                    partition_rules, f"{self.name}/embedding/unit"
                ),
            )
        self.sharding = sharding
        # jit-compiled shard/gather closures, built lazily on first
        # load (one per registry == one compiled transfer per model)
        self._shard_fn = None
        self._gather_fn = None
        self.metrics = metrics
        self.retry_backoff_s = retry_backoff_s
        self.quarantine_after = quarantine_after
        #: exact|quant|ivf — approximate modes build a serve/ann.py
        #: index per loaded checkpoint (IVF centroids cached under
        #: <export_dir>/ann_cache keyed by table CRC)
        self.index_mode = index_mode
        self.ann_clusters = ann_clusters
        self.ann_seed = ann_seed
        #: (shard_index, num_shards) — load only this contiguous row
        #: range of the table and its index; hot swap becomes
        #: coordinator-driven (stage/flip below) so every shard flips
        #: to a new iteration as ONE logical version
        self.shard = shard
        self._model: Optional[LoadedModel] = None
        self._staged: Optional[LoadedModel] = None
        self._refresh_lock = threading.Lock()
        self._watcher: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # path -> (consecutive failures, stat signature at last failure):
        # failure verdicts apply to BYTES, not names — a checkpoint
        # rewritten under the same name sheds its failure count, backoff
        # window, and quarantine alike
        self._failures: Dict[str, Tuple[int, Optional[Tuple]]] = {}
        self._next_retry: Dict[str, float] = {}
        self._quarantined: Dict[str, Tuple[str, Optional[Tuple]]] = {}

    # -- reading -----------------------------------------------------------

    @property
    def model(self) -> LoadedModel:
        """The current model.  The returned object is immutable — hold the
        reference for the duration of one request and every field is from
        the same iteration, regardless of concurrent swaps."""
        m = self._model
        if m is None:
            raise RuntimeError(
                f"no checkpoint loaded yet from {self.export_dir!r} "
                "(call refresh() or check the export dir)"
            )
        return m

    @property
    def loaded(self) -> bool:
        return self._model is not None

    # -- loading / swapping ------------------------------------------------

    def _load(self, dim: int, iteration: int, path: str) -> LoadedModel:
        import jax
        import jax.numpy as jnp

        with ambient_span(
            "model_load", dim=dim, iteration=iteration, path=path
        ):
            row_base = 0
            epoch = None
            sharded = self.shard is not None
            loaded_slice = False
            if sharded and path.endswith(".npz"):
                # read ONLY this shard's contiguous row range — one
                # seek + one read into the uncompressed npz member
                # (io/checkpoint.py read_npz_rows).  The whole point
                # of sharding is a table too big for one host; a load
                # (or hot-swap stage) that transiently materialized
                # the full matrix would OOM the very replicas sized
                # for rows/num_shards.  Falls back to the full-load
                # path on any structural surprise.
                from gene2vec_tpu.io.checkpoint import read_npz_rows
                from gene2vec_tpu.parallel.sharding import shard_ranges

                idx, n = self.shard
                try:
                    _, total_rows = read_npz_rows(path, "emb", 0, 0)
                    row_base, end = shard_ranges(total_rows, n)[idx]
                    emb, _ = read_npz_rows(path, "emb", row_base, end)
                    emb = np.asarray(emb, dtype=np.float32)
                    loaded_slice = True
                except ValueError:
                    row_base = 0
                if loaded_slice:
                    with np.load(path) as z:
                        # NpzFile members load lazily: this touches
                        # only the tiny meta entry, never the tables
                        meta = (
                            json.loads(str(z["meta"]))
                            if "meta" in z.files else {}
                        )
                    tokens = _read_vocab_tokens(path)
                    if len(tokens) != total_rows:
                        raise ValueError(
                            f"{path}: {total_rows} embedding rows vs "
                            f"{len(tokens)} vocab tokens"
                        )
                    tokens = tokens[row_base:end]
            if not loaded_slice:
                if path.endswith(".npz"):
                    tokens, emb, meta = _load_npz(path)
                else:
                    tokens, emb = read_word2vec_format(path)
                    meta = {
                        "dim": dim, "iteration": iteration,
                        "format": "w2v",
                    }
                total_rows = emb.shape[0]
                if sharded:
                    from gene2vec_tpu.parallel.sharding import (
                        shard_ranges,
                    )

                    idx, n = self.shard
                    row_base, end = shard_ranges(total_rows, n)[idx]
                    tokens = tokens[row_base:end]
                    emb = np.ascontiguousarray(emb[row_base:end])
            if sharded:
                epoch = iteration  # the swap token IS the iteration
            unit_np = l2_normalize(emb)
            pad = 0
            if self.sharding is not None:
                pad = (-unit_np.shape[0]) % dim0_shards(self.sharding)
            ann = None
            if self.index_mode in ("quant", "ivf"):
                # built from the UNPADDED table (lists reference real
                # rows only), then padded/placed exactly like the unit
                # matrix; the IVF centroids cache under ann_cache keyed
                # by table CRC, so a re-export with different bytes
                # rebuilds and an unchanged table loads in milliseconds
                from gene2vec_tpu.serve.ann import build_index

                shard_tag = (
                    f"_shard{self.shard[0]}of{self.shard[1]}"
                    if self.shard is not None else ""
                )
                with ambient_span(
                    "ann_build", mode=self.index_mode, dim=dim,
                    iteration=iteration,
                ):
                    ann = build_index(
                        unit_np,
                        self.index_mode,
                        clusters=self.ann_clusters,
                        seed=self.ann_seed,
                        cache_dir=os.path.join(
                            self.export_dir, "ann_cache"
                        ),
                        tag=f"dim{dim}_iter{iteration}{shard_tag}",
                        version=(dim, iteration),
                        sharding=self.sharding,
                        pad_rows=pad,
                    )
                if self.metrics is not None:
                    self.metrics.gauge("ann_build_seconds").set(
                        ann.build_seconds
                    )
                    self.metrics.counter(
                        "ann_cache_hits_total"
                        if ann.built_from_cache else "ann_builds_total"
                    ).inc()
            if self.sharding is not None:
                if pad:
                    unit_np = np.concatenate(
                        [unit_np,
                         np.zeros((pad, unit_np.shape[1]), np.float32)]
                    )
                # device transfer under _refresh_lock is the load path's
                # contract: serve reads use the published _model
                # reference and never contend on this lock
                if self.partition_rules is not None:
                    # declarative path: one jit-compiled shard closure
                    # per registry (pjit out_shardings), reused across
                    # swaps of the same geometry
                    if self._shard_fn is None:
                        from gene2vec_tpu.parallel.partition_rules import (
                            make_shard_and_gather_fns,
                        )

                        self._shard_fn, self._gather_fn = (
                            make_shard_and_gather_fns(
                                self.sharding.spec, self.sharding.mesh
                            )
                        )
                    unit = self._shard_fn(unit_np)  # graftcheck: disable=blocking-while-locked
                else:
                    unit = jax.device_put(jnp.asarray(unit_np), self.sharding)  # graftcheck: disable=blocking-while-locked
            else:
                unit = jnp.asarray(unit_np)  # graftcheck: disable=blocking-while-locked
            unit.block_until_ready()  # graftcheck: disable=blocking-while-locked
        return LoadedModel(
            dim=dim,
            iteration=iteration,
            tokens=tuple(tokens),
            index={tok: i for i, tok in enumerate(tokens)},
            emb=emb,
            unit=unit,
            source=path,
            meta=meta,
            ann=ann,
            row_base=row_base,
            total_rows=total_rows,
            epoch=epoch,
            created_unix=_file_age_base(path),
        )

    @staticmethod
    def _stat_sig(path: str) -> Optional[Tuple]:
        from gene2vec_tpu.resilience.snapshot import stat_sig

        return stat_sig(path)

    def _count_labeled(self, metric: str) -> None:
        """Increment the unlabeled series (the historical contract every
        single-model consumer reads) and, under a non-default name, the
        per-model ``{model=}`` twin — so sibling registries stay
        distinguishable without breaking anyone's existing scrape."""
        self.metrics.counter(metric).inc()
        if self._mlabels is not None:
            self.metrics.counter(metric, labels=self._mlabels).inc()

    def _gauge_labeled(self, metric: str, value: float) -> None:
        self.metrics.gauge(metric).set(value)
        if self._mlabels is not None:
            self.metrics.gauge(metric, labels=self._mlabels).set(value)

    def _record_failure(self, path: str, err: BaseException) -> None:
        n = self._failures.get(path, (0, None))[0] + 1
        self._failures[path] = (n, self._stat_sig(path))
        # exponential backoff per consecutive failure, capped: a flapping
        # NFS mount retries gently, a genuinely bad file stops costing a
        # load attempt every poll
        self._next_retry[path] = time.monotonic() + min(
            self.retry_backoff_s * (2 ** (n - 1)), 300.0
        )
        if self.metrics is not None:
            self._count_labeled("model_load_failures_total")
        _trace_event(
            "model_load_error", model=self.name, path=path, attempt=n,
            error=repr(err)[:200],
        )
        if n >= self.quarantine_after and path not in self._quarantined:
            self._quarantined[path] = (repr(err)[:200], self._stat_sig(path))
            _trace_event(
                "model_quarantined", model=self.name, path=path,
                error=repr(err)[:200],
            )
            if self.metrics is not None:
                self._gauge_labeled(
                    "model_quarantined", len(self._quarantined)
                )

    def _clear_failure_state(self, path: str) -> None:
        self._failures.pop(path, None)
        self._next_retry.pop(path, None)
        if self._quarantined.pop(path, None) is not None:
            _trace_event(
                "model_quarantine_cleared", model=self.name, path=path
            )
            if self.metrics is not None:
                self._gauge_labeled(
                    "model_quarantined", len(self._quarantined)
                )

    def _skip_for_failures(self, path: str, now: float) -> bool:
        """Whether refresh should pass over this candidate because of
        earlier failures — quarantine or an open backoff window.  Every
        verdict is pinned to the bytes it judged: if the file changed
        (or was replaced) since, the slate is wiped and the candidate
        gets a fresh attempt."""
        recorded = self._quarantined.get(path) or self._failures.get(path)
        if recorded is None:
            return False
        if self._stat_sig(path) != recorded[1]:
            self._clear_failure_state(path)
            return False
        if path in self._quarantined:
            return True
        return now < self._next_retry.get(path, 0.0)

    def _gc_failure_state(self) -> None:
        """Drop failure records for paths that no longer exist — a
        long-lived server churning through exports must not accumulate
        bookkeeping forever."""
        for path in list(self._failures) + list(self._quarantined):
            if not os.path.exists(path):
                self._clear_failure_state(path)

    @property
    def quarantined(self) -> Dict[str, str]:
        """Quarantined checkpoint paths → last error (diagnostics)."""
        return {p: reason for p, (reason, _) in self._quarantined.items()}

    def refresh(self) -> bool:
        """Scan the export dir (manifest-verified); load and atomically
        swap in the newest candidate newer than the served one, falling
        back through older candidates when the newest fails to load.
        Returns whether a swap happened.  Serialized — concurrent
        refreshes load once.  Load failures are counted/backed off, not
        raised: the caller keeps its last good model."""
        with self._refresh_lock:
            self._gc_failure_state()
            candidates = discover_candidates(self.export_dir, self.dim)
            cur = self._model
            now = time.monotonic()
            model = None
            for dim, iteration, path in candidates:
                if cur is not None and (iteration, dim) <= (
                    cur.iteration, cur.dim
                ):
                    break  # nothing newer than the served model remains
                if self._skip_for_failures(path, now):
                    continue
                try:
                    model = self._load(dim, iteration, path)
                except Exception as e:
                    self._record_failure(path, e)
                    continue  # fall back to the next-newest candidate
                self._clear_failure_state(path)
                break
            if model is None:
                return False
            # one reference assignment IS the swap: in-flight readers keep
            # the old immutable model, new readers see the new one
            self._model = model
        if self.metrics is not None:
            self._count_labeled("model_swaps_total")
            self._gauge_labeled("model_iteration", model.iteration)
            self._gauge_labeled("model_vocab_size", len(model))
        return True

    # -- shard-atomic staged swap (serve/shardgroup.py SwapCoordinator) ----

    def stage(self, dim: int, iteration: int) -> LoadedModel:
        """Load iteration ``(dim, iteration)`` into the STAGING slot
        without serving it — step one of the fleet's shard-atomic swap.
        Discovery is manifest-verified, so the bytes are CRC-checked
        before any shard reports "staged"; the served model is
        untouched.  Raises on any failure (the coordinator aborts the
        whole swap — no shard flips unless every shard staged)."""
        with self._refresh_lock:
            staged = self._staged
            if (
                staged is not None
                and staged.version == (dim, iteration)
            ):
                return staged  # idempotent: a coordinator retry is free
            for d, it, path in discover_candidates(
                self.export_dir, dim
            ):
                if (d, it) == (dim, iteration):
                    model = self._load(d, it, path)
                    self._staged = model
                    if self.metrics is not None:
                        self.metrics.gauge("model_staged_iteration").set(
                            iteration
                        )
                    return model
            raise FileNotFoundError(
                f"no verified checkpoint dim={dim} iteration={iteration} "
                f"in {self.export_dir!r} to stage"
            )

    def flip(self, epoch: int) -> LoadedModel:
        """Atomically swap the staged model in, stamped with the
        fleet's ``epoch`` token — step two of the shard-atomic swap,
        issued by the coordinator only after EVERY shard staged.  One
        reference assignment, same atomicity as :meth:`refresh`.
        Idempotent when the served model already carries ``epoch``;
        raises when nothing matching is staged (the coordinator
        re-stages and retries)."""
        with self._refresh_lock:
            cur = self._model
            if cur is not None and cur.epoch == epoch:
                return cur
            staged = self._staged
            if staged is None or staged.iteration != epoch:
                raise RuntimeError(
                    f"no staged model for epoch {epoch} "
                    f"(staged: {staged.version if staged else None})"
                )
            model = dataclasses.replace(staged, epoch=epoch)
            self._model = model
            self._staged = None
        if self.metrics is not None:
            self._count_labeled("model_swaps_total")
            self._gauge_labeled("model_iteration", model.iteration)
            self._gauge_labeled("model_epoch", epoch)
            self._gauge_labeled("model_vocab_size", len(model))
        return model

    # -- watching ----------------------------------------------------------

    def start_watcher(self, interval_s: float = 5.0) -> None:
        """Poll :meth:`refresh` every ``interval_s`` on a daemon thread.
        Load failures are absorbed inside :meth:`refresh` (counted,
        backed off, quarantined); the catch here is the last line of
        defense for discovery-level surprises — logged via obs and
        counted, never allowed to kill polling."""
        if self._watcher is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(interval_s):
                try:
                    self.refresh()
                except Exception as e:
                    if self.metrics is not None:
                        self.metrics.counter(
                            "model_refresh_errors_total"
                        ).inc()
                    _trace_event("model_refresh_error", error=repr(e)[:200])

        self._watcher = threading.Thread(
            target=loop, name="model-registry-watcher", daemon=True
        )
        self._watcher.start()

    def stop_watcher(self) -> None:
        if self._watcher is None:
            return
        self._stop.set()
        self._watcher.join(timeout=5.0)
        self._watcher = None
