"""Model registry: checkpoint discovery, device-resident tables, hot swap.

The registry watches one ``<export_dir>`` written by
:mod:`gene2vec_tpu.io.checkpoint` (``gene2vec_dim_<D>_iter_<N>.npz`` +
``vocab.tsv``), loads the newest iteration into an immutable
:class:`LoadedModel` — the raw f32 table for ``/v1/embedding`` plus an
L2-normalized device-resident copy for the cosine top-k engine — and
swaps it in atomically: readers take one reference
(:meth:`ModelRegistry.model`) and every field they then touch belongs to
the same iteration.  A new checkpoint never mutates a served model.

Export dirs produced by the reference scripts carry only the text
exports; the registry falls back to the word2vec-format twin
(``*_w2v.txt``) through the streaming preallocating reader in
``io/emb_io.py``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from gene2vec_tpu.io.checkpoint import iter_checkpoints
from gene2vec_tpu.io.emb_io import read_word2vec_format
from gene2vec_tpu.obs.trace import ambient_span


def l2_normalize(matrix: np.ndarray, eps: float = 1e-12) -> np.ndarray:
    """Unit-normalize rows (zero rows stay zero instead of dividing by 0
    — a gene with a zero vector simply never wins a cosine top-k)."""
    matrix = np.asarray(matrix, dtype=np.float32)
    norms = np.linalg.norm(matrix, axis=1, keepdims=True)
    return matrix / np.maximum(norms, eps)


def dim0_shards(sharding) -> int:
    """How many ways ``sharding`` splits dim 0 (1 for replicated or
    unrecognized specs) — the row-pad multiple the loader must honor."""
    try:
        spec0 = sharding.spec[0]
    except (AttributeError, IndexError):
        return 1
    if spec0 is None:
        return 1
    axes = (spec0,) if isinstance(spec0, str) else tuple(spec0)
    n = 1
    for a in axes:
        n *= sharding.mesh.shape[a]
    return n


@dataclasses.dataclass(frozen=True)
class LoadedModel:
    """One immutable loaded iteration.  ``unit`` is the L2-normalized
    device-resident matrix the engine matmuls against — row-padded with
    zeros up to the shard multiple when the registry places it sharded
    (``len(self)`` is the real row count; the engine masks the pad);
    ``emb`` is the raw, unpadded host table ``/v1/embedding`` serves."""

    dim: int
    iteration: int
    tokens: Tuple[str, ...]
    index: Dict[str, int]
    emb: np.ndarray
    unit: "object"  # jax.Array — typed loosely so the module imports jax lazily
    source: str
    meta: Dict

    @property
    def version(self) -> Tuple[int, int]:
        return (self.dim, self.iteration)

    def __len__(self) -> int:
        return len(self.tokens)


def discover_newest(
    export_dir: str, dim: Optional[int] = None
) -> Optional[Tuple[int, int, str]]:
    """Newest ``(dim, iteration, path)`` in ``export_dir`` — highest
    iteration wins; among equal iterations the largest dim.  ``dim``
    restricts the scan to one table width."""
    best: Optional[Tuple[int, int, str]] = None
    for d, it, path in iter_checkpoints(export_dir, text_fallback=True):
        if dim is not None and d != dim:
            continue
        if best is None or (it, d) > (best[1], best[0]):
            best = (d, it, path)
    return best


def _load_npz(path: str) -> Tuple[List[str], np.ndarray, Dict]:
    with np.load(path) as z:
        meta = json.loads(str(z["meta"])) if "meta" in z.files else {}
        emb = np.asarray(z["emb"], dtype=np.float32)
    vocab_path = os.path.join(os.path.dirname(path), "vocab.tsv")
    tokens: List[str] = []
    with open(vocab_path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.rstrip("\n")
            if line:
                tokens.append(line.split("\t")[0])
    if len(tokens) != emb.shape[0]:
        raise ValueError(
            f"{path}: {emb.shape[0]} embedding rows vs {len(tokens)} vocab "
            f"tokens in {vocab_path}"
        )
    return tokens, emb, meta


class ModelRegistry:
    """Discovers, loads, and hot-swaps checkpoints from one export dir.

    ``sharding`` (a ``jax.sharding.Sharding``, e.g.
    :func:`gene2vec_tpu.parallel.sharding.row_sharding`) places the
    normalized matrix when given; default is the backend's default
    placement.  ``metrics`` (an obs ``MetricsRegistry``) receives
    ``model_iteration`` / ``model_vocab_size`` gauges and a
    ``model_swaps_total`` counter.
    """

    def __init__(
        self,
        export_dir: str,
        dim: Optional[int] = None,
        sharding=None,
        metrics=None,
    ):
        self.export_dir = export_dir
        self.dim = dim
        self.sharding = sharding
        self.metrics = metrics
        self._model: Optional[LoadedModel] = None
        self._refresh_lock = threading.Lock()
        self._watcher: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- reading -----------------------------------------------------------

    @property
    def model(self) -> LoadedModel:
        """The current model.  The returned object is immutable — hold the
        reference for the duration of one request and every field is from
        the same iteration, regardless of concurrent swaps."""
        m = self._model
        if m is None:
            raise RuntimeError(
                f"no checkpoint loaded yet from {self.export_dir!r} "
                "(call refresh() or check the export dir)"
            )
        return m

    @property
    def loaded(self) -> bool:
        return self._model is not None

    # -- loading / swapping ------------------------------------------------

    def _load(self, dim: int, iteration: int, path: str) -> LoadedModel:
        import jax
        import jax.numpy as jnp

        with ambient_span(
            "model_load", dim=dim, iteration=iteration, path=path
        ):
            if path.endswith(".npz"):
                tokens, emb, meta = _load_npz(path)
            else:
                tokens, emb = read_word2vec_format(path)
                meta = {"dim": dim, "iteration": iteration, "format": "w2v"}
            unit_np = l2_normalize(emb)
            if self.sharding is not None:
                pad = (-unit_np.shape[0]) % dim0_shards(self.sharding)
                if pad:
                    unit_np = np.concatenate(
                        [unit_np,
                         np.zeros((pad, unit_np.shape[1]), np.float32)]
                    )
                unit = jax.device_put(jnp.asarray(unit_np), self.sharding)
            else:
                unit = jnp.asarray(unit_np)
            unit.block_until_ready()
        return LoadedModel(
            dim=dim,
            iteration=iteration,
            tokens=tuple(tokens),
            index={tok: i for i, tok in enumerate(tokens)},
            emb=emb,
            unit=unit,
            source=path,
            meta=meta,
        )

    def refresh(self) -> bool:
        """Scan the export dir; load and atomically swap in the newest
        iteration when it is newer than the served one.  Returns whether a
        swap happened.  Serialized — concurrent refreshes load once."""
        with self._refresh_lock:
            newest = discover_newest(self.export_dir, self.dim)
            if newest is None:
                return False
            dim, iteration, path = newest
            cur = self._model
            if cur is not None and (iteration, dim) <= (
                cur.iteration, cur.dim
            ):
                return False
            model = self._load(dim, iteration, path)
            # one reference assignment IS the swap: in-flight readers keep
            # the old immutable model, new readers see the new one
            self._model = model
        if self.metrics is not None:
            self.metrics.counter("model_swaps_total").inc()
            self.metrics.gauge("model_iteration").set(model.iteration)
            self.metrics.gauge("model_vocab_size").set(len(model))
        return True

    # -- watching ----------------------------------------------------------

    def start_watcher(self, interval_s: float = 5.0) -> None:
        """Poll :meth:`refresh` every ``interval_s`` on a daemon thread
        (load errors are recorded as tracer events, never kill the
        watcher — a half-written checkpoint retries next poll)."""
        if self._watcher is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(interval_s):
                try:
                    self.refresh()
                except Exception as e:
                    from gene2vec_tpu.obs import trace

                    tracer = trace.get_tracer()
                    if tracer is not None:
                        tracer.event(
                            "model_refresh_error", error=repr(e)[:200]
                        )

        self._watcher = threading.Thread(
            target=loop, name="model-registry-watcher", daemon=True
        )
        self._watcher.start()

    def stop_watcher(self) -> None:
        if self._watcher is None:
            return
        self._stop.set()
        self._watcher.join(timeout=5.0)
        self._watcher = None
