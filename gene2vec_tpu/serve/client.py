"""Resilient HTTP client for the serve API: retries, deadlines, breakers.

The serving fleet (``serve/fleet.py``), the load generator
(``scripts/serve_loadgen.py --resilient``), and the dashboard all talk
to replicas through this client instead of raw ``urllib`` so that a
replica crash, an overloaded queue, or a slow network hop degrades one
request's latency — never the caller's correctness.  The design follows
the production serving playbook (deadline-propagating retries with
budgets + circuit breaking, the TF-Serving / finagle shape):

* **Per-attempt connect/read timeouts** — each attempt dials with its
  own connect timeout and reads under its own read timeout, both capped
  by the remaining request deadline;
* **Deadline propagation** — the caller's budget is written into the
  request body's ``timeout_ms`` field (the server's native deadline
  contract) and SHRINKS across attempts: a retry asks the server for
  only the time that is actually left, and no attempt is ever launched
  past the caller's deadline;
* **Retry-safe classification** — retries happen only for failures
  where the work provably did not complete: connect errors, HTTP 503
  (no model / injected), and 504s the server marked *expired in queue*
  (never computed).  400s are the caller's bug and 429s are explicit
  backpressure — retrying either amplifies load for zero information;
* **Token-bucket retry budget** — every primary attempt earns a
  fraction of a token, every retry/hedge spends one; during a full
  outage retries self-limit to ``retry_budget_ratio`` of offered load
  instead of multiplying it;
* **Hedging** (optional) — once enough latency samples exist, a request
  still unanswered at the observed p95 fires one hedge attempt on a
  different replica and the first answer wins — tail latency is traded
  against a bounded amount of extra work, paid from the same budget;
* **Per-replica circuit breakers** — ``closed`` → ``open`` after
  ``failure_threshold`` consecutive failures (the replica is skipped in
  rotation) → ``half-open`` after ``reset_timeout_s`` (ONE probe
  request is let through) → ``closed`` again on success.  A dead
  replica costs one connect timeout per reset window, not per request.
* **Trace propagation** — every attempt carries a ``traceparent``
  header (``obs/tracecontext.py``): the ambient request context when
  one is installed (the fleet proxy installs the caller's), else a
  fresh root sampled at ``trace_sample``.  Each retry and hedge is its
  own child span of the logical request, so attempt amplification is
  visible per-trace; the terminal :class:`ClientResponse` carries the
  ``trace_id`` for slow-request reporting (loadgen
  ``--trace-sample``).

Everything is stdlib (``http.client``); tests drive the state machines
with injected clocks and transports — no real sleeps.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import queue as queue_mod
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union
from urllib.parse import urlparse

from gene2vec_tpu.obs import tracecontext
from gene2vec_tpu.obs.trace import hop_span
from gene2vec_tpu.obs.tracecontext import TRACEPARENT_HEADER, TraceContext

__all__ = [
    "BreakerState",
    "PooledTransport",
    "CircuitBreaker",
    "ClientResponse",
    "InFlightTracker",
    "ResilientClient",
    "RetryPolicy",
    "TokenBucket",
]


# -- policy ------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Knobs for :class:`ResilientClient` (defaults suit the fleet
    proxy's hop to a local replica; loadgen overrides per scenario)."""

    max_attempts: int = 3
    connect_timeout_s: float = 1.0
    read_timeout_s: float = 10.0
    default_timeout_s: float = 2.0
    backoff_base_s: float = 0.02
    backoff_max_s: float = 1.0
    jitter_frac: float = 0.5  # uniform in [1-j, 1+j] times the base
    retry_budget_ratio: float = 0.1  # tokens earned per primary attempt
    retry_budget_burst: float = 10.0
    hedge: bool = False
    hedge_min_samples: int = 32
    breaker_failure_threshold: int = 5
    breaker_reset_timeout_s: float = 5.0
    breaker_half_open_successes: int = 2
    #: root-trace sampling rate for requests arriving WITHOUT an
    #: ambient context: selected requests get a sampled root,
    #: unselected ones get NO context (no header — the downstream
    #: sampler stays free to act); propagated contexts always pass
    #: through regardless
    trace_sample: float = 0.0


# -- token-bucket retry budget -----------------------------------------------


class TokenBucket:
    """Request-coupled retry budget: :meth:`earn` adds a fraction of a
    token per primary attempt (capped at ``burst``), :meth:`spend` takes
    a whole token per retry/hedge.  Coupling refill to *traffic* rather
    than wall time is what bounds retry amplification: at 100% failure,
    retries converge to ``ratio`` x offered load no matter how long the
    outage lasts."""

    def __init__(self, ratio: float, burst: float):
        self.ratio = ratio
        self.burst = burst
        self._tokens = burst  # start full: a cold client may retry
        self._lock = threading.Lock()

    def earn(self) -> None:
        with self._lock:
            self._tokens = min(self._tokens + self.ratio, self.burst)

    def spend(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    @property
    def tokens(self) -> float:
        return self._tokens


# -- per-target in-flight accounting -----------------------------------------


class InFlightTracker:
    """Per-target count of attempts currently on the wire.

    The elastic fleet's zero-drop scale-down contract rides on this:
    the front door stops routing to a draining replica (it leaves the
    rotation), then waits for this tracker's count on the victim's URL
    to settle to zero before the supervisor SIGTERMs it — a request the
    client already dispatched must come back through the socket before
    the process serving it dies.  ``enter``/``exit`` wrap exactly the
    transport call in :meth:`ResilientClient._attempt`, so hedges and
    retries are each their own in-flight unit."""

    def __init__(self):
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def enter(self, target: str) -> None:
        with self._lock:
            self._counts[target] = self._counts.get(target, 0) + 1

    def exit(self, target: str) -> None:
        with self._lock:
            n = self._counts.get(target, 0) - 1
            if n <= 0:
                self._counts.pop(target, None)
            else:
                self._counts[target] = n

    def count(self, target: str) -> int:
        with self._lock:
            return self._counts.get(target, 0)

    def total(self) -> int:
        with self._lock:
            return sum(self._counts.values())


# -- circuit breaker ---------------------------------------------------------


class BreakerState:
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-replica failure gate.  ``closed`` admits everything;
    ``failure_threshold`` *consecutive* failures open it; after
    ``reset_timeout_s`` it half-opens and admits exactly ONE in-flight
    probe; ``half_open_successes`` consecutive probe successes close it,
    any probe failure re-opens (with a fresh reset window).

    ``clock`` is injectable so tests walk the state machine without
    sleeping."""

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_timeout_s: float = 5.0,
        half_open_successes: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.half_open_successes = half_open_successes
        self._clock = clock
        self._lock = threading.Lock()
        self._state = BreakerState.CLOSED
        self._consecutive_failures = 0
        self._probe_successes = 0
        self._opened_at = 0.0
        self._probe_in_flight = False

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        if (
            self._state == BreakerState.OPEN
            and self._clock() - self._opened_at >= self.reset_timeout_s
        ):
            self._state = BreakerState.HALF_OPEN
            self._probe_successes = 0
            self._probe_in_flight = False

    def allow(self) -> bool:
        """Whether a request may be sent to this replica right now.  In
        half-open, admits one probe at a time (the caller MUST follow up
        with :meth:`record_success` / :meth:`record_failure`)."""
        with self._lock:
            self._maybe_half_open()
            if self._state == BreakerState.CLOSED:
                return True
            if self._state == BreakerState.OPEN:
                return False
            if self._probe_in_flight:
                return False
            self._probe_in_flight = True
            return True

    def cancel(self) -> None:
        """Release a probe slot :meth:`allow` reserved without recording
        a verdict — for attempts abandoned before any I/O happened
        (deadline already spent, hedge budget denied)."""
        with self._lock:
            self._probe_in_flight = False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._state == BreakerState.HALF_OPEN:
                self._probe_in_flight = False
                self._probe_successes += 1
                if self._probe_successes >= self.half_open_successes:
                    self._state = BreakerState.CLOSED

    def record_failure(self) -> None:
        with self._lock:
            if self._state == BreakerState.HALF_OPEN:
                # the probe failed: straight back to open, fresh window
                self._state = BreakerState.OPEN
                self._opened_at = self._clock()
                self._probe_in_flight = False
                self._consecutive_failures = self.failure_threshold
                return
            self._consecutive_failures += 1
            if (
                self._state == BreakerState.CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._state = BreakerState.OPEN
                self._opened_at = self._clock()


# -- one attempt's outcome ---------------------------------------------------


class ClientResponse:
    """Terminal outcome of one logical request (after retries/hedging).

    ``status`` is the final HTTP status (0 for a transport-level
    failure); ``error_class`` is the loadgen-facing bucket: ``ok``,
    ``http_4xx``, ``http_429``, ``http_503``, ``http_504``,
    ``transport``, or ``deadline`` (the client's own budget ran out
    before any attempt could conclude).

    ``raw`` is the response body bytes when an attempt concluded over
    HTTP.  Successful bodies are **not** parsed by the client — the
    fleet proxy forwards ``raw`` verbatim (zero-copy passthrough) —
    and :attr:`doc` parses lazily on first access for callers that do
    want the document (the chaos drill's answer verification)."""

    __slots__ = ("status", "_doc", "raw", "error_class", "attempts",
                 "retries", "hedged", "target", "latency_s", "trace_id",
                 "_parsed")

    def __init__(
        self,
        status: int,
        doc: Optional[dict] = None,
        error_class: str = "ok",
        attempts: int = 0,
        retries: int = 0,
        hedged: bool = False,
        target: Optional[str] = None,
        latency_s: float = 0.0,
        trace_id: Optional[str] = None,
        raw: Optional[bytes] = None,
    ):
        self.status = status
        self._doc = doc
        self.raw = raw
        self.error_class = error_class
        self.attempts = attempts
        self.retries = retries
        self.hedged = hedged
        self.target = target
        self.latency_s = latency_s
        self.trace_id = trace_id
        self._parsed = doc is not None

    @property
    def doc(self) -> Optional[dict]:
        if not self._parsed:
            self._parsed = True
            if self.raw:
                try:
                    parsed = json.loads(self.raw.decode("utf-8"))
                    self._doc = parsed if isinstance(parsed, dict) else None
                except (ValueError, UnicodeDecodeError):
                    self._doc = None
        return self._doc

    @property
    def ok(self) -> bool:
        return self.error_class == "ok"

    def __repr__(self) -> str:  # debugging/tests
        return (
            f"ClientResponse(status={self.status}, "
            f"error_class={self.error_class!r}, "
            f"attempts={self.attempts}, target={self.target!r})"
        )


def _classify(status: int, doc: Optional[dict]) -> Tuple[str, bool]:
    """(error_class, retry_safe) for one attempt's HTTP outcome."""
    if 200 <= status < 300:
        return "ok", False
    if status == 429:
        return "http_429", False  # explicit backpressure: NEVER retry
    if status == 503:
        return "http_503", True  # not ready / injected: work not done
    if status == 504:
        # only queue-expired 504s are provably uncomputed; a 504 that
        # timed out mid-compute may have side-effect-free work, but
        # retrying it against the same deadline is wasted load
        msg = str((doc or {}).get("error", ""))
        return "http_504", "expired in queue" in msg
    if status == 408:
        return "http_4xx", True  # the server reaped OUR stalled send
    if 400 <= status < 500:
        return "http_4xx", False  # caller bug: retries can't fix it
    return f"http_{status}", True  # 5xx: replica trouble, retry-safe


# -- transport ---------------------------------------------------------------


def _default_transport(
    base_url: str,
    method: str,
    path: str,
    body: Optional[bytes],
    connect_timeout_s: float,
    read_timeout_s: float,
    headers: Optional[Dict[str, str]] = None,
) -> Tuple[int, bytes]:
    """One single-shot HTTP exchange with SEPARATE connect and read
    deadlines (one TCP connection per call — the pre-keep-alive
    transport, kept for callers that want connection-per-request
    semantics).  Raises ``OSError`` (incl.
    ``ConnectionRefusedError``/``Reset``) or ``socket.timeout`` on
    transport failure; HTTP errors return normally as (status,
    payload).  ``headers`` are per-attempt extras (the traceparent
    header)."""
    u = urlparse(base_url)
    conn = http.client.HTTPConnection(
        u.hostname, u.port, timeout=connect_timeout_s
    )
    try:
        conn.connect()
        if conn.sock is not None:
            conn.sock.settimeout(read_timeout_s)
        all_headers = {"Content-Type": "application/json"} if body else {}
        all_headers.update(headers or {})
        conn.request(method, path, body=body, headers=all_headers)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


class PooledTransport:
    """Keep-alive transport: a bounded stack of persistent
    ``http.client`` connections per replica URL, shared by every
    thread using one client.

    Reuse rules: a connection goes back to its pool only after a fully
    read response that did not advertise ``Connection: close``; ANY
    transport error closes and discards the connection (never pooled
    poisoned).  A **reused** connection that fails before yielding a
    response gets ONE internal retry on a fresh connection — the
    server reaping an idle keep-alive connection between requests (its
    idle timeout, its request cap) is routine, not a replica failure,
    and must not surface as a transport error to the retry machinery.
    A failure on a *fresh* connection propagates: that IS a replica
    failure and the caller's breaker needs to see it.
    """

    def __init__(self, max_per_target: int = 8):
        self.max_per_target = max_per_target
        self._pools: Dict[str, List[http.client.HTTPConnection]] = {}
        self._lock = threading.Lock()
        #: observability for the loadgen report: TCP connections dialed
        #: and stale-reuse internal retries
        self.connections_opened = 0
        self.stale_retries = 0

    def _get(self, base_url: str) -> Optional[http.client.HTTPConnection]:
        with self._lock:
            pool = self._pools.get(base_url)
            return pool.pop() if pool else None

    def _put(self, base_url: str,
             conn: http.client.HTTPConnection) -> None:
        with self._lock:
            pool = self._pools.setdefault(base_url, [])
            if len(pool) < self.max_per_target:
                pool.append(conn)
                return
        conn.close()

    def close(self) -> None:
        with self._lock:
            pools, self._pools = self._pools, {}
        for pool in pools.values():
            for conn in pool:
                conn.close()

    def __call__(
        self,
        base_url: str,
        method: str,
        path: str,
        body: Optional[bytes],
        connect_timeout_s: float,
        read_timeout_s: float,
        headers: Optional[Dict[str, str]] = None,
    ) -> Tuple[int, bytes]:
        all_headers = {"Content-Type": "application/json"} if body else {}
        all_headers.update(headers or {})
        last_exc: Optional[BaseException] = None
        for attempt in (0, 1):
            conn = self._get(base_url) if attempt == 0 else None
            reused = conn is not None
            if conn is None:
                u = urlparse(base_url)
                conn = http.client.HTTPConnection(
                    u.hostname, u.port, timeout=connect_timeout_s
                )
                with self._lock:
                    self.connections_opened += 1
            try:
                if conn.sock is None:
                    conn.connect()
                conn.sock.settimeout(read_timeout_s)
                conn.request(method, path, body=body, headers=all_headers)
                resp = conn.getresponse()
                payload = resp.read()
                status = resp.status
                if resp.will_close:
                    conn.close()
                else:
                    self._put(base_url, conn)
                return status, payload
            except (OSError, http.client.HTTPException) as e:
                conn.close()
                last_exc = e
                if not reused:
                    raise
                with self._lock:
                    self.stale_retries += 1
                # fall through: one fresh-connection retry
        raise last_exc  # type: ignore[misc]  # pragma: no cover


# -- the client --------------------------------------------------------------


class ResilientClient:
    """Deadline-aware retrying client over one or more replica URLs.

    ``targets`` is a list of base URLs or a zero-arg callable returning
    the *current* list (the fleet supervisor passes its live healthy
    set).  ``transport``/``clock``/``sleep``/``rng`` are injectable for
    deterministic tests.

    Stats (also mirrored into ``metrics`` when given, prefixed
    ``fleet_client_``): ``requests``, ``retries``, ``hedges``,
    ``breaker_rejections``, ``deadline_exhausted``,
    ``budget_exhausted``.
    """

    def __init__(
        self,
        targets: Union[Sequence[str], Callable[[], Sequence[str]]],
        policy: RetryPolicy = RetryPolicy(),
        metrics=None,
        transport: Optional[Callable] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
        inflight: Optional[InFlightTracker] = None,
        budget: Optional[TokenBucket] = None,
    ):
        self._targets = targets
        self.policy = policy
        self.metrics = metrics
        #: optional per-target in-flight accounting (the fleet proxy's
        #: drain contract); None costs nothing on the attempt path
        self.inflight = inflight
        # default: per-client keep-alive pools (PooledTransport) — one
        # TCP dial per replica per concurrent stream, not per attempt;
        # tests inject fake transports through this same seam
        self._transport = (
            transport if transport is not None else PooledTransport()
        )
        self._clock = clock
        self._sleep = sleep
        self._rng = rng if rng is not None else random.Random()
        self._rr = 0
        self._rr_lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._breakers_lock = threading.Lock()
        self._latencies: "List[float]" = []
        self._lat_lock = threading.Lock()
        # ``budget`` may be SHARED across clients: the sharded front
        # door (serve/shardgroup.py) hands every per-shard client one
        # bucket, so a dead shard's retries draw down the same budget
        # as every other shard's — the scatter cannot amplify attempts
        # fleet-wide no matter how many shards are failing
        self.budget = budget if budget is not None else TokenBucket(
            policy.retry_budget_ratio, policy.retry_budget_burst
        )
        self.stats: Dict[str, int] = {
            "requests": 0, "retries": 0, "hedges": 0,
            "breaker_rejections": 0, "deadline_exhausted": 0,
            "budget_exhausted": 0,
        }
        self._stats_lock = threading.Lock()

    # -- bookkeeping -------------------------------------------------------

    def _count(self, name: str, amount: int = 1) -> None:
        with self._stats_lock:
            self.stats[name] += amount
        if self.metrics is not None:
            self.metrics.counter(f"fleet_client_{name}_total").inc(amount)

    def targets(self) -> List[str]:
        t = self._targets() if callable(self._targets) else self._targets
        return [u.rstrip("/") for u in t]

    def breaker(self, target: str) -> CircuitBreaker:
        with self._breakers_lock:
            b = self._breakers.get(target)
            if b is None:
                b = CircuitBreaker(
                    failure_threshold=self.policy.breaker_failure_threshold,
                    reset_timeout_s=self.policy.breaker_reset_timeout_s,
                    half_open_successes=(
                        self.policy.breaker_half_open_successes
                    ),
                    clock=self._clock,
                )
                self._breakers[target] = b
            return b

    def _record_latency(self, seconds: float) -> None:
        with self._lat_lock:
            self._latencies.append(seconds)
            if len(self._latencies) > 512:
                del self._latencies[:256]

    def p95_latency_s(self) -> Optional[float]:
        with self._lat_lock:
            if len(self._latencies) < self.policy.hedge_min_samples:
                return None
            ordered = sorted(self._latencies)
        return ordered[min(len(ordered) - 1, int(0.95 * len(ordered)))]

    # -- target selection --------------------------------------------------

    def _pick(self, exclude: Sequence[str]) -> Optional[str]:
        """Next target in round-robin order whose breaker admits a
        request, skipping ``exclude`` (targets already tried for this
        logical request — a retry should change replicas when it can).
        Falls back to an excluded-but-admitted target when every other
        breaker is open (retrying the same replica beats failing), and
        to None only when no breaker admits anything.

        ``allow()`` is consulted lazily, one target at a time, because a
        True answer from a half-open breaker RESERVES its single probe
        slot — asking every breaker up front would leak reservations on
        the targets not chosen."""
        targets = self.targets()
        if not targets:
            return None
        with self._rr_lock:
            start = self._rr
            self._rr += 1
        order = [targets[(start + i) % len(targets)]
                 for i in range(len(targets))]
        for t in order:
            if t not in exclude and self.breaker(t).allow():
                return t
        for t in order:
            if t in exclude and self.breaker(t).allow():
                return t
        return None

    # -- one attempt -------------------------------------------------------

    def _attempt(
        self,
        target: str,
        method: str,
        path: str,
        body: Optional[dict],
        deadline: float,
        base_ctx: Optional[TraceContext] = None,
        hedge: bool = False,
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> Tuple[str, int, Optional[dict], str, bool, Optional[bytes]]:
        """(error_class, status, doc, target, retry_safe, raw); records
        breaker + latency.  The remaining budget is propagated INTO the
        body's ``timeout_ms`` so the server's own deadline machinery
        never works past the caller's.  Each attempt derives its OWN
        child span of ``base_ctx`` and advertises it in the
        ``traceparent`` header — the downstream handler parents to this
        attempt, and retries/hedges show up as sibling spans."""
        remaining = deadline - self._clock()
        breaker = self.breaker(target)
        if remaining <= 0:
            # the breaker admitted this attempt (allow() in _pick) but no
            # I/O will happen; give any probe slot back without a verdict
            breaker.cancel()
            return "deadline", 0, None, target, False, None
        ctx = base_ctx.child() if base_ctx is not None else None
        headers: Optional[Dict[str, str]] = None
        if ctx is not None or extra_headers:
            headers = dict(extra_headers or {})
            if ctx is not None:
                headers[TRACEPARENT_HEADER] = ctx.to_header()
        payload: Optional[bytes] = None
        if body is not None:
            shrunk = dict(body)
            shrunk["timeout_ms"] = max(1.0, remaining * 1000.0)
            payload = json.dumps(shrunk).encode("utf-8")
        t0 = self._clock()
        t0_wall = time.time()
        if self.inflight is not None:
            self.inflight.enter(target)
        try:
            status, raw = self._transport(
                target,
                method,
                path,
                payload,
                min(self.policy.connect_timeout_s, remaining),
                min(self.policy.read_timeout_s, remaining),
                headers,
            )
        except (OSError, http.client.HTTPException):
            breaker.record_failure()
            hop_span(
                "client_attempt", ctx, dur=self._clock() - t0,
                wall=t0_wall, target=target, status=0,
                error_class="transport", hedge=hedge,
            )
            return "transport", 0, None, target, True, None
        finally:
            if self.inflight is not None:
                self.inflight.exit(target)
        # successful bodies stay UNPARSED (ClientResponse.doc parses
        # lazily; the fleet proxy forwards the raw bytes) — only error
        # statuses need the document for retry-safety classification
        doc: Optional[dict] = None
        if not 200 <= status < 300:
            try:
                doc = json.loads(raw.decode("utf-8")) if raw else None
            except (ValueError, UnicodeDecodeError):
                doc = None
        error_class, retry_safe = _classify(status, doc)
        if error_class == "ok":
            breaker.record_success()
            self._record_latency(self._clock() - t0)
        elif error_class in ("http_429", "http_4xx"):
            # the replica is healthy — it answered, and the failure is
            # ours (bad request) or deliberate (backpressure)
            breaker.record_success()
        else:
            breaker.record_failure()
        hop_span(
            "client_attempt", ctx, dur=self._clock() - t0, wall=t0_wall,
            target=target, status=status, error_class=error_class,
            hedge=hedge,
        )
        return error_class, status, doc, target, retry_safe, raw

    # -- the public call ---------------------------------------------------

    def request(
        self,
        path: str,
        body: Optional[dict] = None,
        method: Optional[str] = None,
        timeout_s: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
    ) -> ClientResponse:
        """One logical request with retries/hedging under one deadline.
        Never raises for server/transport failures — the terminal
        outcome (including ``deadline`` exhaustion) comes back as a
        :class:`ClientResponse`.  ``headers`` are caller extras carried
        on EVERY attempt (the fleet proxy forwards the request's
        ``X-Tenant`` this way); the traceparent header still wins on
        conflict."""
        method = method or ("POST" if body is not None else "GET")
        timeout_s = (
            self.policy.default_timeout_s if timeout_s is None
            else float(timeout_s)
        )
        # the logical request's trace context: the ambient one when the
        # caller (fleet proxy handler) installed it, else a fresh
        # SAMPLED root for requests selected at trace_sample.  An
        # unselected request gets NO context at all (the Sampler
        # contract): no id minting, no header — and crucially no
        # unsampled header reaching the replica, which would suppress
        # its own head sampling for all of this client's traffic.
        base_ctx = tracecontext.current()
        if (
            base_ctx is None
            and self.policy.trace_sample > 0
            and self._rng.random() < self.policy.trace_sample
        ):
            base_ctx = tracecontext.new_trace(sampled=True)
        t_start = self._clock()
        deadline = t_start + timeout_s
        self._count("requests")
        self.budget.earn()

        tried: List[str] = []
        attempts = 0
        retries = 0
        hedged = False
        last: Tuple = ("transport", 0, None, None, True, None)

        while attempts < self.policy.max_attempts:
            remaining = deadline - self._clock()
            if remaining <= 0:
                self._count("deadline_exhausted")
                return self._done(
                    "deadline", 0, None, attempts, retries, hedged,
                    last[3], t_start, base_ctx,
                )
            target = self._pick(tried)
            if target is None:
                self._count("breaker_rejections")
                return self._done(
                    "breaker_open", 503,
                    {"error": "every replica's circuit breaker is open"},
                    attempts, retries, hedged, None, t_start, base_ctx,
                )
            attempts += 1
            if target not in tried:
                tried.append(target)

            hedge_after = self.p95_latency_s() if (
                self.policy.hedge and attempts == 1
            ) else None
            if hedge_after is not None and hedge_after < remaining:
                outcome, was_hedge = self._attempt_hedged(
                    target, method, path, body, deadline, hedge_after,
                    tried, base_ctx, extra_headers=headers,
                )
                if was_hedge:
                    hedged = True
                    attempts += 1
            else:
                outcome = self._attempt(
                    target, method, path, body, deadline, base_ctx,
                    extra_headers=headers,
                )
            last = outcome
            error_class, status, doc, _target, retry_safe, raw = outcome
            if error_class == "deadline":
                break  # the budget is gone; looping would only burn a token
            if error_class == "ok" or not retry_safe:
                return self._done(
                    error_class, status, doc, attempts, retries, hedged,
                    outcome[3], t_start, base_ctx, raw=raw,
                )
            if attempts >= self.policy.max_attempts:
                break
            if not self.budget.spend():
                self._count("budget_exhausted")
                break
            retries += 1
            self._count("retries")
            backoff = min(
                self.policy.backoff_base_s * (2 ** (retries - 1)),
                self.policy.backoff_max_s,
            ) * (1.0 + self.policy.jitter_frac * (2 * self._rng.random() - 1))
            remaining = deadline - self._clock()
            if backoff >= remaining:
                # sleeping would eat the whole budget: go now with what's
                # left rather than guaranteeing a deadline failure
                backoff = 0.0
            if backoff > 0:
                self._sleep(backoff)

        error_class, status, doc, target, _safe, raw = last
        if error_class == "deadline":
            self._count("deadline_exhausted")
        return self._done(
            error_class, status, doc, attempts, retries, hedged, target,
            t_start, base_ctx, raw=raw,
        )

    def _attempt_hedged(
        self,
        target: str,
        method: str,
        path: str,
        body: Optional[dict],
        deadline: float,
        hedge_after_s: float,
        tried: List[str],
        base_ctx: Optional[TraceContext] = None,
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> Tuple[Tuple, bool]:
        """Primary attempt + one hedge fired at the p95 mark: whichever
        concludes first wins; a hedge is paid from the retry budget and
        targets a different replica.  Returns (outcome, hedge_fired)."""
        results: "queue_mod.Queue[Tuple]" = queue_mod.Queue()

        def run(t: str, is_hedge: bool = False) -> None:
            results.put(self._attempt(
                t, method, path, body, deadline, base_ctx,
                hedge=is_hedge, extra_headers=extra_headers,
            ))

        threading.Thread(target=run, args=(target,), daemon=True).start()
        try:
            return results.get(timeout=hedge_after_s), False
        except queue_mod.Empty:
            pass
        hedge_target = self._pick(tried)
        if hedge_target is None or not self.budget.spend():
            if hedge_target is not None:
                # reserved by _pick but the budget said no: release any
                # half-open probe slot before falling back to waiting
                self.breaker(hedge_target).cancel()
            remaining = max(0.05, deadline - self._clock())
            try:
                return results.get(timeout=remaining), False
            except queue_mod.Empty:
                return ("deadline", 0, None, target, False, None), False
        self._count("hedges")
        if hedge_target not in tried:
            tried.append(hedge_target)
        threading.Thread(
            target=run, args=(hedge_target, True), daemon=True
        ).start()
        # first FINAL outcome wins; a failed first arrival falls through
        # to the second (both are within the same deadline)
        remaining = max(0.05, deadline - self._clock())
        try:
            first = results.get(timeout=remaining)
        except queue_mod.Empty:
            return ("deadline", 0, None, target, False, None), True
        if first[0] == "ok":
            return first, True
        remaining = max(0.05, deadline - self._clock())
        try:
            second = results.get(timeout=remaining)
        except queue_mod.Empty:
            return first, True
        return (second if second[0] == "ok" else first), True

    def _done(
        self,
        error_class: str,
        status: int,
        doc: Optional[dict],
        attempts: int,
        retries: int,
        hedged: bool,
        target: Optional[str],
        t_start: float,
        base_ctx: Optional[TraceContext] = None,
        raw: Optional[bytes] = None,
    ) -> ClientResponse:
        if error_class == "breaker_open":
            error_class = "http_503"
        return ClientResponse(
            status=status,
            doc=doc,
            error_class=error_class,
            attempts=attempts,
            retries=retries,
            hedged=hedged,
            target=target,
            latency_s=self._clock() - t_start,
            trace_id=base_ctx.trace_id if base_ctx is not None else None,
            raw=raw,
        )
