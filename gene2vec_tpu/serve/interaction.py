"""GGIPNN interaction scoring over the registry's served embedding.

``/v1/interaction`` scores gene pairs with the
:class:`~gene2vec_tpu.models.ggipnn_train.GGIPNNTrainer` predict path —
the same jitted scanned inference the classification harness uses, so a
request batch costs one compiled call.  The scorer binds to one
:class:`~gene2vec_tpu.serve.registry.LoadedModel` snapshot (version
checked by the server, which rebuilds on hot swap):

* the embedding table is the served model's raw table, row-aligned to
  the served vocab;
* the MLP head loads from a GGIPNN run checkpoint
  (``checkpoints/model-<step>.npz``, the
  :mod:`gene2vec_tpu.models.ggipnn_obs` format) when one is supplied;
  without one the head keeps its random init and scores are only useful
  for wiring tests — ``trained`` records which case this is, and the
  server echoes it in every response so untrained scores cannot
  masquerade as predictions.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from gene2vec_tpu.config import GGIPNNConfig
from gene2vec_tpu.models.ggipnn_data import PairTextVocab
from gene2vec_tpu.models.ggipnn_obs import load_checkpoint
from gene2vec_tpu.models.ggipnn_train import GGIPNNTrainer


def unflatten_params(flat: Dict[str, np.ndarray]) -> dict:
    """``{'hidden1/kernel': a, ...}`` (the ggipnn_obs checkpoint layout)
    back to the nested param pytree."""
    out: dict = {}
    for path, value in flat.items():
        node = out
        parts = path.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return out


class InteractionScorer:
    """GGIPNN softmax scores for (gene, gene) pairs from one model
    snapshot."""

    def __init__(
        self,
        model,
        checkpoint_path: Optional[str] = None,
        batch_size: int = 64,
    ):
        import jax.numpy as jnp

        self.version = model.version
        vocab = PairTextVocab()
        vocab.token_to_id = dict(model.index)
        vocab.id_to_token = list(model.tokens)
        config = GGIPNNConfig(
            embedding_dim=model.dim, batch_size=batch_size
        )
        self.trainer = GGIPNNTrainer(config, vocab)
        params, _ = self.trainer.init_state()
        params = dict(params)
        params["embedding"] = jnp.asarray(model.emb)
        self.trained = False
        if checkpoint_path is not None:
            loaded = unflatten_params(load_checkpoint(checkpoint_path))
            emb = loaded.get("embedding")
            if emb is not None and emb.shape != params["embedding"].shape:
                raise ValueError(
                    f"{checkpoint_path}: embedding {emb.shape} does not "
                    f"match the served model "
                    f"{tuple(params['embedding'].shape)} — the checkpoint "
                    "was trained against a different vocab/dim"
                )
            for name, value in loaded.items():
                # head weights only: the served model's table stays (the
                # module contract), so hot swaps change scores and the
                # checkpoint's own table — row-ordered by its TRAINING
                # vocab, not the served one — can never be indexed by
                # served-vocab ids
                if name == "embedding":
                    continue
                params[name] = (
                    jnp.asarray(value) if not isinstance(value, dict)
                    else value
                )
            self.trained = True
        self.params = params

    def encode(self, pairs: Sequence[Tuple[str, str]]) -> np.ndarray:
        """(N, 2) int32 ids; raises KeyError naming the first unknown
        gene (the server maps it to HTTP 400)."""
        index = self.trainer.vocab.token_to_id
        out = []
        for a, b in pairs:
            if a not in index:
                raise KeyError(a)
            if b not in index:
                raise KeyError(b)
            out.append((index[a], index[b]))
        return np.asarray(out, dtype=np.int32).reshape(-1, 2)

    def score(self, pairs: Sequence[Tuple[str, str]]) -> List[float]:
        """Positive-class softmax score per pair (``scores[:, 1]``, the
        column the reference's ROC-AUC reads)."""
        if not pairs:
            return []
        ids = self.encode(pairs)
        scores, _, _ = self.trainer.predict(self.params, ids)
        return [float(s) for s in scores[:, 1]]
