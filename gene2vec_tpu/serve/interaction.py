"""GGIPNN interaction scoring over the registry's served embedding.

``/v1/interaction`` scores gene pairs with the
:class:`~gene2vec_tpu.models.ggipnn_train.GGIPNNTrainer` predict path —
the same jitted scanned inference the classification harness uses, so a
request batch costs one compiled call.  The scorer binds to one
:class:`~gene2vec_tpu.serve.registry.LoadedModel` snapshot (version
checked by the server, which rebuilds on hot swap):

* the embedding table is the served model's raw table, row-aligned to
  the served vocab;
* the MLP head loads from a GGIPNN run checkpoint
  (``checkpoints/model-<step>.npz``, the
  :mod:`gene2vec_tpu.models.ggipnn_obs` format) when one is supplied;
  without one the head keeps its random init and scores are only useful
  for wiring tests — ``trained`` records which case this is, and the
  server echoes it in every response so untrained scores cannot
  masquerade as predictions.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from gene2vec_tpu.config import GGIPNNConfig
from gene2vec_tpu.models.ggipnn_data import PairTextVocab
from gene2vec_tpu.models.ggipnn_obs import load_checkpoint
from gene2vec_tpu.models.ggipnn_train import GGIPNNTrainer


def unflatten_params(flat: Dict[str, np.ndarray]) -> dict:
    """``{'hidden1/kernel': a, ...}`` (the ggipnn_obs checkpoint layout)
    back to the nested param pytree."""
    out: dict = {}
    for path, value in flat.items():
        node = out
        parts = path.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return out


class InteractionScorer:
    """GGIPNN softmax scores for (gene, gene) pairs from one model
    snapshot."""

    def __init__(
        self,
        model,
        checkpoint_path: Optional[str] = None,
        batch_size: int = 64,
    ):
        import jax.numpy as jnp

        self.version = model.version
        vocab = PairTextVocab()
        vocab.token_to_id = dict(model.index)
        vocab.id_to_token = list(model.tokens)
        config = GGIPNNConfig(
            embedding_dim=model.dim, batch_size=batch_size
        )
        self.trainer = GGIPNNTrainer(config, vocab)
        params, _ = self.trainer.init_state()
        params = dict(params)
        params["embedding"] = jnp.asarray(model.emb)
        self.trained = False
        if checkpoint_path is not None:
            loaded = unflatten_params(load_checkpoint(checkpoint_path))
            emb = loaded.get("embedding")
            if emb is not None and emb.shape != params["embedding"].shape:
                raise ValueError(
                    f"{checkpoint_path}: embedding {emb.shape} does not "
                    f"match the served model "
                    f"{tuple(params['embedding'].shape)} — the checkpoint "
                    "was trained against a different vocab/dim"
                )
            for name, value in loaded.items():
                # head weights only: the served model's table stays (the
                # module contract), so hot swaps change scores and the
                # checkpoint's own table — row-ordered by its TRAINING
                # vocab, not the served one — can never be indexed by
                # served-vocab ids
                if name == "embedding":
                    continue
                params[name] = (
                    jnp.asarray(value) if not isinstance(value, dict)
                    else value
                )
            self.trained = True
        self.params = params

    def encode(self, pairs: Sequence[Tuple[str, str]]) -> np.ndarray:
        """(N, 2) int32 ids; raises KeyError naming the first unknown
        gene (the server maps it to HTTP 400)."""
        index = self.trainer.vocab.token_to_id
        out = []
        for a, b in pairs:
            if a not in index:
                raise KeyError(a)
            if b not in index:
                raise KeyError(b)
            out.append((index[a], index[b]))
        return np.asarray(out, dtype=np.int32).reshape(-1, 2)

    def score(self, pairs: Sequence[Tuple[str, str]]) -> List[float]:
        """Positive-class softmax score per pair (``scores[:, 1]``, the
        column the reference's ROC-AUC reads)."""
        if not pairs:
            return []
        ids = self.encode(pairs)
        scores, _, _ = self.trainer.predict(self.params, ids)
        return [float(s) for s in scores[:, 1]]


class CrossShardScorer:
    """GGIPNN pair scoring from raw VECTORS — the sharded fleet's
    front-door scorer (``serve/shardgroup.py:ShardGroup.interaction``).

    On a ``--shard-by-rows`` fleet no single process holds the whole
    table, so the front door resolves each gene's vector from its owner
    shard's replica group and scores here.  The math is exactly
    :class:`InteractionScorer`'s: the same :class:`GGIPNNTrainer`
    predict path runs over a fixed-shape SCRATCH embedding table
    (``2 * max_pairs`` rows) whose rows are filled with the resolved
    vectors per call — pair *i* looks up rows ``(2i, 2i+1)``.  The
    fixed shape keeps the jit cache at one entry no matter how many
    pairs a request carries; identical inputs to an identical MLP make
    parity with the single-replica scorer structural, not numerical
    luck (``tests/test_shard.py`` asserts it).

    The head loads from the same ``ggipnn_obs`` checkpoint format;
    without one it keeps its deterministic random init and ``trained``
    stays false — the front door echoes it so untrained scores cannot
    masquerade, exactly the replica contract."""

    def __init__(
        self,
        dim: int,
        checkpoint_path: Optional[str] = None,
        max_pairs: int = 64,
        batch_size: int = 64,
    ):
        import jax.numpy as jnp

        self.dim = int(dim)
        self.max_pairs = int(max_pairs)
        rows = 2 * self.max_pairs
        vocab = PairTextVocab()
        vocab.token_to_id = {f"_slot{i}": i for i in range(rows)}
        vocab.id_to_token = [f"_slot{i}" for i in range(rows)]
        config = GGIPNNConfig(
            embedding_dim=self.dim, batch_size=batch_size
        )
        self.trainer = GGIPNNTrainer(config, vocab)
        params, _ = self.trainer.init_state()
        params = dict(params)
        self._scratch_shape = tuple(params["embedding"].shape)
        self.trained = False
        if checkpoint_path is not None:
            loaded = unflatten_params(load_checkpoint(checkpoint_path))
            emb = loaded.get("embedding")
            if emb is not None and emb.shape[1] != self.dim:
                raise ValueError(
                    f"{checkpoint_path}: head trained at dim "
                    f"{emb.shape[1]}, the served table is dim "
                    f"{self.dim}"
                )
            for name, value in loaded.items():
                # head weights only — the embedding rows are per-call
                # scratch filled from the shards (the checkpoint's own
                # table is row-ordered by its TRAINING vocab and can
                # never be indexed by scratch slots)
                if name == "embedding":
                    continue
                params[name] = (
                    jnp.asarray(value) if not isinstance(value, dict)
                    else value
                )
            self.trained = True
        self.params = params
        self._jnp = jnp

    def score_vectors(
        self, vec_pairs: Sequence[Tuple[np.ndarray, np.ndarray]]
    ) -> List[float]:
        """Positive-class softmax score per (vector, vector) pair —
        ``InteractionScorer.score`` with the table lookup replaced by
        caller-resolved vectors."""
        if not vec_pairs:
            return []
        if len(vec_pairs) > self.max_pairs:
            raise ValueError(
                f"at most {self.max_pairs} pairs per call"
            )
        table = np.zeros(self._scratch_shape, np.float32)
        for i, (a, b) in enumerate(vec_pairs):
            table[2 * i] = np.asarray(a, np.float32)
            table[2 * i + 1] = np.asarray(b, np.float32)
        params = dict(self.params)
        params["embedding"] = self._jnp.asarray(table)
        ids = np.arange(
            2 * len(vec_pairs), dtype=np.int32
        ).reshape(-1, 2)
        scores, _, _ = self.trainer.predict(params, ids)
        return [float(s) for s in scores[:, 1]]
