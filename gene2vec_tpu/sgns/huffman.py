"""Huffman coding tree for hierarchical softmax.

word2vec's HS variant (gensim builds this in ``build_vocab`` when ``hs=1``;
the reference's trainer exposes it implicitly through gensim's constructor,
``src/gene2vec.py:70``) assigns each vocab token a root-to-leaf path through
V-1 internal nodes; the output layer scores one sigmoid per node on the
path.  With ``min_count=1`` (the reference's setting) the tree spans the
full vocabulary.

TPU shape: paths are padded to the tree's max code length L and stored as
two dense (V, L) arrays — ``points`` (internal-node ids) and ``codes``
(branch bits) — plus a (V,) ``lengths`` vector, so a batch's paths are one
gather and every step is shape-static.
"""

from __future__ import annotations

import heapq
import itertools
from typing import NamedTuple

import numpy as np


class HuffmanTree(NamedTuple):
    points: np.ndarray   # (V, L) int32 — internal-node ids per token path
    codes: np.ndarray    # (V, L) float32 — branch bit per path node (0/1)
    lengths: np.ndarray  # (V,) int32 — true path length per token
    num_nodes: int       # V - 1 internal nodes

    @property
    def max_code_length(self) -> int:
        return int(self.points.shape[1])


def build_huffman_tree(counts: np.ndarray) -> HuffmanTree:
    """Standard word2vec Huffman construction over token counts.

    Token ids are the vocab's frequency-sorted ids; internal nodes get ids
    0..V-2 in creation order (leaves merged first = deepest).
    """
    counts = np.asarray(counts, dtype=np.int64)
    v = int(counts.size)
    if v == 0:
        raise ValueError("empty vocabulary")
    if v == 1:
        # degenerate: single token, empty path
        return HuffmanTree(
            points=np.zeros((1, 1), np.int32),
            codes=np.zeros((1, 1), np.float32),
            lengths=np.zeros(1, np.int32),
            num_nodes=0,
        )

    # heap items: (count, tiebreak, node). Leaves are ints < v; internal
    # nodes are ints >= v (id - v = internal node index).
    tiebreak = itertools.count()
    heap = [(int(c), next(tiebreak), i) for i, c in enumerate(counts)]
    heapq.heapify(heap)
    parent = {}
    bit = {}
    next_internal = 0
    while len(heap) > 1:
        c1, _, n1 = heapq.heappop(heap)
        c2, _, n2 = heapq.heappop(heap)
        node = v + next_internal
        next_internal += 1
        parent[n1], bit[n1] = node, 0.0
        parent[n2], bit[n2] = node, 1.0
        heapq.heappush(heap, (c1 + c2, next(tiebreak), node))

    num_nodes = next_internal  # == v - 1
    # walk each leaf to the root, collecting (node, bit) pairs leaf→root,
    # then reverse to get root→leaf order (word2vec convention).
    paths = []
    max_len = 0
    for leaf in range(v):
        pts, cds = [], []
        n = leaf
        while n in parent:
            p = parent[n]
            pts.append(p - v)
            cds.append(bit[n])
            n = p
        pts.reverse()
        cds.reverse()
        paths.append((pts, cds))
        max_len = max(max_len, len(pts))

    points = np.zeros((v, max_len), np.int32)
    codes = np.zeros((v, max_len), np.float32)
    lengths = np.zeros(v, np.int32)
    for i, (pts, cds) in enumerate(paths):
        points[i, : len(pts)] = pts
        codes[i, : len(cds)] = cds
        lengths[i] = len(pts)
    return HuffmanTree(points=points, codes=codes, lengths=lengths, num_nodes=num_nodes)
