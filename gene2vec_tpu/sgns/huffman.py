"""Huffman coding tree for hierarchical softmax.

word2vec's HS variant (gensim builds this in ``build_vocab`` when ``hs=1``;
the reference's trainer exposes it implicitly through gensim's constructor,
``src/gene2vec.py:70``) assigns each vocab token a root-to-leaf path through
V-1 internal nodes; the output layer scores one sigmoid per node on the
path.  With ``min_count=1`` (the reference's setting) the tree spans the
full vocabulary.

TPU shape: paths are padded to the tree's max code length L and stored as
two dense (V, L) arrays — ``points`` (internal-node ids) and ``codes``
(branch bits) — plus a (V,) ``lengths`` vector, so a batch's paths are one
gather and every step is shape-static.

Round 4 adds :func:`split_shallow` — the frequency-bucketed path layout
(VERDICT r3 item 6): internal nodes at tree depth < ``depth`` (at most
``2^depth − 1`` of them, shared by every path and carrying ALL of a hot
token's short code) are renumbered into a contiguous prefix of the node
table, and each token's shallow path is re-encoded as a dense ±1/0 sign
row over that prefix.  The HS step then scores the shallow levels with
MXU matmuls against the contiguous prefix slab (zero random node row
ops — the exact analogue of the stratified SGNS head) and pays per-row
gathers/scatters only for the deep levels of rare tokens' paths.
"""

from __future__ import annotations

import heapq
import itertools
from typing import NamedTuple

import numpy as np


class HuffmanTree(NamedTuple):
    points: np.ndarray   # (V, L) int32 — internal-node ids per token path
    codes: np.ndarray    # (V, L) float32 — branch bit per path node (0/1)
    lengths: np.ndarray  # (V,) int32 — true path length per token
    num_nodes: int       # V - 1 internal nodes

    @property
    def max_code_length(self) -> int:
        return int(self.points.shape[1])


class ShallowSplit(NamedTuple):
    """Depth-split Huffman path layout (see module docstring).

    Internal-node ids are PERMUTED relative to the source tree: shallow
    nodes (depth < split depth) occupy ids [0, n_shallow) so the HS step
    can slice them as one contiguous slab.
    """

    sign: np.ndarray          # (V, n_shallow) int8 — +1/−1 if the node is
                              # on the token's shallow path (1 − 2·code), 0 off-path
    points_deep: np.ndarray   # (V, L_deep) int32 — PERMUTED deep node ids
    codes_deep: np.ndarray    # (V, L_deep) float32
    lengths_deep: np.ndarray  # (V,) int32 — max(0, length − depth)
    n_shallow: int            # shallow slab size (< 2^depth)
    perm: np.ndarray          # (num_nodes,) int32 — old node id -> new id


def split_shallow(tree: HuffmanTree, depth: int) -> ShallowSplit:
    """Split ``tree``'s paths at ``depth`` levels, renumbering internal
    nodes so the shallow ones form a contiguous table prefix.

    A node's depth is its (unique) position along any root-to-leaf path
    through it, so membership is well defined.  Deep points keep at least
    one column (all-padding when the whole tree is shallower than
    ``depth``) so downstream shapes stay static and non-degenerate.
    """
    if depth < 1:
        raise ValueError(f"depth must be >= 1, got {depth}")
    v, max_len = tree.points.shape
    num_nodes = max(tree.num_nodes, 1)

    on_shallow = np.zeros(num_nodes, bool)
    d_eff = min(depth, max_len)
    for l in range(d_eff):
        live = tree.lengths > l
        on_shallow[tree.points[live, l]] = True
    shallow_ids = np.flatnonzero(on_shallow)
    n_shallow = int(shallow_ids.size)

    perm = np.zeros(num_nodes, np.int32)
    perm[shallow_ids] = np.arange(n_shallow, dtype=np.int32)
    deep_ids = np.flatnonzero(~on_shallow)
    perm[deep_ids] = np.arange(
        n_shallow, num_nodes, dtype=np.int32
    )

    sign = np.zeros((v, max(n_shallow, 1)), np.int8)
    for l in range(d_eff):
        live = np.flatnonzero(tree.lengths > l)
        cols = perm[tree.points[live, l]]
        sign[live, cols] = (1 - 2 * tree.codes[live, l]).astype(np.int8)

    l_deep = max(max_len - depth, 1)
    points_deep = np.zeros((v, l_deep), np.int32)
    codes_deep = np.zeros((v, l_deep), np.float32)
    if max_len > depth:
        points_deep[:, : max_len - depth] = perm[tree.points[:, depth:]]
        codes_deep[:, : max_len - depth] = tree.codes[:, depth:]
    lengths_deep = np.maximum(tree.lengths - depth, 0).astype(np.int32)
    return ShallowSplit(
        sign=sign,
        points_deep=points_deep,
        codes_deep=codes_deep,
        lengths_deep=lengths_deep,
        n_shallow=n_shallow,
        perm=perm,
    )


def build_huffman_tree(counts: np.ndarray) -> HuffmanTree:
    """Standard word2vec Huffman construction over token counts.

    Token ids are the vocab's frequency-sorted ids; internal nodes get ids
    0..V-2 in creation order (leaves merged first = deepest).
    """
    counts = np.asarray(counts, dtype=np.int64)
    v = int(counts.size)
    if v == 0:
        raise ValueError("empty vocabulary")
    if v == 1:
        # degenerate: single token, empty path
        return HuffmanTree(
            points=np.zeros((1, 1), np.int32),
            codes=np.zeros((1, 1), np.float32),
            lengths=np.zeros(1, np.int32),
            num_nodes=0,
        )

    # heap items: (count, tiebreak, node). Leaves are ints < v; internal
    # nodes are ints >= v (id - v = internal node index).
    tiebreak = itertools.count()
    heap = [(int(c), next(tiebreak), i) for i, c in enumerate(counts)]
    heapq.heapify(heap)
    parent = {}
    bit = {}
    next_internal = 0
    while len(heap) > 1:
        c1, _, n1 = heapq.heappop(heap)
        c2, _, n2 = heapq.heappop(heap)
        node = v + next_internal
        next_internal += 1
        parent[n1], bit[n1] = node, 0.0
        parent[n2], bit[n2] = node, 1.0
        heapq.heappush(heap, (c1 + c2, next(tiebreak), node))

    num_nodes = next_internal  # == v - 1
    # walk each leaf to the root, collecting (node, bit) pairs leaf→root,
    # then reverse to get root→leaf order (word2vec convention).
    paths = []
    max_len = 0
    for leaf in range(v):
        pts, cds = [], []
        n = leaf
        while n in parent:
            p = parent[n]
            pts.append(p - v)
            cds.append(bit[n])
            n = p
        pts.reverse()
        cds.reverse()
        paths.append((pts, cds))
        max_len = max(max_len, len(pts))

    points = np.zeros((v, max_len), np.int32)
    codes = np.zeros((v, max_len), np.float32)
    lengths = np.zeros(v, np.int32)
    for i, (pts, cds) in enumerate(paths):
        points[i, : len(pts)] = pts
        codes[i, : len(cds)] = cds
        lengths[i] = len(pts)
    return HuffmanTree(points=points, codes=codes, lengths=lengths, num_nodes=num_nodes)
