"""Pure-numpy SGNS trainer — the CPU oracle backend.

Two jobs (SURVEY §7 steps 2-3):

* an independent implementation of the exact word2vec SGNS recipe
  (per-example negatives, sequential-minded sum updates, linear alpha decay)
  that parity tests and the target-function gate compare the TPU path
  against;
* a measured stand-in CPU baseline when gensim (the reference's engine,
  ``src/gene2vec.py:70``) is not installed — see backends.py for the gated
  gensim wrapper.

Vectorized over small batches for practicality, but with gensim's summed
(sequential-SGD-like) duplicate handling, per-example noise draws, and the
same alpha sweep per iteration.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import numpy as np

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.negative_sampling import noise_distribution
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io import checkpoint as ckpt
from gene2vec_tpu.sgns.model import SGNSParams


def _sigmoid(x: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-x))


class NumpySGNSTrainer:
    """CPU oracle with the SGNSTrainer interface (init/train_epoch/run)."""

    def __init__(self, corpus: PairCorpus, config: SGNSConfig = SGNSConfig()):
        if corpus.num_pairs == 0:
            raise ValueError("corpus is empty")
        self.corpus = corpus
        self.config = config
        self.probs = noise_distribution(
            corpus.vocab.counts, config.ns_exponent
        ).astype(np.float64)
        self.probs /= self.probs.sum()
        self.batch = min(max(config.batch_pairs, 1), 1024, corpus.num_pairs)

    def init(self, seed: Optional[int] = None) -> SGNSParams:
        cfg = self.config
        rng = np.random.RandomState(cfg.seed if seed is None else seed)
        emb = rng.uniform(
            -0.5 / cfg.dim, 0.5 / cfg.dim, (self.corpus.vocab_size, cfg.dim)
        ).astype(np.float32)
        ctx = np.zeros((self.corpus.vocab_size, cfg.dim), np.float32)
        return SGNSParams(emb=emb, ctx=ctx)

    def train_epoch(self, params: SGNSParams, rng: np.random.RandomState):
        cfg = self.config
        emb = np.asarray(params.emb).copy()
        ctx = np.asarray(params.ctx).copy()
        pairs = self.corpus.pairs
        order = rng.permutation(len(pairs))
        num_batches = len(pairs) // self.batch
        losses = []
        for b in range(num_batches):
            batch = pairs[order[b * self.batch : (b + 1) * self.batch]]
            frac = b / max(num_batches, 1)
            lr = cfg.lr * (1.0 - frac) + cfg.min_lr * frac
            if cfg.both_directions:
                centers = np.concatenate([batch[:, 0], batch[:, 1]])
                contexts = np.concatenate([batch[:, 1], batch[:, 0]])
            else:
                centers, contexts = batch[:, 0], batch[:, 1]
            e = len(centers)
            negs = rng.choice(
                self.corpus.vocab_size, size=(e, cfg.negatives), p=self.probs
            )
            v = emb[centers]                       # (E, D)
            u = ctx[contexts]                      # (E, D)
            un = ctx[negs]                         # (E, K, D)
            pos = np.sum(v * u, axis=-1)
            neg = np.einsum("ed,ekd->ek", v, un)
            mask = (negs != contexts[:, None]).astype(np.float32)
            losses.append(
                float(
                    np.mean(
                        np.logaddexp(0, -pos)
                        + np.sum(mask * np.logaddexp(0, neg), axis=-1)
                    )
                )
            )
            g_pos = _sigmoid(pos) - 1.0
            g_neg = _sigmoid(neg) * mask
            d_c = g_pos[:, None] * u + np.einsum("ek,ekd->ed", g_neg, un)
            np.add.at(emb, centers, -lr * d_c)
            np.add.at(ctx, contexts, -lr * (g_pos[:, None] * v))
            np.add.at(
                ctx,
                negs.reshape(-1),
                -lr * (g_neg[:, :, None] * v[:, None, :]).reshape(-1, v.shape[1]),
            )
        return SGNSParams(emb=emb, ctx=ctx), float(np.mean(losses))

    def run(
        self,
        export_dir: str,
        start_iter: Optional[int] = None,
        log: Callable[[str], None] = print,
        preempt=None,
    ) -> SGNSParams:
        cfg = self.config
        if start_iter is None:
            start_iter = ckpt.latest_iteration(export_dir, cfg.dim) + 1
        if start_iter > 1:
            params, _, _ = ckpt.load_iteration(
                export_dir, cfg.dim, start_iter - 1,
                table_dtype="float32",  # this backend computes in f32
            )
            params = SGNSParams(
                emb=np.asarray(params.emb), ctx=np.asarray(params.ctx)
            )
            log(f"resuming from iteration {start_iter - 1}")
        else:
            params = self.init()
            start_iter = 1
        pairs_per_epoch = (self.corpus.num_pairs // self.batch) * self.batch
        for it in range(start_iter, cfg.num_iters + 1):
            if preempt is not None and preempt.triggered:
                break
            t0 = time.perf_counter()
            # per-iteration stream keyed by (seed, it): a resumed run draws
            # the same shuffles/negatives as an uninterrupted one (round-1
            # advisor finding).  SeedSequence mixes the key non-additively —
            # seed+it would make adjacent-seed runs share streams (run
            # seed=2 iter 1 == run seed=1 iter 2; round-2 advisor finding)
            params, loss = self.train_epoch(
                params,
                np.random.RandomState(
                    # int, not the 1-element array: RandomState seeds arrays
                    # via init_by_array but scalars via init_genrand — the
                    # scalar form keys identically to native_backend
                    int(
                        np.random.SeedSequence(
                            [cfg.seed, it]
                        ).generate_state(1)[0]
                    )
                ),
            )
            dt = time.perf_counter() - t0
            rate = pairs_per_epoch / dt if dt > 0 else float("inf")
            log(
                f"gene2vec [numpy] dimension {cfg.dim} iteration {it} done: "
                f"loss={loss:.4f} {rate:,.0f} pairs/s ({dt:.2f}s)"
            )
            ckpt.save_iteration(
                export_dir, cfg.dim, it, params, self.corpus.vocab,
                txt_output=cfg.txt_output,
                meta={"loss": loss, "pairs_per_sec": rate, "backend": "numpy"},
            )
            if preempt is not None and preempt.triggered:
                log(f"preemption requested; drained after iteration {it}")
                break
        return params
