"""Backend dispatch for embedding training: jax (TPU) | numpy | gensim.

BASELINE.json mandates a ``--backend={gensim,jax}`` switch with gensim as
the CPU oracle (the reference's engine, ``src/gene2vec.py:70,87``).  gensim
is not part of this image's baked-in dependency set, so its wrapper is
import-gated with an actionable error; the numpy oracle (numpy_backend.py)
is the always-available CPU reference.
"""

from __future__ import annotations

from typing import Callable, Optional

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.pipeline import PairCorpus

BACKENDS = ("jax", "numpy", "hogwild", "gensim")


def make_backend_trainer(
    corpus: PairCorpus, config: SGNSConfig, backend: str = "jax"
):
    """Trainer with the common ``run(export_dir)`` interface (jax and numpy
    backends additionally expose init/train_epoch; gensim drives its own
    training loop internally)."""
    if backend == "jax":
        from gene2vec_tpu.sgns.cbow_hs import make_trainer

        return make_trainer(corpus, config)
    if backend == "numpy":
        if config.objective != "sgns":
            raise NotImplementedError(
                "numpy backend implements the sgns objective only"
            )
        from gene2vec_tpu.sgns.numpy_backend import NumpySGNSTrainer

        return NumpySGNSTrainer(corpus, config)
    if backend == "hogwild":
        if config.objective != "sgns":
            raise NotImplementedError(
                "hogwild backend implements the sgns objective only"
            )
        from gene2vec_tpu.sgns.native_backend import HogwildSGNSTrainer

        return HogwildSGNSTrainer(corpus, config)
    if backend == "gensim":
        return GensimTrainer(corpus, config)
    raise ValueError(f"unknown backend {backend!r}; choose from {BACKENDS}")


class GensimTrainer:
    """The reference's gensim path, kept as a CPU oracle behind an import gate.

    Reproduces ``src/gene2vec.py:57-92``: dim/window/min_count/workers/sg
    parameters, one ``train()`` epoch per iteration with reshuffle, save +
    txt export per iteration.
    """

    def __init__(
        self, corpus: PairCorpus, config: SGNSConfig, workers: int = 32
    ):
        try:
            import gensim  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "backend='gensim' requires the gensim package, which is not "
                "installed in this environment; use backend='numpy' for a "
                "CPU oracle or backend='jax' for the TPU path"
            ) from e
        self.corpus = corpus
        self.config = config
        self.workers = workers

    def run(
        self,
        export_dir: str,
        start_iter: Optional[int] = None,
        log: Callable[[str], None] = print,
    ):
        import os
        import random

        import gensim

        from gene2vec_tpu.io import checkpoint as ckpt
        from gene2vec_tpu.sgns.model import SGNSParams

        cfg = self.config
        vocab = self.corpus.vocab
        if start_iter is None:
            start_iter = ckpt.latest_iteration(export_dir, cfg.dim) + 1
        if start_iter > cfg.num_iters:
            log(f"resuming from iteration {start_iter - 1}")
            return None
        if start_iter > 1:
            # gensim's binary model is not part of our checkpoint layout, so
            # a partial run restarts from scratch rather than resuming
            # mid-stream (the reference reloads its own .save files,
            # src/gene2vec.py:86-88; our layout keeps only the tables)
            log(
                f"gensim backend cannot resume mid-run from iteration "
                f"{start_iter - 1}; retraining from iteration 1"
            )
        sentences = [
            [vocab.id_to_token[a], vocab.id_to_token[b]]
            for a, b in self.corpus.pairs
        ]
        random.seed(cfg.seed)
        model = None
        os.makedirs(export_dir, exist_ok=True)
        sg = 0 if cfg.objective.startswith("cbow") else 1
        hs = 1 if cfg.objective.endswith("_hs") else 0
        # pure HS when hs=1: gensim would otherwise train hierarchical
        # softmax AND negative sampling together, a different objective
        # from the jax *_hs path and useless as an oracle for it
        negative = 0 if hs else cfg.negatives
        import numpy as np

        for it in range(1, cfg.num_iters + 1):
            random.shuffle(sentences)
            if model is None:
                kwargs = dict(
                    vector_size=cfg.dim, window=cfg.window,
                    min_count=cfg.min_count, workers=self.workers,
                    epochs=1, sg=sg, hs=hs, negative=negative,
                    alpha=cfg.lr, min_alpha=cfg.min_lr, seed=cfg.seed,
                )
                try:
                    model = gensim.models.Word2Vec(sentences, **kwargs)
                except TypeError:  # gensim<4 used size=/iter=
                    kwargs["size"] = kwargs.pop("vector_size")
                    kwargs["iter"] = kwargs.pop("epochs")
                    model = gensim.models.Word2Vec(sentences, **kwargs)
            else:
                model.train(
                    sentences, total_examples=model.corpus_count, epochs=1
                )
            # export through the same checkpoint layout as the other
            # backends, row-aligned to OUR vocab: gensim may drop tokens
            # (its min_count reapplies over possibly-different counts), so
            # missing rows stay zero rather than shifting every row after
            # them onto the wrong gene
            toks = getattr(model.wv, "index_to_key", None)
            if toks is None:  # gensim<4
                toks = model.wv.index2word
            pos = {t: i for i, t in enumerate(toks)}
            mat = np.asarray(model.wv.vectors, np.float32)
            emb = np.zeros((len(vocab), cfg.dim), np.float32)
            for row, t in enumerate(vocab.id_to_token):
                i = pos.get(t)
                if i is not None:
                    emb[row] = mat[i]
            params = SGNSParams(emb=emb, ctx=np.zeros_like(emb))
            ckpt.save_iteration(
                export_dir, cfg.dim, it, params, vocab,
                txt_output=cfg.txt_output, meta={"backend": "gensim"},
            )
            log(f"gene2vec [gensim] dimension {cfg.dim} iteration {it} done")
        return model
