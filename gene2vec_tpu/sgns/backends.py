"""Backend dispatch for embedding training: jax (TPU) | numpy | gensim.

BASELINE.json mandates a ``--backend={gensim,jax}`` switch with gensim as
the CPU oracle (the reference's engine, ``src/gene2vec.py:70,87``).  gensim
is not part of this image's baked-in dependency set, so its wrapper is
import-gated with an actionable error; the numpy oracle (numpy_backend.py)
is the always-available CPU reference.
"""

from __future__ import annotations

from typing import Callable, Optional

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.pipeline import PairCorpus

BACKENDS = ("jax", "numpy", "hogwild", "gensim")


def make_backend_trainer(
    corpus: PairCorpus, config: SGNSConfig, backend: str = "jax"
):
    """Trainer with the common ``run(export_dir)`` interface (jax and numpy
    backends additionally expose init/train_epoch; gensim drives its own
    training loop internally)."""
    if backend == "jax":
        from gene2vec_tpu.sgns.cbow_hs import make_trainer

        return make_trainer(corpus, config)
    if backend == "numpy":
        if config.objective != "sgns":
            raise NotImplementedError(
                "numpy backend implements the sgns objective only"
            )
        from gene2vec_tpu.sgns.numpy_backend import NumpySGNSTrainer

        return NumpySGNSTrainer(corpus, config)
    if backend == "hogwild":
        if config.objective != "sgns":
            raise NotImplementedError(
                "hogwild backend implements the sgns objective only"
            )
        from gene2vec_tpu.sgns.native_backend import HogwildSGNSTrainer

        return HogwildSGNSTrainer(corpus, config)
    if backend == "gensim":
        return GensimTrainer(corpus, config)
    raise ValueError(f"unknown backend {backend!r}; choose from {BACKENDS}")


class GensimTrainer:
    """The reference's gensim path, kept as a CPU oracle behind an import gate.

    Reproduces ``src/gene2vec.py:57-92``: dim/window/min_count/workers/sg
    parameters, one ``train()`` epoch per iteration with reshuffle, save +
    txt export per iteration.  Mid-run resume works the way the reference's
    does (reload the previous iteration's saved model and keep training,
    ``src/gene2vec.py:86-88``): every iteration also saves gensim's own
    binary model next to the npz layout, and a restart loads the latest one
    instead of retraining from iteration 1.
    """

    @staticmethod
    def model_path(export_dir: str, dim: int, iteration: int) -> str:
        """gensim's own save file per iteration (the reference keeps one per
        iteration too: ``gene2vec_dim_200_iter_N``)."""
        import os

        return os.path.join(
            export_dir, f"gene2vec_dim_{dim}_iter_{iteration}.gensim"
        )

    def __init__(
        self, corpus: PairCorpus, config: SGNSConfig, workers: int = 32
    ):
        try:
            import gensim  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "backend='gensim' requires the gensim package, which is not "
                "installed in this environment; use backend='numpy' for a "
                "CPU oracle or backend='jax' for the TPU path"
            ) from e
        self.corpus = corpus
        self.config = config
        self.workers = workers

    def run(
        self,
        export_dir: str,
        start_iter: Optional[int] = None,
        log: Callable[[str], None] = print,
        preempt=None,
    ):
        import os
        import random

        import gensim

        from gene2vec_tpu.io import checkpoint as ckpt
        from gene2vec_tpu.sgns.model import SGNSParams

        cfg = self.config
        vocab = self.corpus.vocab
        if start_iter is None:
            start_iter = ckpt.latest_iteration(export_dir, cfg.dim) + 1
        if start_iter > cfg.num_iters:
            log(f"resuming from iteration {start_iter - 1}")
            return None
        model = None
        if start_iter > 1:
            # the reference's resume: reload the previous iteration's saved
            # model and continue training (src/gene2vec.py:86-88)
            prev = self.model_path(export_dir, cfg.dim, start_iter - 1)
            if os.path.exists(prev):
                try:
                    model = gensim.models.Word2Vec.load(prev)
                except Exception as e:
                    # a torn .gensim file (pre-atomic-save dirs) must
                    # degrade to the retrain path, not crash resume
                    log(
                        f"saved gensim model {prev} failed to load "
                        f"({e!r}); retraining from iteration 1"
                    )
                    start_iter = 1
                else:
                    log(
                        f"resuming from iteration {start_iter - 1} "
                        "(gensim model reloaded)"
                    )
            else:
                # older export dirs carry only the npz tables; without
                # gensim's own save file the run restarts from scratch
                log(
                    f"no saved gensim model for iteration {start_iter - 1}; "
                    "retraining from iteration 1"
                )
                start_iter = 1
        sentences = [
            [vocab.id_to_token[a], vocab.id_to_token[b]]
            for a, b in self.corpus.pairs
        ]
        os.makedirs(export_dir, exist_ok=True)
        sg = 0 if cfg.objective.startswith("cbow") else 1
        hs = 1 if cfg.objective.endswith("_hs") else 0
        # pure HS when hs=1: gensim would otherwise train hierarchical
        # softmax AND negative sampling together, a different objective
        # from the jax *_hs path and useless as an oracle for it
        negative = 0 if hs else cfg.negatives
        import numpy as np

        canonical = sentences
        for it in range(start_iter, cfg.num_iters + 1):
            if preempt is not None and preempt.triggered:
                log(f"preemption requested; drained after iteration {it - 1}")
                break
            # iteration N's order is shuffle_N(canonical) — derived from
            # the canonical corpus order, not the previous iteration's, so
            # a resumed run sees exactly the sequence an uninterrupted one
            # would (cumulative in-place shuffles would diverge on resume)
            sentences = list(canonical)
            random.Random(cfg.seed * 1_000_003 + it).shuffle(sentences)
            if model is None:
                kwargs = dict(
                    vector_size=cfg.dim, window=cfg.window,
                    min_count=cfg.min_count, workers=self.workers,
                    epochs=1, sg=sg, hs=hs, negative=negative,
                    alpha=cfg.lr, min_alpha=cfg.min_lr, seed=cfg.seed,
                )
                try:
                    model = gensim.models.Word2Vec(sentences, **kwargs)
                except TypeError:  # gensim<4 used size=/iter=
                    kwargs["size"] = kwargs.pop("vector_size")
                    kwargs["iter"] = kwargs.pop("epochs")
                    model = gensim.models.Word2Vec(sentences, **kwargs)
            else:
                model.train(
                    sentences, total_examples=model.corpus_count, epochs=1
                )
            # export through the same checkpoint layout as the other
            # backends, row-aligned to OUR vocab: gensim may drop tokens
            # (its min_count reapplies over possibly-different counts), so
            # missing rows stay zero rather than shifting every row after
            # them onto the wrong gene
            toks = getattr(model.wv, "index_to_key", None)
            if toks is None:  # gensim<4
                toks = model.wv.index2word
            pos = {t: i for i, t in enumerate(toks)}
            mat = np.asarray(model.wv.vectors, np.float32)
            emb = np.zeros((len(vocab), cfg.dim), np.float32)
            for row, t in enumerate(vocab.id_to_token):
                i = pos.get(t)
                if i is not None:
                    emb[row] = mat[i]
            params = SGNSParams(emb=emb, ctx=np.zeros_like(emb))
            # gensim's own resume artifact lands (atomically) BEFORE the
            # manifest-stamped checkpoint: the manifest is the commit
            # record, so nothing an iteration needs for resume may be
            # written after it — a kill in between would otherwise leave
            # a "committed" iteration whose resume restarts from scratch.
            # model.save is a FAMILY of files at real scale (arrays over
            # gensim's sep_limit become '<target>.<attr>.npy' sidecars,
            # resolved from the LOAD path), so the whole temp-prefixed
            # family renames together, main pickle last.
            from gene2vec_tpu.resilience import snapshot as snap

            final = self.model_path(export_dir, cfg.dim, it)
            tmp = f"{final}.tmp{os.getpid()}"
            try:
                model.save(tmp)
                family = sorted(
                    os.path.join(export_dir, name)
                    for name in os.listdir(export_dir)
                    if os.path.join(export_dir, name) == tmp
                    or os.path.join(export_dir, name).startswith(tmp + ".")
                )
                for path in family:
                    if path != tmp:  # sidecars first
                        snap.atomic_replace(path, final + path[len(tmp):])
                snap.atomic_replace(tmp, final)
            finally:
                for name in os.listdir(export_dir):
                    if name.startswith(os.path.basename(tmp)):
                        os.unlink(os.path.join(export_dir, name))
            ckpt.save_iteration(
                export_dir, cfg.dim, it, params, vocab,
                txt_output=cfg.txt_output, meta={"backend": "gensim"},
            )
            log(f"gene2vec [gensim] dimension {cfg.dim} iteration {it} done")
        return model
