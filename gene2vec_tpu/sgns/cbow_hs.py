"""CBOW and hierarchical-softmax word2vec variants (BASELINE config 4).

The reference trains skip-gram + negative sampling only (``sg=1`` and
gensim defaults, ``src/gene2vec.py:59-63``), but gensim's constructor — the
reference's de-facto API — exposes ``sg=0`` (CBOW) and ``hs=1``
(hierarchical softmax); BASELINE.json config 4 requires both variants.

With the reference's corpus shape (2-token "sentences", ``window=1``,
SURVEY §2.2 #1) CBOW degenerates to single-context prediction: the CBOW
"context mean" is one vector, so CBOW and skip-gram differ only in which
table (input vs output) hosts which role.  We keep the roles explicit so
the exported *input* table matches gensim's for each variant:

* ``cbow``     — input = context token's emb row, target = center, negative
  sampling against the center's noise draws;
* ``sg_hs``    — input = center's emb row, output = sigmoid per
  Huffman-path node of the context token;
* ``cbow_hs``  — input = context row, path of the center token.

Hierarchical softmax on TPU: each token's padded root-to-leaf path (see
huffman.py) is gathered as (E, L) node ids + branch bits; the per-node
logits are one einsum against the gathered node vectors; masked softplus
gives the loss; updates scatter into the (V-1, D) node table with the same
capped duplicate-row combiner as the SGNS step.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time
from typing import TYPE_CHECKING, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.negative_sampling import NegativeSampler
from gene2vec_tpu.data.pipeline import PairCorpus, epoch_shuffle, host_preshuffle
from gene2vec_tpu.io import checkpoint as ckpt
from gene2vec_tpu.sgns.huffman import (
    HuffmanTree,
    ShallowSplit,
    build_huffman_tree,
    split_shallow,
)
from gene2vec_tpu.sgns.model import SGNSParams
from gene2vec_tpu.sgns.step import (
    _acc_dtype_for,
    _apply_row_updates,
    _examples_from_pairs,
    _finalize_row_updates,
    _scatter_accumulator,
    sgns_step,
)
from gene2vec_tpu.utils.profiling import StepTimer

if TYPE_CHECKING:  # runtime import would cycle through gene2vec_tpu.parallel
    from gene2vec_tpu.parallel.sharding import SGNSSharding

OBJECTIVES = ("cbow", "sg_hs", "cbow_hs")


def hs_loss_and_grads(
    emb: jax.Array,        # (V, D) input table
    node: jax.Array,       # (V-1, D) internal-node (output) table
    inputs: jax.Array,     # (E,) input token ids
    targets: jax.Array,    # (E,) tokens whose Huffman path is scored
    points: jax.Array,     # (V, L) path node ids
    codes: jax.Array,      # (V, L) branch bits
    lengths: jax.Array,    # (V,) path lengths
    compute_dtype=jnp.float32,
    precomputed_v: Optional[jax.Array] = None,  # reuse the caller's gather
):
    """Masked per-path-node logistic loss and closed-form gradients.

    word2vec HS: loss = -Σ_l log σ((1 − 2·code_l) · v·w_l) over the target
    token's path; dL/dlogit_l = σ(logit_l) − (1 − code_l).
    """
    v = (
        emb[inputs].astype(compute_dtype)
        if precomputed_v is None
        else precomputed_v
    )                                                  # (E, D)
    pts = points[targets]                              # (E, L)
    cds = codes[targets].astype(compute_dtype)         # (E, L)
    max_len = points.shape[1]
    mask = (
        jnp.arange(max_len, dtype=jnp.int32)[None, :] < lengths[targets][:, None]
    ).astype(compute_dtype)                            # (E, L)

    w = node[pts].astype(compute_dtype)                # (E, L, D)
    logit = jnp.einsum("ed,eld->el", v, w)             # (E, L)
    sign = 1.0 - 2.0 * cds
    loss = jnp.sum(mask * jax.nn.softplus(-sign * logit), axis=-1)  # (E,)

    g = (jax.nn.sigmoid(logit) - (1.0 - cds)) * mask   # (E, L) dL/dlogit
    d_input = jnp.einsum("el,eld->ed", g, w)           # (E, D)
    d_node = g[:, :, None] * v[:, None, :]             # (E, L, D)
    return jnp.mean(loss), d_input, d_node, pts, mask


def hs_step(
    params: SGNSParams,   # emb = input table, ctx = (V-1, D) node table
    pairs: jax.Array,
    tree_points: jax.Array,
    tree_codes: jax.Array,
    tree_lengths: jax.Array,
    lr: jax.Array,
    *,
    cbow: bool,
    both_directions: bool = True,
    compute_dtype=jnp.float32,
    combiner: str = "capped",
    shallow_sign: Optional[jax.Array] = None,  # (V, Ns) int8, split layout
    n_shallow: int = 0,
    sr_key: Optional[jax.Array] = None,  # bf16 stochastic write-back key
) -> Tuple[SGNSParams, jax.Array]:
    """One hierarchical-softmax SGD step over a batch of corpus pairs.

    With ``shallow_sign``/``n_shallow`` set (the :func:`split_shallow`
    layout; ``tree_*`` must then be the DEEP remainders), the first tree
    levels are scored densely against the contiguous node-table prefix:
    per example, one (Ns,)-row sign gather plus MXU matmuls replace up to
    ``depth`` node gathers AND scatters — and a hot token's whole path
    lives in the prefix, so only rare tokens' deep levels pay per-row
    ops (docs/PERF_NOTES.md round-4 CBOW/HS section).  The objective is
    unchanged: the split is an exact re-grouping of the same per-node
    logistic terms (pinned by tests/test_cbow_hs.py).
    """
    centers, contexts = _examples_from_pairs(pairs, both_directions)
    # sg_hs: input center, path of context. cbow_hs: input context, path of
    # center (the 1-token-context CBOW degeneration).
    inputs, targets = (contexts, centers) if cbow else (centers, contexts)

    v_in = (
        params.emb[inputs].astype(compute_dtype)
        if shallow_sign is not None
        else None
    )
    loss, d_input, d_node, pts, mask = hs_loss_and_grads(
        params.emb, params.ctx, inputs, targets,
        tree_points, tree_codes, tree_lengths, compute_dtype,
        precomputed_v=v_in,
    )
    d = d_input.shape[-1]

    if shallow_sign is not None:
        # ---- dense shallow levels over the contiguous node prefix -------
        w_s = params.ctx[:n_shallow].astype(compute_dtype) # contiguous slab
        s = shallow_sign[targets].astype(compute_dtype)    # (E, Ns) ±1/0
        abs_s = jnp.abs(s)
        logit_s = v_in @ w_s.T                             # (E, Ns) MXU
        # word2vec HS per node: loss = softplus(−sign·logit), dL/dlogit =
        # σ(logit) − (1 − code) with (1 − code) = (1 + sign)/2
        loss_s = jnp.sum(abs_s * jax.nn.softplus(-s * logit_s), axis=-1)
        g_s = abs_s * (jax.nn.sigmoid(logit_s) - (1.0 + s) / 2.0)  # (E, Ns)
        loss = loss + jnp.mean(loss_s)
        d_input = d_input + g_s @ w_s                      # (E, D) MXU

    sk_emb = sk_node = None
    if sr_key is not None and params.emb.dtype == jnp.bfloat16:
        sk_emb, sk_node = jax.random.split(sr_key)
    emb = _apply_row_updates(
        params.emb,
        inputs,
        d_input,
        jnp.ones_like(inputs, compute_dtype),
        lr,
        combiner,
        compute_dtype,
        sr_key=sk_emb,
    )

    if shallow_sign is None:
        # Same fused (rows, D+1) accumulator scatter + dense divisor/axpy
        # as the SGNS step (step.py:_apply_row_updates).  Padded path
        # entries carry weight 0 (mask), so they combine into row 0 with
        # zero payload.
        node = _apply_row_updates(
            params.ctx,
            pts.reshape(-1),
            d_node.reshape(-1, d),
            mask.reshape(-1),
            lr,
            combiner,
            compute_dtype,
            sr_key=sk_node,
        )
        return SGNSParams(emb=emb, ctx=node), loss

    # node table: deep rows via the fused scatter, shallow rows via dense
    # adds into the same (rows, D+1) accumulator — one divisor per node
    # over the sum of shallow and deep load (cap-symmetry invariant,
    # exactly the stratified head's pattern in step.py)
    acc_dtype = _acc_dtype_for(compute_dtype)
    acc = _scatter_accumulator(
        params.ctx.shape[0],
        pts.reshape(-1),
        d_node.reshape(-1, d),
        mask.reshape(-1),
        acc_dtype,
    )
    d_shallow = (g_s.T @ v_in).astype(acc_dtype)           # (Ns, D) MXU
    u_shallow = jnp.sum(abs_s, axis=0, dtype=acc_dtype)    # σ-free units
    acc = acc.at[:n_shallow, :d].add(d_shallow)
    acc = acc.at[:n_shallow, d].add(u_shallow)
    node = _finalize_row_updates(params.ctx, acc, lr, combiner, sr_key=sk_node)
    return SGNSParams(emb=emb, ctx=node), loss


class CBOWHSTrainer:
    """Trainer for the cbow / sg_hs / cbow_hs objectives.

    Mirrors :class:`gene2vec_tpu.sgns.train.SGNSTrainer`'s interface (init /
    train_epoch / run with per-iteration checkpoint + txt export), including
    mesh sharding: data-parallel batch sharding and vocab-sharded
    (row-parallel) tables both apply — the HS node table row-shards over the
    model axis exactly like the SGNS context table.
    """

    def __init__(
        self,
        corpus: PairCorpus,
        config: SGNSConfig,
        sharding: Optional["SGNSSharding"] = None,
    ):
        if config.objective not in OBJECTIVES:
            raise ValueError(
                f"objective={config.objective!r} not in {OBJECTIVES}; plain "
                "'sgns' uses SGNSTrainer"
            )
        if corpus.num_pairs == 0 or corpus.vocab_size == 0:
            raise ValueError("corpus is empty")
        if sharding is not None:
            corpus = corpus.pad_to_multiple(sharding.mesh.shape[sharding.data_axis])
        if corpus.num_pairs < config.batch_pairs:
            config = dataclasses.replace(config, batch_pairs=max(1, corpus.num_pairs))
        if config.shuffle_mode not in ("offset", "full"):
            raise ValueError(f"unknown shuffle_mode {config.shuffle_mode!r}")
        if config.shuffle_mode == "offset":
            corpus = host_preshuffle(corpus, config.seed)
        self.config = config
        self.corpus = corpus
        self.sharding = sharding
        self.num_batches = corpus.num_batches(config.batch_pairs)
        self.timer = StepTimer()
        self.hs = config.objective.endswith("_hs")
        self.split: Optional[ShallowSplit] = None
        if self.hs:
            self.tree: Optional[HuffmanTree] = build_huffman_tree(corpus.vocab.counts)
            if config.hs_dense_depth > 0 and self.tree.num_nodes > 1:
                self.split = split_shallow(self.tree, config.hs_dense_depth)
                points = jnp.asarray(self.split.points_deep)
                codes = jnp.asarray(self.split.codes_deep)
                lengths = jnp.asarray(self.split.lengths_deep)
                sign = jnp.asarray(self.split.sign)
            else:
                points = jnp.asarray(self.tree.points)
                codes = jnp.asarray(self.tree.codes)
                lengths = jnp.asarray(self.tree.lengths)
                sign = None
            if sharding is not None:
                rep = sharding.replicated()
                points = jax.device_put(points, rep)
                codes = jax.device_put(codes, rep)
                lengths = jax.device_put(lengths, rep)
                if sign is not None:
                    sign = jax.device_put(sign, rep)
            self._points, self._codes, self._lengths = points, codes, lengths
            self._sign = sign
        else:
            self.tree = None
            self.sampler = NegativeSampler(corpus.vocab.counts, config.ns_exponent)
            self.noise = (
                jax.device_put(self.sampler.table, sharding.replicated())
                if sharding is not None
                else self.sampler.table
            )
            self.stratified = None
            if config.negative_mode == "stratified":
                from gene2vec_tpu.data.negative_sampling import (
                    build_stratified_spec,
                )

                self.stratified = build_stratified_spec(
                    corpus.vocab.counts, config.strat_head,
                    config.strat_block, config.ns_exponent,
                )
                if sharding is not None:
                    self.stratified = jax.device_put(
                        self.stratified, sharding.replicated()
                    )
        self.pairs = (
            corpus.device_pairs(sharding.corpus_sharding())
            if sharding is not None
            else corpus.device_pairs()
        )
        self._epoch_fn = self._make_epoch()

    def _make_epoch(self) -> Callable:
        cfg = self.config
        sharding = self.sharding
        compute_dtype = jnp.dtype(cfg.compute_dtype)
        num_pairs, num_batches = self.corpus.num_pairs, self.num_batches
        cbow = cfg.objective.startswith("cbow")

        def epoch(params, pairs, key):
            shuffle_key, step_key = jax.random.split(key)
            shuffled = epoch_shuffle(
                pairs, shuffle_key, num_pairs, num_batches, cfg.batch_pairs,
                cfg.shuffle_mode, enabled=cfg.shuffle_each_iter,
            )
            if sharding is not None:
                shuffled = sharding.constrain_batch(shuffled)

            def body(params, step):
                batch = jax.lax.dynamic_slice_in_dim(
                    shuffled, step * cfg.batch_pairs, cfg.batch_pairs
                )
                if sharding is not None:
                    batch = sharding.constrain_batch(batch)
                frac = step.astype(compute_dtype) / max(num_batches, 1)
                lr = cfg.lr * (1.0 - frac) + cfg.min_lr * frac
                if self.hs:
                    params, loss = hs_step(
                        params, batch,
                        self._points, self._codes, self._lengths,
                        lr,
                        cbow=cbow,
                        both_directions=cfg.both_directions,
                        compute_dtype=compute_dtype,
                        combiner=cfg.combiner,
                        shallow_sign=self._sign,
                        n_shallow=(
                            self.split.n_shallow if self.split else 0
                        ),
                        sr_key=(
                            jax.random.fold_in(step_key, step)
                            if cfg.bf16_stochastic_round
                            else None
                        ),
                    )
                else:
                    # cbow + negative sampling: swap roles so the *input*
                    # table hosts the context vector (gensim's cbow layout);
                    # with both_directions the example set is symmetric.
                    swapped = batch[:, ::-1]
                    params, loss = sgns_step(
                        params, swapped, self.noise,
                        jax.random.fold_in(step_key, step),
                        lr,
                        negatives=cfg.negatives,
                        both_directions=cfg.both_directions,
                        compute_dtype=compute_dtype,
                        combiner=cfg.combiner,
                        negative_mode=cfg.negative_mode,
                        shared_pool=cfg.shared_pool,
                        shared_pool_auto=cfg.shared_pool_auto,
                        shared_groups=cfg.shared_groups,
                        strat_group=cfg.strat_group,
                        stratified=self.stratified,
                        bf16_stochastic_round=cfg.bf16_stochastic_round,
                    )
                if sharding is not None:
                    params = sharding.constrain_params(params)
                return params, loss

            params, losses = jax.lax.scan(
                body, params, jnp.arange(num_batches, dtype=jnp.int32)
            )
            return params, jnp.mean(losses)

        donate = (0,) if cfg.donate else ()
        return jax.jit(epoch, donate_argnums=donate)

    # -- params ------------------------------------------------------------

    def _init_impl(self, key, dtype):
        cfg = self.config
        v = self.corpus.vocab_size
        emb = jax.random.uniform(
            key, (v, cfg.dim), dtype=dtype,
            minval=-0.5 / cfg.dim, maxval=0.5 / cfg.dim,
        )
        out_rows = max(self.tree.num_nodes if self.hs else v, 1)
        if self.sharding is not None and self.sharding.vocab_sharded:
            # row-sharding needs dimension 0 divisible by the model axis;
            # the HS node table has V-1 rows, so pad — padded rows are
            # never referenced by any Huffman path.
            shards = self.sharding.mesh.shape[self.sharding.model_axis]
            out_rows = -(-out_rows // shards) * shards
        ctx = jnp.zeros((out_rows, cfg.dim), dtype=dtype)
        return SGNSParams(emb=emb, ctx=ctx)

    def init(self, seed: Optional[int] = None) -> SGNSParams:
        cfg = self.config
        key = jax.random.PRNGKey(cfg.seed if seed is None else seed)
        dtype = jnp.dtype(cfg.table_dtype)
        if self.sharding is not None:
            init_fn = jax.jit(
                functools.partial(self._init_impl, dtype=dtype),
                out_shardings=self.sharding.params_sharding(),
            )
            return init_fn(key)
        return self._init_impl(key, dtype)

    # -- training ----------------------------------------------------------

    def train_epoch(self, params: SGNSParams, key: jax.Array):
        return self._epoch_fn(params, self.pairs, key)

    def profile_kernel(
        self, profiler, params: Optional[SGNSParams] = None,
        name: str = "cbow_hs_step",
    ):
        """AOT kernel attribution of the compiled epoch step
        (``obs/profiler.py``): lower+compile cost and XLA static costs
        under ``name``.  Warm-time only — bench.py and the
        ``kernel_profile`` run path call it once before training."""
        if params is None:
            params = self.init()
        key = jax.random.PRNGKey(self.config.seed)
        return profiler.attribute(
            name, self._epoch_fn, (params, self.pairs, key)
        )

    def run(
        self,
        export_dir: str,
        start_iter: Optional[int] = None,
        log: Callable[[str], None] = print,
        preempt=None,
    ) -> SGNSParams:
        """``preempt`` (a resilience ``PreemptionHandler``) drains the
        loop at the next iteration boundary after a SIGTERM/SIGINT and
        stamps the run manifest ``interrupted=true``
        (docs/RESILIENCE.md)."""
        import contextlib

        from gene2vec_tpu.obs import goodput
        from gene2vec_tpu.obs.run import Run
        from gene2vec_tpu.obs.timeline import TIMELINE_NAME, PhaseTimeline

        cfg = self.config
        run = Run(
            export_dir, name=cfg.objective, config=cfg,
            manifest_extra={
                "num_pairs": self.corpus.num_pairs,
                "vocab_size": self.corpus.vocab_size,
                "num_batches": self.num_batches,
                "hs_shallow_nodes": self.split.n_shallow if self.split else 0,
            },
        )
        run.registry.attach_csv(os.path.join(export_dir, "training_log.csv"))
        # per-iteration phase timeline + goodput, same wiring as the SGNS
        # trainer (obs/timeline.py, obs/goodput.py)
        tl = PhaseTimeline(enabled=cfg.timeline)
        # kernel cost attribution, same wiring as the SGNS trainer:
        # one AOT lower+compile at startup, one float add per epoch
        kp = None
        if cfg.kernel_profile:
            from gene2vec_tpu.obs.profiler import KernelProfiler

            kp = KernelProfiler(
                run_dir=export_dir, registry=run.registry
            )
        wall_t0 = time.perf_counter()
        pairs_done = 0.0
        best_rate = 0.0
        # everything after Run construction runs under its finally, so a
        # failed resume (e.g. the hs_dense_depth mismatch below) still
        # closes the run instead of leaking the ambient tracer
        try:
            if start_iter is None:
                start_iter = ckpt.latest_iteration(export_dir, cfg.dim) + 1
            if start_iter > 1:
                params, _, meta = ckpt.load_iteration(
                    export_dir, cfg.dim, start_iter - 1,
                    table_dtype=cfg.table_dtype,
                )
                if self.hs:
                    # node-table row ids depend on the shallow-split layout;
                    # resuming a checkpoint saved under a different
                    # hs_dense_depth would silently feed permuted node
                    # vectors into the step (absent = pre-round-4 = depth 0)
                    saved_depth = int(meta.get("hs_dense_depth", 0))
                    if saved_depth != cfg.hs_dense_depth:
                        raise ValueError(
                            f"checkpoint in {export_dir} was saved with "
                            f"hs_dense_depth={saved_depth}, config has "
                            f"{cfg.hs_dense_depth}: node-table layouts differ "
                            "— resume with the saved depth or start a fresh "
                            "export dir"
                        )
                log(f"resuming from iteration {start_iter - 1}")
            else:
                params = self.init()
                start_iter = 1

            root_key = jax.random.PRNGKey(cfg.seed)
            if kp is not None:
                with run.span(
                    "kernel_attribution", kernel="cbow_hs_step"
                ):
                    self.profile_kernel(kp, params=params)
            pairs_per_epoch = self.num_batches * cfg.batch_pairs
            pairs_counter = run.registry.counter("pairs_total")
            for it in range(start_iter, cfg.num_iters + 1):
                if preempt is not None and preempt.triggered:
                    break
                t0 = time.perf_counter()
                with tl.phase("host_ingest", step=it):
                    epoch_key = jax.random.fold_in(root_key, it)
                with run.step(
                    "iteration", iteration=it, pairs=pairs_per_epoch
                ) as span_out:
                    with tl.phase("dispatch", step=it):
                        params, loss = self.train_epoch(params, epoch_key)
                    with tl.phase("compute", step=it):
                        loss = float(loss)
                    span_out["loss"] = loss
                dt = time.perf_counter() - t0
                rate = pairs_per_epoch / dt if dt > 0 else float("inf")
                if kp is not None:
                    kp.observe("cbow_hs_step", dt)
                self.timer.record(pairs_per_epoch, dt)
                pairs_counter.inc(pairs_per_epoch)
                pairs_done += pairs_per_epoch
                if dt > 0 and it != start_iter:
                    best_rate = max(best_rate, rate)
                log(
                    f"gene2vec [{cfg.objective}] dimension {cfg.dim} iteration "
                    f"{it} done: loss={loss:.4f} {rate:,.0f} pairs/s ({dt:.2f}s)"
                )
                run.log_row(
                    it, {"loss": loss, "pairs_per_sec": rate, "seconds": dt}
                )
                run.probe()
                with run.span("checkpoint", iteration=it), tl.phase(
                    "ckpt_stage", step=it
                ):
                    ckpt.save_iteration(
                        export_dir, cfg.dim, it, params, self.corpus.vocab,
                        txt_output=cfg.txt_output,
                        meta={
                            "loss": loss,
                            "pairs_per_sec": rate,
                            "objective": cfg.objective,
                            # node-table layout tag: resume refuses a mismatch
                            "hs_dense_depth": cfg.hs_dense_depth if self.hs else 0,
                        },
                    )
                if preempt is not None and preempt.triggered:
                    log(
                        f"preemption requested (signal {preempt.received}); "
                        f"drained after iteration {it}"
                    )
                    break
        finally:
            if preempt is not None and preempt.triggered:
                run.mark_interrupted("signal", signal=preempt.received)
            # observability residue must never mask the in-flight error
            with contextlib.suppress(Exception):
                wall_s = time.perf_counter() - wall_t0
                preempted_s = 0.0
                if (
                    preempt is not None and preempt.triggered
                    and preempt.received_wall is not None
                ):
                    preempted_s = min(
                        max(time.time() - preempt.received_wall, 0.0), wall_s
                    )
                tl.flush(os.path.join(run.run_dir, TIMELINE_NAME))
                if kp is not None:
                    kp.flush()
                goodput.stamp(run, goodput.summarize(
                    tl.records(), wall_s, pairs_total=pairs_done,
                    peak_pairs_per_sec=best_rate or None,
                    preempted_s=preempted_s,
                    kernel_seconds=(
                        kp.attributed_seconds() if kp is not None
                        else None
                    ),
                ))
            run.close()
        return params


def make_trainer(
    corpus: PairCorpus,
    config: SGNSConfig,
    sharding: Optional["SGNSSharding"] = None,
):
    """Objective-dispatching factory: 'sgns' → SGNSTrainer, else CBOWHSTrainer."""
    if config.objective == "sgns":
        from gene2vec_tpu.sgns.train import SGNSTrainer

        return SGNSTrainer(corpus, config, sharding=sharding)
    return CBOWHSTrainer(corpus, config, sharding=sharding)
