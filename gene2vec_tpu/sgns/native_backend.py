"""ctypes bridge + trainer for the native Hogwild SGNS CPU oracle.

This is the measured stand-in for the reference's gensim-Cython engine
(32 lock-free threads over shared tables, ``src/gene2vec.py:59``): the
benchmark's ``vs_baseline`` divides the TPU rate by THIS kernel's rate, so
the baseline is a real multithreaded C++ loop, not Python.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import time
from typing import Callable, Optional

import numpy as np

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.negative_sampling import NegativeSampler
from gene2vec_tpu.data.pipeline import PairCorpus
from gene2vec_tpu.io import checkpoint as ckpt
from gene2vec_tpu.obs.trace import ambient_span
from gene2vec_tpu.sgns.model import SGNSParams

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libsgns_hogwild.so")

_lib: Optional[ctypes.CDLL] = None
_build_attempted = False


_ABI_VERSION = 2  # must match SGNS_HOGWILD_ABI_VERSION in sgns_hogwild.cpp


def _make() -> None:
    if not os.environ.get("GENE2VEC_TPU_NO_NATIVE_BUILD"):
        try:
            subprocess.run(
                ["make", "-B", "-C", _NATIVE_DIR, "libsgns_hogwild.so"],
                capture_output=True, timeout=120, check=False,
            )
        except Exception:
            pass


def _stamp_path(path: str) -> str:
    return path + ".abi"


def _so_digest(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()[:16]


def _stamp_ok(path: str) -> bool:
    """True when the sidecar ``.abi`` stamp (written at build time by the
    Makefile, or here after a successful probe) matches ``_ABI_VERSION``
    AND was written for this exact ``.so`` (content hash on line 2) — the
    cheap fast path that replaces the per-process subprocess ABI probe.
    Binding to content rather than mtime means a stamp restored by e.g.
    a git checkout can never validate a stale library."""
    try:
        with open(_stamp_path(path), "r", encoding="ascii") as f:
            lines = f.read().split()
        if len(lines) < 2 or int(lines[0]) != _ABI_VERSION:
            return False
        return lines[1] == _so_digest(path)
    except (OSError, ValueError):
        return False


def _write_stamp(path: str) -> None:
    try:
        digest = _so_digest(path)
        with open(_stamp_path(path), "w", encoding="ascii") as f:
            f.write(f"{_ABI_VERSION}\n{digest}\n")
    except OSError:
        pass  # unwritable checkout: fall back to probing next process


def _stale(path: str) -> bool:
    """ABI-check WITHOUT dlopening into this process: dlopen caches by
    path, so probing with ctypes.CDLL would pin a stale mapping that a
    post-rebuild re-CDLL silently returns again.  A subprocess probe
    leaves this process clean (the pairio pattern builds before loading;
    here the .so may predate the ABI gate entirely, so we must inspect).

    Only reached when the ``.abi`` sidecar stamp is missing or
    mismatched — the common case reads the stamp and never forks."""
    probe = (
        "import ctypes, sys\n"
        f"lib = ctypes.CDLL({path!r})\n"
        "ok = hasattr(lib, 'sgns_hogwild_abi_version') and "
        f"lib.sgns_hogwild_abi_version() == {_ABI_VERSION}\n"
        "sys.exit(0 if ok else 1)\n"
    )
    try:
        import sys

        return (
            subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True, timeout=60,
            ).returncode
            != 0
        )
    except Exception:
        return True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_attempted
    if _lib is not None:
        return _lib
    with ambient_span("native_abi_check", lib="libsgns_hogwild.so") as span:
        stamp_valid = False  # carried to the post-dlopen write below, so
        # the fast path hashes the .so once, not twice
        if not _build_attempted and not os.path.exists(_LIB_PATH):
            _build_attempted = True
            span["action"] = "build"
            _make()
        elif not _build_attempted:
            stamp_valid = _stamp_ok(_LIB_PATH)
            if stamp_valid:
                span["action"] = "stamp_ok"
            else:
                # no (or mismatched) build-time stamp: one subprocess
                # probe, then a rebuild if the .so really is a different
                # ABI — BEFORE the first dlopen in this process
                _build_attempted = True
                if _stale(_LIB_PATH):
                    span["action"] = "rebuild_stale"
                    _make()
                else:
                    span["action"] = "probed_ok"
                    _write_stamp(_LIB_PATH)  # next process skips the probe
                    stamp_valid = True
        else:
            span["action"] = "stamp_ok"
        if not os.path.exists(_LIB_PATH):
            span["action"] = "missing"
            return None
        lib = ctypes.CDLL(_LIB_PATH)
        if not hasattr(lib, "sgns_hogwild_abi_version") or (
            lib.sgns_hogwild_abi_version() != _ABI_VERSION
        ):
            span["action"] = "abi_mismatch"
            # Whatever said this .so was fine lied — drop the stamp so the
            # next process probes (and rebuilds) instead of repeating this.
            try:
                os.remove(_stamp_path(_LIB_PATH))
            except OSError:
                pass
            return None  # rebuild failed or was disabled; never call across ABIs
        if not stamp_valid:
            _write_stamp(_LIB_PATH)  # fresh build, or pre-stamp .so
    lib.sgns_hogwild_epoch.argtypes = [
        ctypes.POINTER(ctypes.c_float),   # emb
        ctypes.POINTER(ctypes.c_float),   # ctx
        ctypes.c_int64,                   # vocab
        ctypes.c_int32,                   # dim
        ctypes.POINTER(ctypes.c_int32),   # pairs
        ctypes.c_int64,                   # n_pairs
        ctypes.POINTER(ctypes.c_float),   # alias prob
        ctypes.POINTER(ctypes.c_int32),   # alias alias
        ctypes.c_int32,                   # negatives
        ctypes.c_float,                   # lr_start
        ctypes.c_float,                   # lr_end
        ctypes.c_int32,                   # n_threads
        ctypes.c_uint64,                  # seed
        ctypes.c_int32,                   # both_directions
    ]
    lib.sgns_hogwild_epoch.restype = ctypes.c_float
    lib.sgns_hogwild_abi_version.restype = ctypes.c_int64
    lib.hs_hogwild_epoch.argtypes = [
        ctypes.POINTER(ctypes.c_float),   # emb (input table)
        ctypes.POINTER(ctypes.c_float),   # node table
        ctypes.c_int32,                   # dim
        ctypes.POINTER(ctypes.c_int32),   # pairs
        ctypes.c_int64,                   # n_pairs
        ctypes.POINTER(ctypes.c_int32),   # points (V, L)
        ctypes.POINTER(ctypes.c_float),   # codes (V, L)
        ctypes.POINTER(ctypes.c_int32),   # lengths (V,)
        ctypes.c_int32,                   # max_len
        ctypes.c_float,                   # lr_start
        ctypes.c_float,                   # lr_end
        ctypes.c_int32,                   # n_threads
        ctypes.c_int32,                   # both_directions
        ctypes.c_int32,                   # cbow
    ]
    lib.hs_hogwild_epoch.restype = ctypes.c_float
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _iptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


class HogwildHSTrainer:
    """Native CPU trainer for the hierarchical-softmax objectives
    (BASELINE config 4: gensim ``sg=0, hs=1`` and the ``sg_hs`` variant) —
    the measured denominator for ``cbow_hs_vs_cpu`` in the bench
    secondary.  Scores the SAME Huffman tree the TPU path builds
    (``huffman.build_tree``), so losses are comparable objective-for-
    objective, not just rate-for-rate."""

    def __init__(
        self,
        corpus: PairCorpus,
        config: SGNSConfig = SGNSConfig(objective="cbow_hs"),
        n_threads: Optional[int] = None,
    ):
        if _load() is None:
            raise RuntimeError(
                "native Hogwild library not available (make -C native failed?)"
            )
        if config.objective not in ("cbow_hs", "sg_hs"):
            raise ValueError(
                f"HogwildHSTrainer implements the hs objectives, not "
                f"{config.objective!r}"
            )
        if corpus.num_pairs == 0:
            raise ValueError("corpus is empty")
        from gene2vec_tpu.sgns.huffman import build_huffman_tree

        self.corpus = corpus
        self.config = config
        self.n_threads = n_threads or os.cpu_count() or 1
        tree = build_huffman_tree(corpus.vocab.counts)
        self._points = np.ascontiguousarray(tree.points, np.int32)
        self._codes = np.ascontiguousarray(tree.codes, np.float32)
        self._lengths = np.ascontiguousarray(tree.lengths, np.int32)

    def init(self, seed: Optional[int] = None) -> SGNSParams:
        cfg = self.config
        rng = np.random.RandomState(cfg.seed if seed is None else seed)
        emb = rng.uniform(
            -0.5 / cfg.dim, 0.5 / cfg.dim, (self.corpus.vocab_size, cfg.dim)
        ).astype(np.float32)
        node = np.zeros(
            (max(self.corpus.vocab_size - 1, 1), cfg.dim), np.float32
        )
        return SGNSParams(emb=emb, ctx=node)

    def train_epoch(
        self,
        params: SGNSParams,
        seed: int = 0,
        rng: Optional[np.random.RandomState] = None,
    ):
        """One Hogwild HS epoch.  Returns ``(updated SGNSParams, loss)``.

        In-place contract (same as :meth:`HogwildSGNSTrainer.train_epoch`):
        contiguous float32 *numpy* inputs are updated in place AND
        returned; any other input — a JAX array, a non-contiguous view,
        a different dtype — is **copied** first, so the caller's arrays
        stay untouched and only the returned params carry the update.
        Always use the return value.
        """
        cfg = self.config
        emb = np.ascontiguousarray(np.asarray(params.emb), np.float32)
        node = np.ascontiguousarray(np.asarray(params.ctx), np.float32)
        pairs = self.corpus.pairs
        if rng is not None:
            pairs = pairs[rng.permutation(len(pairs))]
        pairs = np.ascontiguousarray(pairs, np.int32)
        loss = _load().hs_hogwild_epoch(
            _fptr(emb), _fptr(node), cfg.dim,
            _iptr(pairs), len(pairs),
            _iptr(self._points), _fptr(self._codes), _iptr(self._lengths),
            self._points.shape[1],
            cfg.lr, cfg.min_lr,
            self.n_threads,
            int(cfg.both_directions),
            int(cfg.objective.startswith("cbow")),
        )
        return SGNSParams(emb=emb, ctx=node), float(loss)


class HogwildSGNSTrainer:
    """Native CPU trainer with the common init/train_epoch/run interface."""

    def __init__(
        self,
        corpus: PairCorpus,
        config: SGNSConfig = SGNSConfig(),
        n_threads: Optional[int] = None,
    ):
        if _load() is None:
            raise RuntimeError(
                "native Hogwild library not available (make -C native failed?)"
            )
        if corpus.num_pairs == 0:
            raise ValueError("corpus is empty")
        self.corpus = corpus
        self.config = config
        self.n_threads = n_threads or os.cpu_count() or 1
        sampler = NegativeSampler(corpus.vocab.counts, config.ns_exponent)
        self._prob = np.ascontiguousarray(
            np.asarray(sampler.table.prob), np.float32
        )
        self._alias = np.ascontiguousarray(
            np.asarray(sampler.table.alias), np.int32
        )

    def init(self, seed: Optional[int] = None) -> SGNSParams:
        cfg = self.config
        rng = np.random.RandomState(cfg.seed if seed is None else seed)
        emb = rng.uniform(
            -0.5 / cfg.dim, 0.5 / cfg.dim, (self.corpus.vocab_size, cfg.dim)
        ).astype(np.float32)
        ctx = np.zeros((self.corpus.vocab_size, cfg.dim), np.float32)
        return SGNSParams(emb=emb, ctx=ctx)

    def train_epoch(
        self, params: SGNSParams, seed: int, rng: Optional[np.random.RandomState] = None
    ):
        """One Hogwild epoch.  Returns ``(updated SGNSParams, loss)``.

        In-place contract: contiguous float32 *numpy* inputs are updated
        in place AND returned; any other input — a JAX array, a
        non-contiguous view, a different dtype — is **copied** first
        (``np.ascontiguousarray``), so the caller's arrays stay untouched
        and only the returned params carry the update.  Always use the
        return value.
        """
        cfg = self.config
        emb = np.ascontiguousarray(np.asarray(params.emb), np.float32)
        ctx = np.ascontiguousarray(np.asarray(params.ctx), np.float32)
        pairs = self.corpus.pairs
        if rng is not None:  # reference reshuffle per iteration
            pairs = pairs[rng.permutation(len(pairs))]
        pairs = np.ascontiguousarray(pairs, np.int32)
        loss = _load().sgns_hogwild_epoch(
            _fptr(emb), _fptr(ctx),
            self.corpus.vocab_size, cfg.dim,
            _iptr(pairs), len(pairs),
            _fptr(self._prob), _iptr(self._alias),
            cfg.negatives, cfg.lr, cfg.min_lr,
            self.n_threads, seed, int(cfg.both_directions),
        )
        return SGNSParams(emb=emb, ctx=ctx), float(loss)

    def run(
        self,
        export_dir: str,
        start_iter: Optional[int] = None,
        log: Callable[[str], None] = print,
        preempt=None,
    ) -> SGNSParams:
        from gene2vec_tpu.obs.run import Run

        cfg = self.config
        # probe_devices=False: this trainer must not initialize a jax
        # backend just to write a manifest.  The buffered native_abi_check
        # span (ambient_span at _load time) flushes into this run's
        # events.jsonl, so the ABI-probe cost is visible per run.
        run = Run(
            export_dir, name="hogwild", config=cfg, probe_devices=False,
            manifest_extra={
                "backend": {"platform": "native-cpu", "threads": self.n_threads},
                "num_pairs": self.corpus.num_pairs,
                "vocab_size": self.corpus.vocab_size,
            },
        )
        run.registry.attach_csv(os.path.join(export_dir, "training_log.csv"))
        # everything after Run construction runs under its finally, so a
        # failed resume still closes the run instead of leaking the
        # ambient tracer into later runs in this process
        try:
            if start_iter is None:
                start_iter = ckpt.latest_iteration(export_dir, cfg.dim) + 1
            if start_iter > 1:
                params, _, _ = ckpt.load_iteration(
                    export_dir, cfg.dim, start_iter - 1,
                    table_dtype="float32",  # this backend computes in f32
                )
                params = SGNSParams(
                    emb=np.asarray(params.emb), ctx=np.asarray(params.ctx)
                )
                log(f"resuming from iteration {start_iter - 1}")
            else:
                params = self.init()
                start_iter = 1
            pairs_counter = run.registry.counter("pairs_total")
            for it in range(start_iter, cfg.num_iters + 1):
                if preempt is not None and preempt.triggered:
                    break
                t0 = time.perf_counter()
                # shuffle stream keyed by (seed, it) so a resumed run shuffles
                # identically to an uninterrupted one (round-1 advisor finding);
                # SeedSequence mixes non-additively so adjacent-seed runs don't
                # share streams (seed=2 iter 1 vs seed=1 iter 2 — round-2
                # advisor finding, same fix as numpy_backend)
                mixed = int(
                    np.random.SeedSequence([cfg.seed, it]).generate_state(1)[0]
                )
                with run.step(
                    "iteration", iteration=it, pairs=self.corpus.num_pairs
                ) as span_out:
                    params, loss = self.train_epoch(
                        params,
                        seed=mixed,
                        rng=np.random.RandomState(mixed),
                    )
                    span_out["loss"] = loss
                dt = time.perf_counter() - t0
                rate = self.corpus.num_pairs / dt if dt > 0 else float("inf")
                pairs_counter.inc(self.corpus.num_pairs)
                log(
                    f"gene2vec [hogwild x{self.n_threads}] dimension {cfg.dim} "
                    f"iteration {it} done: loss={loss:.4f} {rate:,.0f} pairs/s "
                    f"({dt:.2f}s)"
                )
                run.log_row(
                    it, {"loss": loss, "pairs_per_sec": rate, "seconds": dt}
                )
                run.probe()
                with run.span("checkpoint", iteration=it):
                    ckpt.save_iteration(
                        export_dir, cfg.dim, it, params, self.corpus.vocab,
                        txt_output=cfg.txt_output,
                        meta={
                            "loss": loss, "pairs_per_sec": rate,
                            "backend": "hogwild",
                        },
                    )
                if preempt is not None and preempt.triggered:
                    log(
                        f"preemption requested (signal {preempt.received}); "
                        f"drained after iteration {it}"
                    )
                    break
        finally:
            if preempt is not None and preempt.triggered:
                run.mark_interrupted("signal", signal=preempt.received)
            run.close()
        return params
