"""SGNS trainer: whole-epoch jitted scan + reference-shaped iteration loop.

Replaces the driver in ``src/gene2vec.py``: load corpus → shuffle → N
iterations of (reshuffle, 1 training epoch, checkpoint, txt export), with
resume-from-previous-iteration semantics (``src/gene2vec.py:67-92``).

TPU shape: one ``jax.jit`` call per epoch.  The corpus, noise CDF and both
tables live in HBM; the epoch is a ``lax.scan`` over shuffled batches with
the learning rate decaying linearly from ``lr`` to ``min_lr`` across the
epoch — the same per-``train()``-call alpha sweep gensim performs for each
of the reference's 10 iterations.  Buffers are donated, so the tables are
updated in place.  The host does nothing between checkpoints.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time
from typing import TYPE_CHECKING, Callable, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from gene2vec_tpu.config import SGNSConfig
from gene2vec_tpu.data.negative_sampling import NegativeSampler
from gene2vec_tpu.data.pipeline import (
    PairCorpus,
    epoch_shuffle,
    host_preshuffle,
    segment_corpus_by_head,
    segment_corpus_by_head_multihost,
    segmented_epoch_shuffle,
)
from gene2vec_tpu.io import checkpoint as ckpt
from gene2vec_tpu.sgns.model import SGNSParams, init_params
from gene2vec_tpu.sgns.step import sgns_step
from gene2vec_tpu.utils.profiling import StepTimer

if TYPE_CHECKING:  # runtime import would cycle through gene2vec_tpu.parallel
    from gene2vec_tpu.parallel.sharding import SGNSSharding


def _positive_boundaries(config: SGNSConfig):
    """Frequency-band boundaries for the dense-slab positive layout: one
    boundary (head/tail) or two (head/mid/tail) when ``positive_mid`` adds
    the second slab (sgns/step.py round 5)."""
    if config.positive_mid > 0:
        return (config.positive_head, config.positive_head + config.positive_mid)
    return config.positive_head


def make_train_epoch(
    num_pairs: int,
    num_batches: int,
    config: SGNSConfig,
    sharding: Optional["SGNSSharding"] = None,
    stratified=None,
    pos_quotas: Optional[Tuple[int, ...]] = None,
    pos_shards: int = 1,
) -> Callable:
    """Build the jitted epoch function.

    Signature: (params, pairs, noise, key) -> (params, mean_loss).
    All loop structure is static; only array contents are traced.
    ``stratified`` (a StratifiedSpec) is captured in the closure — its
    arrays are per-trainer constants derived from the vocab counts.
    With ``pos_quotas`` (dense positives), ``pairs`` is the tuple of
    class pools from ``segment_corpus_by_head`` — 3 for the head/tail
    layout, 6 for head/mid/tail ([HH|HM|HT|MM|MT|TT]) — with one quota
    per pool, and each batch is assembled as ``pos_shards`` device
    blocks at static per-block quota offsets.
    """
    batch_pairs = config.batch_pairs
    compute_dtype = jnp.dtype(config.compute_dtype)
    positive_head = config.positive_head if pos_quotas is not None else 0
    positive_mid = config.positive_mid if pos_quotas is not None else 0

    def train_epoch(params, pairs, noise, key):
        shuffle_key, step_key = jax.random.split(key)
        if pos_quotas is not None:
            pools = segmented_epoch_shuffle(
                pairs, shuffle_key, pos_quotas, num_batches,
                config.shuffle_mode, enabled=config.shuffle_each_iter,
            )
        else:
            shuffled = epoch_shuffle(
                pairs, shuffle_key, num_pairs, num_batches, batch_pairs,
                config.shuffle_mode, enabled=config.shuffle_each_iter,
            )
            if sharding is not None:
                shuffled = sharding.constrain_batch(shuffled)

        def body(params, step):
            if pos_quotas is not None:
                # [dev0: hh|ht|tt][dev1: hh|ht|tt]… — each data-parallel
                # block carries the same per-class quota slice, so the
                # step's per-block segment offsets hold on every device
                batch = jnp.concatenate(
                    [
                        jax.lax.dynamic_slice_in_dim(
                            pool, step * q, q
                        ).reshape(pos_shards, q // pos_shards, 2)
                        for pool, q in zip(pools, pos_quotas)
                        if q
                    ],
                    axis=1,
                ).reshape(batch_pairs, 2)
            else:
                batch = jax.lax.dynamic_slice_in_dim(
                    shuffled, step * batch_pairs, batch_pairs
                )
            if sharding is not None:
                batch = sharding.constrain_batch(batch)
            frac = step.astype(compute_dtype) / max(num_batches, 1)
            lr = config.lr * (1.0 - frac) + config.min_lr * frac
            params, loss = sgns_step(
                params,
                batch,
                noise,
                jax.random.fold_in(step_key, step),
                lr,
                negatives=config.negatives,
                both_directions=config.both_directions,
                compute_dtype=compute_dtype,
                combiner=config.combiner,
                negative_mode=config.negative_mode,
                shared_pool=config.shared_pool,
                shared_pool_auto=config.shared_pool_auto,
                shared_groups=config.shared_groups,
                strat_group=config.strat_group,
                stratified=stratified,
                positive_head=positive_head,
                positive_mid=positive_mid,
                pos_quotas=pos_quotas,
                pos_shards=pos_shards,
                bf16_stochastic_round=config.bf16_stochastic_round,
                acc_constraint=(
                    sharding.constrain_acc
                    if sharding is not None and sharding.vocab_sharded
                    else None
                ),
            )
            if sharding is not None:
                params = sharding.constrain_params(params)
            return params, loss

        params, losses = jax.lax.scan(
            body, params, jnp.arange(num_batches, dtype=jnp.int32)
        )
        return params, jnp.mean(losses)

    donate = (0,) if config.donate else ()
    return jax.jit(train_epoch, donate_argnums=donate)


def train_epochs(corpus: PairCorpus, config: SGNSConfig, epochs: int):
    """Convenience loop shared by the quality tooling (bench gate,
    experiments/quality_matrix.py, tests) so they all train identically:
    fresh init, one epoch per iteration keyed by fold_in(seed, it).

    Returns (final emb as numpy, per-epoch loss list).
    """
    trainer = SGNSTrainer(corpus, config)
    params = trainer.init()
    losses = []
    for it in range(1, epochs + 1):
        params, loss = trainer.train_epoch(
            params, jax.random.fold_in(jax.random.PRNGKey(config.seed), it)
        )
        losses.append(float(loss))
    return np.asarray(params.emb)[: corpus.vocab_size], losses


class SGNSTrainer:
    """End-to-end trainer over an encoded :class:`PairCorpus`."""

    def __init__(
        self,
        corpus: PairCorpus,
        config: SGNSConfig = SGNSConfig(),
        sharding: Optional["SGNSSharding"] = None,
        full_corpus: Optional[PairCorpus] = None,
    ):
        """``corpus`` is this host's (possibly process-sharded) pair set.
        On multi-host runs, passing ``full_corpus`` (the UN-sharded
        corpus every host already read — docs/DISTRIBUTED.md data
        feeding) additionally enables dense-head positives: the static
        segment quotas derive from the full corpus, so every host
        compiles the same batch layout.  Ignored on single-host runs.
        """
        if corpus.num_pairs == 0 or corpus.vocab_size == 0:
            raise ValueError(
                "corpus is empty — no pair lines matched the source "
                "directory/pattern (or min_count filtered every token)"
            )
        if config.objective != "sgns":
            raise NotImplementedError(
                f"objective={config.objective!r}: use CBOWHSTrainer from "
                "gene2vec_tpu.sgns.cbow_hs for the cbow/hierarchical-softmax "
                "variants"
            )
        if sharding is not None:
            # even row count per data shard is required to device_put the
            # corpus with a sharded axis
            corpus = corpus.pad_to_multiple(sharding.mesh.shape[sharding.data_axis])
        # multi-host SPMD: `corpus` is this host's equal-length shard
        # (docs/DISTRIBUTED.md) but the jitted epoch runs against the
        # GLOBAL pair array, so pair/batch counts derive from the global
        # row count — identical on every host because process_shard trims
        # shards to equal length
        self._procs = jax.process_count() if sharding is not None else 1
        if corpus.num_pairs * self._procs < config.batch_pairs:
            # shrink the batch rather than failing on tiny corpora
            # (the reference smoke corpus data/test.txt has 39 pairs)
            config = dataclasses.replace(
                config, batch_pairs=max(1, corpus.num_pairs * self._procs)
            )
        if config.shuffle_mode not in ("offset", "full"):
            raise ValueError(f"unknown shuffle_mode {config.shuffle_mode!r}")
        config, self.pos_shards = self._resolve_positive_head(
            config, corpus, sharding, full_corpus=full_corpus,
        )
        dense_multihost = config.positive_head > 0 and self._procs > 1
        if config.shuffle_mode == "offset" and not dense_multihost:
            # one-time host-side shuffle, unconditional like the reference's
            # pre-training random.shuffle (src/gene2vec.py:52); per-epoch
            # decorrelation then needs no per-row device gathers.  The
            # dense multi-host path preshuffles full_corpus instead — its
            # device arrays derive from that, never from the local shard.
            corpus = host_preshuffle(corpus, config.seed)
        self.pos_quotas = None
        self.config = config
        self.corpus = corpus
        self.sharding = sharding
        self.sampler = NegativeSampler(corpus.vocab.counts, config.ns_exponent)
        self.global_num_pairs = corpus.num_pairs * self._procs
        self.num_batches = self.global_num_pairs // config.batch_pairs

        if dense_multihost:
            # multi-host dense head: quotas and num_batches derive from
            # the FULL corpus (identical on every host), each host keeps
            # deterministic-length local pool shards, and the pools
            # assemble into global row-sharded arrays
            assert full_corpus is not None  # gated in _resolve_positive_head
            fc = full_corpus
            if config.shuffle_mode == "offset":
                fc = host_preshuffle(fc, config.seed)
            local_pools, self.pos_quotas, self.num_batches = (
                segment_corpus_by_head_multihost(
                    fc.pairs, _positive_boundaries(config),
                    config.batch_pairs, self.pos_shards,
                    jax.process_index(), self._procs,
                )
            )
            self.global_num_pairs = self.num_batches * config.batch_pairs
            self.pairs = tuple(
                jax.make_array_from_process_local_data(
                    sharding.corpus_sharding(), p
                )
                if len(p)
                else jnp.asarray(p)
                for p in local_pools
            )
        elif config.positive_head > 0:
            pools, self.pos_quotas = segment_corpus_by_head(
                corpus.pairs, _positive_boundaries(config),
                config.batch_pairs, multiple=self.pos_shards,
            )
            if sharding is not None:
                # pools live row-sharded over data like the plain corpus
                # path (replicating the corpus would cost pairs-bytes per
                # device at 100M+ pair scale); the per-step batch slice is
                # re-sharded into per-device blocks by constrain_batch.
                # Pool lengths are already multiples of pos_shards
                # (segment_corpus_by_head pads them, so a layout-pinned
                # single-device reference shuffles identical pools).
                self.pairs = tuple(
                    jax.device_put(p, sharding.corpus_sharding())
                    for p in pools
                )
            else:
                self.pairs = tuple(jnp.asarray(p) for p in pools)
        elif sharding is not None and self._procs > 1:
            # per-host shards assemble into ONE global row-sharded array;
            # device_put would require identical values on every host
            self.pairs = jax.make_array_from_process_local_data(
                sharding.corpus_sharding(), corpus.pairs
            )
        elif sharding is not None:
            self.pairs = corpus.device_pairs(sharding.corpus_sharding())
        else:
            self.pairs = corpus.device_pairs()
        if sharding is not None:
            self.noise = jax.device_put(self.sampler.table, sharding.replicated())
        else:
            self.noise = self.sampler.table

        # vocab-sharded tables need a row count divisible by the model
        # axis; pad with zero rows that never train (no pair, noise or
        # slab mass reaches ids >= vocab_size) and are sliced off at
        # export (config 5 at the real 24,447-gene vocab on an 8-way mesh)
        self.padded_vocab = corpus.vocab_size
        if sharding is not None and sharding.vocab_sharded:
            m = int(sharding.mesh.shape[sharding.model_axis])
            self.padded_vocab = -(-corpus.vocab_size // m) * m

        self.stratified = None
        if config.negative_mode == "stratified":
            from gene2vec_tpu.data.negative_sampling import (
                build_stratified_spec,
            )

            self.stratified = build_stratified_spec(
                corpus.vocab.counts, config.strat_head, config.strat_block,
                config.ns_exponent,
            )
            if sharding is not None:
                self.stratified = jax.device_put(
                    self.stratified, sharding.replicated()
                )

        self._epoch_fn = make_train_epoch(
            self.global_num_pairs, self.num_batches, self.config, sharding,
            stratified=self.stratified, pos_quotas=self.pos_quotas,
            pos_shards=self.pos_shards,
        )
        self.timer = StepTimer()

    @staticmethod
    def _resolve_positive_head(
        config, corpus, sharding, full_corpus=None
    ):
        """Gate the dense-head positive path: returns (config, pos_shards)
        with ``positive_head`` clamped to the vocab, or set to 0 (with a
        warning) when the class-segmented batch layout cannot apply.  The
        layout needs stratified + both-direction training with replicated
        tables, and a batch cuttable into uniform per-device [HH|HT|TT]
        blocks.  Multi-host runs additionally need ``full_corpus`` so the
        static quotas derive from global data — per-host shards would
        derive mismatched quotas and deadlock the collectives, the
        failure class process_shard's equal-length trim prevents for
        num_batches (docs/DISTRIBUTED.md)."""
        import warnings

        def disabled(msg):
            warnings.warn(
                f"positive_head (dense-head positives) disabled: {msg}",
                stacklevel=3,
            )
            return dataclasses.replace(
                config, positive_head=0, positive_mid=0
            ), 1

        if config.positive_head <= 0:
            if 0 < config.positive_mid != type(config)().positive_mid:
                # only an EXPLICIT non-default mid deserves the warning —
                # positive_head=0 alone must not complain about the
                # default mid the user never touched
                warnings.warn(
                    "positive_mid > 0 has no effect without positive_head "
                    "> 0 (the mid slab extends the dense-head batch "
                    "layout); running the plain-gather path",
                    stacklevel=3,
                )
            return dataclasses.replace(config, positive_mid=0), 1
        if config.negative_mode != "stratified" or not config.both_directions:
            # silent: these configs never supported the dense path
            return dataclasses.replace(
                config, positive_head=0, positive_mid=0
            ), 1
        if jax.process_count() > 1 and full_corpus is None:
            return disabled(
                "multi-host run without full_corpus — per-host corpus "
                "shards would derive mismatched segment quotas; pass the "
                "un-sharded corpus as SGNSTrainer(..., full_corpus=...) "
                "to enable (docs/DISTRIBUTED.md)"
            )
        # Vocab-sharded tables run the dense path too (round 5): at the
        # default geometry the head+mid slabs (~2.5k rows) fit inside model
        # shard 0, so ``table[lo:hi]`` lowers to a broadcast of that
        # shard's prefix and the slab scatter lands back on it; when a
        # slab does span shard boundaries XLA stitches it from the
        # owners.  Loss parity vs the unsharded layout-pinned reference is
        # pinned in tests/test_parallel.py::test_sharded_matches_unsharded.
        shards = 1
        if sharding is not None:
            shards = int(sharding.mesh.shape[sharding.data_axis])
        if config.pos_layout_shards > 0:
            # explicit layout override (sharded-vs-unsharded parity tests
            # reproduce a mesh layout on one device)
            shards = config.pos_layout_shards
        head = min(config.positive_head, corpus.vocab_size)
        mid = min(max(config.positive_mid, 0), corpus.vocab_size - head)
        # every NON-EMPTY class-pair pool needs quota >= shards, so the
        # batch must cover shards x (pools actually present in the pairs
        # the segmentation will classify — the FULL corpus on multi-host
        # runs, where a class pair absent from one host's shard but
        # present globally must not make hosts' gates diverge (they would
        # compile different programs and deadlock the collectives)
        seg_pairs = (
            full_corpus.pairs if full_corpus is not None else corpus.pairs
        )

        def pools_present(bounds):
            # chunked with early exit: one pass over a 100M-pair corpus
            # only when some pool really is near-empty
            n_classes = len(bounds) + 1
            limit = n_classes * (n_classes + 1) // 2
            present = set()
            for lo in range(0, len(seg_pairs), 1 << 20):
                c = np.searchsorted(
                    bounds, seg_pairs[lo : lo + (1 << 20)], side="right"
                )
                present.update(
                    np.unique(c.min(axis=1) * n_classes + c.max(axis=1))
                    .tolist()
                )
                if len(present) == limit:
                    break
            return len(present)

        if config.batch_pairs % shards:
            return disabled(
                f"batch_pairs={config.batch_pairs} cannot form {shards} "
                f"uniform device blocks (needs a multiple of {shards})"
            )
        if mid > 0:
            n_pools = pools_present(
                np.asarray((head, head + mid), dtype=np.int64)
            )
            if config.batch_pairs < n_pools * shards:
                # the 6-class layout does not fit this batch — fall back
                # to the round-4 2-class head-only layout before giving
                # up on dense positives entirely
                warnings.warn(
                    f"positive_mid disabled: batch_pairs="
                    f"{config.batch_pairs} cannot cover the corpus's "
                    f"{n_pools} head/mid/tail pools x {shards} device "
                    "blocks; falling back to the 2-class head-only "
                    "layout",
                    stacklevel=3,
                )
                mid = 0
        if mid == 0:
            n_pools = pools_present(np.asarray((head,), dtype=np.int64))
            if config.batch_pairs < n_pools * shards:
                return disabled(
                    f"batch_pairs={config.batch_pairs} cannot form "
                    f"{shards} uniform class-segmented device blocks over "
                    f"the corpus's {n_pools} class pools (needs at least "
                    f"{n_pools * shards})"
                )
        return (
            dataclasses.replace(config, positive_head=head, positive_mid=mid),
            shards,
        )

    # -- params ------------------------------------------------------------

    def init(self, seed: Optional[int] = None) -> SGNSParams:
        key = jax.random.PRNGKey(self.config.seed if seed is None else seed)
        if self.sharding is not None:
            init_fn = jax.jit(
                functools.partial(
                    init_params,
                    vocab_size=self.padded_vocab,
                    dim=self.config.dim,
                    dtype=jnp.dtype(self.config.table_dtype),
                ),
                out_shardings=self.sharding.params_sharding(),
            )
            return init_fn(key)
        return init_params(
            key,
            self.padded_vocab,
            self.config.dim,
            jnp.dtype(self.config.table_dtype),
        )

    def _pad_params(self, params: SGNSParams) -> SGNSParams:
        """Re-pad checkpoint-loaded (logical-vocab) tables to the sharded
        row multiple; inverse of the export-time slice."""
        pad = self.padded_vocab - params.emb.shape[0]
        if pad <= 0:
            return params

        def f(t):
            t = jnp.asarray(t)
            return jnp.concatenate(
                [t, jnp.zeros((pad, t.shape[1]), t.dtype)]
            )

        return SGNSParams(emb=f(params.emb), ctx=f(params.ctx))

    def _export_params(self, params: SGNSParams) -> SGNSParams:
        """Slice padding rows off for checkpoint/export (no-op unpadded)."""
        v = self.corpus.vocab_size
        if params.emb.shape[0] == v:
            return params
        return SGNSParams(emb=params.emb[:v], ctx=params.ctx[:v])

    # -- training ----------------------------------------------------------

    def train_epoch(
        self, params: SGNSParams, epoch_key: jax.Array
    ) -> Tuple[SGNSParams, float]:
        params, loss = self._epoch_fn(params, self.pairs, self.noise, epoch_key)
        return params, loss

    def _ckpt_meta(self, run, it: int, loss: float, rate: float) -> dict:
        cfg = self.config
        return {
            "loss": loss,
            "pairs_per_sec": rate,
            "config_hash": run.manifest.get("config_hash"),
            # RNG lineage + cursor: iteration N trains with epoch key
            # fold_in(PRNGKey(seed), N) over a corpus preshuffled by
            # `seed`, so (seed, iteration) is the COMPLETE data/RNG
            # cursor — a resumed run replays the exact stream an
            # uninterrupted one would (the chaos drill's bit-exactness
            # contract, docs/RESILIENCE.md)
            "rng": {
                "seed": cfg.seed,
                "epoch_key": f"fold_in(PRNGKey({cfg.seed}), iteration)",
            },
        }

    def _checkpoint(self, writer, export_dir, it, params, meta) -> None:
        """Commit iteration ``it``: inline when ``writer`` is None, else
        stage a host copy (the device→host half of the double buffer —
        it must happen before the next epoch donates these buffers) and
        hand the disk half to the background writer."""
        cfg = self.config
        exported = self._export_params(params)
        if writer is None:
            ckpt.save_iteration(
                export_dir, cfg.dim, it, exported, self.corpus.vocab,
                txt_output=cfg.txt_output, meta=meta,
            )
            return
        # copy=True is load-bearing: np.asarray of a CPU-backed jax array
        # can be a zero-copy VIEW of the device buffer, and the next
        # epoch donates that buffer (donate_argnums) — an aliased "host
        # copy" would let the writer serialize bytes XLA is overwriting,
        # and the manifest would CRC-stamp the corruption as valid
        host = SGNSParams(
            emb=np.array(exported.emb, copy=True),
            ctx=np.array(exported.ctx, copy=True),
        )

        def write() -> Optional[int]:
            from gene2vec_tpu.resilience import snapshot as snap

            path = ckpt.save_iteration(
                export_dir, cfg.dim, it, host, self.corpus.vocab,
                txt_output=cfg.txt_output, meta=meta,
            )
            # the writer verifies its own commit; the byte count feeds
            # ckpt_bytes_total
            res = snap.verify_manifest(path[: -len(".npz")])
            if not res:
                raise IOError(
                    f"checkpoint iteration {it} failed post-write "
                    f"verification: {res.reason}"
                )
            return snap.manifest_bytes(res.manifest)

        writer.submit(write, iteration=it)

    def run(
        self,
        export_dir: str,
        start_iter: Optional[int] = None,
        log: Callable[[str], None] = print,
        profile_dir: Optional[str] = None,
        preempt=None,
    ) -> SGNSParams:
        """The reference iteration loop: resume from the last saved
        iteration if present, else init fresh; each iteration reshuffles
        (a fresh PRNG fold), trains one epoch, checkpoints and exports.

        ``profile_dir`` wraps the first post-resume epoch in a
        ``jax.profiler`` trace.  Per-iteration metrics (loss, pairs/sec)
        append to ``<export_dir>/training_log.csv``; the full observed
        run (``manifest.json`` + ``events.jsonl`` + ``metrics.prom``)
        lands in the same directory (docs/OBSERVABILITY.md).

        With ``config.async_checkpoint`` the per-iteration save runs on
        the resilience double-buffered writer (disk I/O overlaps the
        next epoch; ``ckpt_*`` metrics quantify the residue).

        ``preempt`` (a :class:`gene2vec_tpu.resilience.preempt.
        PreemptionHandler`) makes the loop drain cooperatively: the
        current iteration finishes, its checkpoint commits, the run
        manifest is stamped ``interrupted=true``, and the method returns
        normally — the caller maps :attr:`preempt.triggered` to
        ``EXIT_PREEMPTED`` (docs/RESILIENCE.md).
        """
        import contextlib

        from gene2vec_tpu.obs import goodput
        from gene2vec_tpu.obs.run import Run
        from gene2vec_tpu.obs.timeline import TIMELINE_NAME, PhaseTimeline
        from gene2vec_tpu.utils.profiling import trace_context

        cfg = self.config
        run = Run(
            export_dir, name="sgns", config=cfg,
            manifest_extra={
                "num_pairs": self.global_num_pairs,
                "vocab_size": self.corpus.vocab_size,
                "num_batches": self.num_batches,
                "pos_quotas": list(self.pos_quotas) if self.pos_quotas else None,
            },
        )
        run.registry.attach_csv(os.path.join(export_dir, "training_log.csv"))
        writer = None
        if cfg.async_checkpoint:
            from gene2vec_tpu.resilience.async_writer import (
                AsyncCheckpointWriter,
            )

            writer = AsyncCheckpointWriter(metrics=run.registry)
        # step-phase timeline (obs/timeline.py): per-iteration host /
        # dispatch / compute / checkpoint-staging breakdown into a
        # bounded ring, flushed to timeline.jsonl at run close and
        # classified into goodput buckets for the manifest
        tl = PhaseTimeline(enabled=cfg.timeline)
        # kernel cost attribution (obs/profiler.py): one AOT
        # lower+compile of the epoch step at startup, one float add per
        # epoch after that — never per batch inside the scan (the
        # profiler-hook-in-jit gate)
        kp = None
        if cfg.kernel_profile:
            from gene2vec_tpu.obs.profiler import KernelProfiler

            kp = KernelProfiler(
                run_dir=export_dir, registry=run.registry
            )
        wall_t0 = time.perf_counter()
        pairs_done = 0.0
        best_rate = 0.0
        completed = None
        # everything after Run construction runs under its finally, so a
        # failed resume still closes the run (and uninstalls the ambient
        # tracer) instead of leaking it into later runs in this process
        try:
            if start_iter is None:
                start_iter = ckpt.latest_iteration(export_dir, cfg.dim) + 1
            if start_iter > 1:
                with run.span("resume", iteration=start_iter - 1):
                    params, _, _ = ckpt.load_iteration(
                        export_dir, cfg.dim, start_iter - 1,
                        table_dtype=cfg.table_dtype,
                    )
                    params = self._pad_params(params)
                log(f"resuming from iteration {start_iter - 1}")
                completed = start_iter - 1
            else:
                with run.span("init_params"):
                    params = self.init()
                start_iter = 1

            root_key = jax.random.PRNGKey(cfg.seed)
            if kp is not None:
                with run.span("kernel_attribution", kernel="sgns_train_step"):
                    kp.attribute(
                        "sgns_train_step", self._epoch_fn,
                        (params, self.pairs, self.noise,
                         jax.random.fold_in(root_key, 0)),
                    )
            pairs_per_epoch = self.num_batches * cfg.batch_pairs
            pairs_counter = run.registry.counter("pairs_total")
            for it in range(start_iter, cfg.num_iters + 1):
                if preempt is not None and preempt.triggered:
                    break  # signal landed between iterations
                log(f"gene2vec dimension {cfg.dim} iteration {it} start")
                t0 = time.perf_counter()
                with tl.phase("host_ingest", step=it):
                    epoch_key = jax.random.fold_in(root_key, it)
                with trace_context(profile_dir if it == start_iter else None):
                    with run.step(
                        "iteration", iteration=it, pairs=pairs_per_epoch
                    ) as span_out:
                        with tl.phase("dispatch", step=it):
                            params, loss = self.train_epoch(
                                params, epoch_key
                            )
                        with tl.phase("compute", step=it):
                            loss = float(loss)  # blocks until epoch finishes
                        span_out["loss"] = loss
                dt = time.perf_counter() - t0
                rate = pairs_per_epoch / dt if dt > 0 else float("inf")
                if kp is not None:
                    kp.observe("sgns_train_step", dt)
                self.timer.record(pairs_per_epoch, dt)
                pairs_counter.inc(pairs_per_epoch)
                pairs_done += pairs_per_epoch
                if dt > 0 and it != start_iter:
                    # peak excludes the compile/relayout first iteration
                    best_rate = max(best_rate, rate)
                log(
                    f"gene2vec dimension {cfg.dim} iteration {it} done: "
                    f"loss={loss:.4f} {rate:,.0f} pairs/s ({dt:.2f}s)"
                )
                run.log_row(
                    it, {"loss": loss, "pairs_per_sec": rate, "seconds": dt}
                )
                run.probe()
                with run.span(
                    "checkpoint", iteration=it,
                    mode="async" if writer is not None else "sync",
                ):
                    with tl.phase("ckpt_stage", step=it):
                        self._checkpoint(
                            writer, export_dir, it, params,
                            self._ckpt_meta(run, it, loss, rate),
                        )
                completed = it
                if preempt is not None and preempt.triggered:
                    # cooperative drain: the iteration and its checkpoint
                    # are committed; stop here instead of starting work
                    # the grace window cannot fit
                    log(
                        f"preemption requested (signal {preempt.received}); "
                        f"drained after iteration {it}"
                    )
                    break
            if writer is not None:
                writer.close()  # surface any background write error
        finally:
            if writer is not None:
                # error-path cleanup: still drain staged writes (the last
                # committed checkpoint is the resume point), but never
                # mask the in-flight exception
                with contextlib.suppress(Exception):
                    writer.close()
            if preempt is not None and preempt.triggered:
                run.mark_interrupted(
                    "signal",
                    signal=preempt.received,
                    completed_iteration=completed,
                )
            # goodput + timeline are observability residue — they must
            # never mask the in-flight exception (same discipline as the
            # writer drain above)
            with contextlib.suppress(Exception):
                wall_s = time.perf_counter() - wall_t0
                preempted_s = 0.0
                if (
                    preempt is not None and preempt.triggered
                    and preempt.received_wall is not None
                ):
                    preempted_s = min(
                        max(time.time() - preempt.received_wall, 0.0), wall_s
                    )
                tl.flush(os.path.join(run.run_dir, TIMELINE_NAME))
                if kp is not None:
                    kp.flush()
                goodput.stamp(run, goodput.summarize(
                    tl.records(), wall_s, pairs_total=pairs_done,
                    peak_pairs_per_sec=best_rate or None,
                    preempted_s=preempted_s,
                    kernel_seconds=(
                        kp.attributed_seconds() if kp is not None
                        else None
                    ),
                ))
            run.close()
        return params
