from gene2vec_tpu.sgns.model import SGNSParams, init_params  # noqa: F401
from gene2vec_tpu.sgns.step import sgns_loss_and_grads, sgns_step  # noqa: F401
from gene2vec_tpu.sgns.train import SGNSTrainer  # noqa: F401
