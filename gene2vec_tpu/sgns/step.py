"""The SGNS training step — the framework's hot loop.

This replaces gensim's Cython/Hogwild inner loop (the engine behind
``src/gene2vec.py:70,87``): per (center, context) pair, gather rows, draw k
negatives from the unigram^0.75 table, sigmoid dot-products, SGD row updates.

TPU-first formulation:

* a batch of B corpus pairs becomes 2B training examples — each pair is a
  2-token "sentence" with window=1 (SURVEY §2.2 #1), so skip-gram
  degenerates to symmetric pair prediction and we emit both directions
  explicitly;
* gradients are closed-form (the loss is a sum of log-sigmoids of rank-1
  dots — autodiff would materialize the same expressions with more
  bookkeeping), applied with deterministic ``.at[].add`` scatter-adds.
  Duplicate indices within a batch sum their contributions — the
  deterministic analogue of gensim's benign Hogwild races (SURVEY §7 hard
  part 1);
* negatives that collide with the positive target are masked out of loss and
  update (gensim skips them; a resampling loop would be data-dependent
  control flow XLA can't tile).

Everything is shape-static and jit-safe; under a Mesh the same code runs
data-parallel (sharded batch, replicated tables → XLA all-reduces the
scatter updates) or row-parallel (vocab-sharded tables → XLA turns
gather/scatter into ICI collectives). See gene2vec_tpu/parallel/sharding.py.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from gene2vec_tpu.data.negative_sampling import sample_negatives
from gene2vec_tpu.sgns.model import SGNSParams


def _examples_from_pairs(
    pairs: jax.Array, both_directions: bool = True
) -> Tuple[jax.Array, jax.Array]:
    """(B, 2) pairs → (E,) centers, (E,) contexts with E = 2B (or B)."""
    if both_directions:
        centers = jnp.concatenate([pairs[:, 0], pairs[:, 1]])
        contexts = jnp.concatenate([pairs[:, 1], pairs[:, 0]])
    else:
        centers, contexts = pairs[:, 0], pairs[:, 1]
    return centers, contexts


def sgns_loss_and_grads(
    params: SGNSParams,
    centers: jax.Array,   # (E,) int32
    contexts: jax.Array,  # (E,) int32
    negatives: jax.Array, # (E, K) int32
    compute_dtype=jnp.float32,
):
    """Per-example loss and closed-form row gradients.

    Returns (loss_mean, (d_center (E,D), d_pos (E,D), d_neg (E,K,D), neg_mask)).
    """
    emb, ctx = params.emb, params.ctx
    v = emb[centers].astype(compute_dtype)        # (E, D)
    u_pos = ctx[contexts].astype(compute_dtype)   # (E, D)
    u_neg = ctx[negatives].astype(compute_dtype)  # (E, K, D)

    pos_logit = jnp.sum(v * u_pos, axis=-1)                    # (E,)
    neg_logit = jnp.einsum("ed,ekd->ek", v, u_neg)             # (E, K)

    # gensim skips a negative equal to the positive target; we zero it.
    neg_mask = (negatives != contexts[:, None]).astype(compute_dtype)

    # loss = -log σ(pos) - Σ_k log σ(-neg_k)
    loss = jax.nn.softplus(-pos_logit) + jnp.sum(
        neg_mask * jax.nn.softplus(neg_logit), axis=-1
    )

    g_pos = jax.nn.sigmoid(pos_logit) - 1.0                    # (E,)  dL/dpos_logit
    g_neg = jax.nn.sigmoid(neg_logit) * neg_mask               # (E, K)

    d_center = g_pos[:, None] * u_pos + jnp.einsum("ek,ekd->ed", g_neg, u_neg)
    d_pos = g_pos[:, None] * v
    d_neg = g_neg[:, :, None] * v[:, None, :]
    return jnp.mean(loss), (d_center, d_pos, d_neg)


def sgns_step(
    params: SGNSParams,
    pairs: jax.Array,  # (B, 2) int32
    cdf: jax.Array,    # (V,) noise CDF
    key: jax.Array,
    lr: jax.Array,
    negatives: int = 5,
    both_directions: bool = True,
    compute_dtype=jnp.float32,
) -> Tuple[SGNSParams, jax.Array]:
    """One fused SGD step over a batch of corpus pairs."""
    centers, contexts = _examples_from_pairs(pairs, both_directions)
    negs = sample_negatives(cdf, key, (centers.shape[0], negatives))

    loss, (d_center, d_pos, d_neg) = sgns_loss_and_grads(
        params, centers, contexts, negs, compute_dtype
    )

    dtype = params.emb.dtype
    lr = jnp.asarray(lr, compute_dtype)
    emb = params.emb.at[centers].add((-lr * d_center).astype(dtype))
    ctx = params.ctx.at[contexts].add((-lr * d_pos).astype(dtype))
    ctx = ctx.at[negs.reshape(-1)].add(
        (-lr * d_neg).reshape(-1, d_neg.shape[-1]).astype(dtype)
    )
    return SGNSParams(emb=emb, ctx=ctx), loss
