"""The SGNS training step — the framework's hot loop.

This replaces gensim's Cython/Hogwild inner loop (the engine behind
``src/gene2vec.py:70,87``): per (center, context) pair, gather rows, draw k
negatives from the unigram^0.75 table, sigmoid dot-products, SGD row updates.

TPU-first formulation:

* a batch of B corpus pairs becomes 2B training examples — each pair is a
  2-token "sentence" with window=1 (SURVEY §2.2 #1), so skip-gram
  degenerates to symmetric pair prediction and we emit both directions
  explicitly;
* gradients are closed-form (the loss is a sum of log-sigmoids of rank-1
  dots — autodiff would materialize the same expressions with more
  bookkeeping), applied with deterministic ``.at[].add`` scatter-adds.
  Duplicate indices within a batch combine via ``combiner`` (default
  ``"capped"``): plain summing matches sequential SGD for typical duplicate
  counts but diverges when a hot token appears thousands of times per batch
  (all those gradients are evaluated at the same stale parameter value —
  gensim never hits this because its Hogwild loop applies updates one pair
  at a time), so the per-row sum is capped at C x mean (see
  :func:`_row_divisor`, SURVEY §7 hard part 1).  ``combiner="sum"``
  restores raw summing for small-batch oracle comparisons;
* negatives that collide with the positive target are masked out of loss and
  update (gensim skips them; a resampling loop would be data-dependent
  control flow XLA can't tile);
* by default negatives are **shared across the batch** (``negative_mode=
  "shared"``): one pool of P = ``shared_pool`` noise draws per step (each
  example's negative term is the pool mean importance-weighted by K/P, an
  unbiased estimate of the K-negative SGNS objective), so the negative
  logits are a single (E, D) x (D, P) MXU matmul and the negative update is
  a (P, E) x (E, D) matmul scattered into just P rows — versus a
  per-example (E, K, D) gather plus an E*K-row scatter, which profiling
  showed dominated the step.  ``negative_mode="per_example"`` keeps
  gensim's exact per-example draws for oracle comparisons.

Everything is shape-static and jit-safe; under a Mesh the same code runs
data-parallel (sharded batch, replicated tables → XLA all-reduces the
scatter updates) or row-parallel (vocab-sharded tables → XLA turns
gather/scatter into ICI collectives). See gene2vec_tpu/parallel/sharding.py.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from gene2vec_tpu.data.negative_sampling import NoiseTable, sample_negatives
from gene2vec_tpu.sgns.model import SGNSParams


def _examples_from_pairs(
    pairs: jax.Array, both_directions: bool = True
) -> Tuple[jax.Array, jax.Array]:
    """(B, 2) pairs → (E,) centers, (E,) contexts with E = 2B (or B)."""
    if both_directions:
        centers = jnp.concatenate([pairs[:, 0], pairs[:, 1]])
        contexts = jnp.concatenate([pairs[:, 1], pairs[:, 0]])
    else:
        centers, contexts = pairs[:, 0], pairs[:, 1]
    return centers, contexts


def sgns_loss_and_grads(
    params: SGNSParams,
    centers: jax.Array,   # (E,) int32
    contexts: jax.Array,  # (E,) int32
    negatives: jax.Array, # (E, K) int32
    compute_dtype=jnp.float32,
):
    """Per-example loss and closed-form row gradients.

    Returns (loss_mean, (d_center (E,D), d_pos (E,D), d_neg (E,K,D)), neg_mask).
    """
    emb, ctx = params.emb, params.ctx
    v = emb[centers].astype(compute_dtype)        # (E, D)
    u_pos = ctx[contexts].astype(compute_dtype)   # (E, D)
    u_neg = ctx[negatives].astype(compute_dtype)  # (E, K, D)

    pos_logit = jnp.sum(v * u_pos, axis=-1)                    # (E,)
    neg_logit = jnp.einsum("ed,ekd->ek", v, u_neg)             # (E, K)

    # gensim skips a negative equal to the positive target; we zero it.
    neg_mask = (negatives != contexts[:, None]).astype(compute_dtype)

    # loss = -log σ(pos) - Σ_k log σ(-neg_k)
    loss = jax.nn.softplus(-pos_logit) + jnp.sum(
        neg_mask * jax.nn.softplus(neg_logit), axis=-1
    )

    g_pos = jax.nn.sigmoid(pos_logit) - 1.0                    # (E,)  dL/dpos_logit
    g_neg = jax.nn.sigmoid(neg_logit) * neg_mask               # (E, K)

    d_center = g_pos[:, None] * u_pos + jnp.einsum("ek,ekd->ed", g_neg, u_neg)
    d_pos = g_pos[:, None] * v
    d_neg = g_neg[:, :, None] * v[:, None, :]
    return jnp.mean(loss), (d_center, d_pos, d_neg), neg_mask


_CAP = 32.0  # "capped": sum up to this many duplicates, then scale as C x mean


def _row_divisor(cnt: jax.Array, combiner: str) -> jax.Array:
    """Divisor applied to each example's gradient given its row's duplicate
    count within the batch.

    * ``"sum"``    — 1 (sequential-SGD-like; diverges when a hot token is
      duplicated thousands of times per batch, since all those gradients are
      evaluated at the same stale parameter value);
    * ``"mean"``   — cnt (always stable, but under-trains hot rows: a row
      advances one averaged step per batch no matter how often it occurred);
    * ``"capped"`` — max(cnt / C, 1): exact sum while a row has at most
      C = 32 duplicates (bitwise-equal to "sum" on typical corpora), smoothly
      capped at C x mean beyond, which keeps the hot-row step bounded at any
      batch size.  The default (SURVEY §7 hard part 1).
    """
    cnt = jnp.maximum(cnt, 1.0)
    if combiner == "sum":
        return jnp.ones_like(cnt)
    if combiner == "mean":
        return cnt
    if combiner == "capped":
        return jnp.maximum(cnt / _CAP, 1.0)
    raise ValueError(f"unknown combiner {combiner!r}")


def _apply_row_updates(
    table: jax.Array,        # (V, D)
    idx: jax.Array,          # (R,) row per gradient
    grads: jax.Array,        # (R, D)
    weights: jax.Array,      # (R,) occurrence weight per gradient row
    lr: jax.Array,
    combiner: str,
    compute_dtype,
) -> jax.Array:
    """table − lr · combined row updates, via ONE fused scatter.

    Gradients and occurrence weights scatter together into a (V, D+1)
    accumulator — one scatter instead of a count scatter + count gather +
    grad scatter (profiling showed scatter count, not scatter payload,
    dominates) — and the combiner divisor is applied row-wise on the dense
    accumulator afterwards.  Weights accumulate in f32 via the accumulator's
    dtype; see :func:`_row_divisor` for the combiner semantics.
    """
    v, d = table.shape
    acc_dtype = jnp.float32 if compute_dtype == jnp.bfloat16 else compute_dtype
    payload = jnp.concatenate(
        [grads.astype(acc_dtype), weights.astype(acc_dtype)[:, None]], axis=1
    )
    acc = jnp.zeros((v, d + 1), acc_dtype).at[idx].add(payload)
    update = acc[:, :d] / _row_divisor(acc[:, d], combiner)[:, None]
    lr = jnp.asarray(lr, acc_dtype)
    return (table.astype(acc_dtype) - lr * update).astype(table.dtype)


def _step_per_example(
    params: SGNSParams,
    centers: jax.Array,
    contexts: jax.Array,
    negs: jax.Array,  # (E, K)
    lr: jax.Array,
    compute_dtype,
    combiner: str,
) -> Tuple[SGNSParams, jax.Array]:
    loss, (d_center, d_pos, d_neg), neg_mask = sgns_loss_and_grads(
        params, centers, contexts, negs, compute_dtype
    )
    d = d_center.shape[-1]
    emb = _apply_row_updates(
        params.emb,
        centers,
        d_center,
        jnp.ones_like(centers, compute_dtype),
        lr,
        combiner,
        compute_dtype,
    )
    ctx = _apply_row_updates(
        params.ctx,
        jnp.concatenate([contexts, negs.reshape(-1)]),
        jnp.concatenate([d_pos, d_neg.reshape(-1, d)]),
        jnp.concatenate(
            [jnp.ones_like(contexts, compute_dtype), neg_mask.reshape(-1)]
        ),
        lr,
        combiner,
        compute_dtype,
    )
    return SGNSParams(emb=emb, ctx=ctx), loss


def _step_shared(
    params: SGNSParams,
    centers: jax.Array,   # (E,)
    contexts: jax.Array,  # (E,)
    negs: jax.Array,      # (P,) — one noise pool for the whole batch
    k_negatives: int,     # the objective's K (negative-term weight)
    lr: jax.Array,
    compute_dtype,
    combiner: str,
) -> Tuple[SGNSParams, jax.Array]:
    emb_t, ctx_t = params.emb, params.ctx
    vocab_size = emb_t.shape[0]
    v = emb_t[centers].astype(compute_dtype)      # (E, D)
    u_pos = ctx_t[contexts].astype(compute_dtype) # (E, D)
    u_neg = ctx_t[negs].astype(compute_dtype)     # (P, D)

    pos_logit = jnp.sum(v * u_pos, axis=-1)                     # (E,)
    neg_logit = v @ u_neg.T                                     # (E, P) — MXU
    neg_mask = (negs[None, :] != contexts[:, None]).astype(compute_dtype)

    # The pool holds P >= K draws for vocab coverage; weighting the mean of
    # P noise terms by K keeps the SGNS objective's negative-term weight
    # unchanged in expectation (a K/P importance weight per draw).
    scale = jnp.asarray(k_negatives / negs.shape[0], compute_dtype)
    loss = jax.nn.softplus(-pos_logit) + scale * jnp.sum(
        neg_mask * jax.nn.softplus(neg_logit), axis=-1
    )
    g_pos = jax.nn.sigmoid(pos_logit) - 1.0                     # (E,)
    g_neg = scale * jax.nn.sigmoid(neg_logit) * neg_mask        # (E, P)

    d_center = g_pos[:, None] * u_pos + g_neg @ u_neg           # (E, D) — MXU
    d_pos = g_pos[:, None] * v                                  # (E, D)
    d_negrow = g_neg.T @ v                                      # (P, D) — MXU

    emb = _apply_row_updates(
        emb_t,
        centers,
        d_center,
        jnp.ones_like(centers, compute_dtype),
        lr,
        combiner,
        compute_dtype,
    )
    ctx = _apply_row_updates(
        ctx_t,
        jnp.concatenate([contexts, negs]),
        jnp.concatenate([d_pos, d_negrow]),
        jnp.concatenate(
            [
                jnp.ones_like(contexts, jnp.float32),
                # f32 reduction: a bf16 sum of ones saturates at 256, which
                # would defeat the capped divisor for hot pool rows
                scale.astype(jnp.float32)
                * neg_mask.sum(axis=0, dtype=jnp.float32),
            ]
        ),
        lr,
        combiner,
        compute_dtype,
    )
    return SGNSParams(emb=emb, ctx=ctx), jnp.mean(loss)


def sgns_step(
    params: SGNSParams,
    pairs: jax.Array,  # (B, 2) int32
    noise: "NoiseTable",  # alias-method noise table (see data/negative_sampling)
    key: jax.Array,
    lr: jax.Array,
    negatives: int = 5,
    both_directions: bool = True,
    compute_dtype=jnp.float32,
    combiner: str = "capped",
    negative_mode: str = "shared",
    shared_pool: int = 64,
) -> Tuple[SGNSParams, jax.Array]:
    """One fused SGD step over a batch of corpus pairs."""
    centers, contexts = _examples_from_pairs(pairs, both_directions)
    if negative_mode == "shared":
        pool = max(negatives, shared_pool)
        negs = sample_negatives(noise, key, (pool,))
        return _step_shared(
            params, centers, contexts, negs, negatives, lr, compute_dtype, combiner
        )
    if negative_mode != "per_example":
        raise ValueError(f"unknown negative_mode {negative_mode!r}")
    negs = sample_negatives(noise, key, (centers.shape[0], negatives))
    return _step_per_example(
        params, centers, contexts, negs, lr, compute_dtype, combiner
    )
