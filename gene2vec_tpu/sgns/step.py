"""The SGNS training step — the framework's hot loop.

This replaces gensim's Cython/Hogwild inner loop (the engine behind
``src/gene2vec.py:70,87``): per (center, context) pair, gather rows, draw k
negatives from the unigram^0.75 table, sigmoid dot-products, SGD row updates.

TPU-first formulation:

* a batch of B corpus pairs becomes 2B training examples — each pair is a
  2-token "sentence" with window=1 (SURVEY §2.2 #1), so skip-gram
  degenerates to symmetric pair prediction and we emit both directions
  explicitly;
* gradients are closed-form (the loss is a sum of log-sigmoids of rank-1
  dots — autodiff would materialize the same expressions with more
  bookkeeping), applied with deterministic ``.at[].add`` scatter-adds.
  Duplicate indices within a batch combine via ``combiner`` (default
  ``"capped"``): plain summing matches sequential SGD for typical duplicate
  counts but diverges when a hot token appears thousands of times per batch
  (all those gradients are evaluated at the same stale parameter value —
  gensim never hits this because its Hogwild loop applies updates one pair
  at a time), so the per-row sum is capped at C x mean (see
  :func:`_row_divisor`, SURVEY §7 hard part 1).  ``combiner="sum"``
  restores raw summing for small-batch oracle comparisons.  For the cap
  to coexist with shared-mode noise, the pool is auto-sized so that one
  slot aggregates only a few sequential draws' worth of gradient
  (P = 0.8*E*K, ``shared_pool_auto``): a far smaller pool either
  diverges under ``"sum"`` (each slot applies E*K/P stale sequential
  updates to one row at once — measured at P=64, B=16384) or, under
  ``"capped"``, has every slot's weight divided by ~E*K/(P*C), crushing
  the negative term ~80x and freezing the loss at its init value — the
  round-2 quality failure: a row's positive pulls and negative pushes
  must shrink together or not at all for the SGNS objective to be
  minimized (see the invariants in :func:`_step_shared`);
* negatives that collide with the positive target are masked out of loss and
  update (gensim skips them; a resampling loop would be data-dependent
  control flow XLA can't tile);
* the default noise estimator is **stratified** (``negative_mode=
  "stratified"``, :func:`_step_stratified`): an exact expectation term
  over the frequency head plus importance-weighted random contiguous
  tail blocks — the noise term becomes pure MXU matmuls and block-DMA
  traffic with zero random noise row ops (round-3 redesign,
  docs/PERF_NOTES.md).  ``negative_mode="shared"`` keeps the round-2
  grouped noise pool (G sub-batches, each drawing its own slice of a
  pool of P = 0.8*E*K draws, importance-weighted by K/(P/G)); it is the
  estimator the P_total quality sweep was measured on.
  ``negative_mode="per_example"`` keeps gensim's exact per-example draws
  for oracle comparisons.

* the positive side runs **dense-slab** when the trainer feeds
  class-segmented batches (``positive_head``/``positive_mid``): tokens in
  the frequency head — and, round 5, a second mid band — move as one-hot
  MXU contractions over contiguous table slabs; batches arrive
  [HH|HT|TT] (2-class) or [HH|HM|HT|MM|MT|TT] (3-class) at static
  per-pool quotas, so only true-tail examples pay dynamic row ops.

Everything is shape-static and jit-safe; under a Mesh the same code runs
data-parallel (sharded batch, replicated tables → XLA all-reduces the
scatter updates) or row-parallel (vocab-sharded tables → XLA turns
gather/scatter into ICI collectives). See gene2vec_tpu/parallel/sharding.py.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

from gene2vec_tpu.data.negative_sampling import NoiseTable, sample_negatives
from gene2vec_tpu.data.pipeline import pool_class_pairs as _pool_class_pairs
from gene2vec_tpu.sgns.model import SGNSParams


def _examples_from_pairs(
    pairs: jax.Array, both_directions: bool = True, shards: int = 1
) -> Tuple[jax.Array, jax.Array]:
    """(B, 2) pairs → (E,) centers, (E,) contexts with E = 2B (or B).

    ``shards > 1`` (dense-head positives under data parallelism) emits the
    two directions per DEVICE block instead of globally — pairs are viewed
    as (shards, B/shards, 2) and each block's examples are [its forward
    directions | its reverse directions], so a device's examples stay on
    its shard and the per-block [HH|HT|TT] segment layout survives into
    the example axis.  shards=1 reduces to the global concat.
    """
    if both_directions:
        b = pairs.shape[0]
        p3 = pairs.reshape(shards, b // shards, 2)
        centers = jnp.concatenate([p3[:, :, 0], p3[:, :, 1]], axis=1)
        contexts = jnp.concatenate([p3[:, :, 1], p3[:, :, 0]], axis=1)
        return centers.reshape(-1), contexts.reshape(-1)
    centers, contexts = pairs[:, 0], pairs[:, 1]
    return centers, contexts


def sgns_loss_and_grads(
    params: SGNSParams,
    centers: jax.Array,   # (E,) int32
    contexts: jax.Array,  # (E,) int32
    negatives: jax.Array, # (E, K) int32
    compute_dtype=jnp.float32,
):
    """Per-example loss and closed-form row gradients.

    Returns (loss_mean, (d_center (E,D), d_pos (E,D), d_neg (E,K,D)), neg_mask).
    """
    emb, ctx = params.emb, params.ctx
    v = emb[centers].astype(compute_dtype)        # (E, D)
    u_pos = ctx[contexts].astype(compute_dtype)   # (E, D)
    u_neg = ctx[negatives].astype(compute_dtype)  # (E, K, D)

    pos_logit = jnp.sum(v * u_pos, axis=-1)                    # (E,)
    neg_logit = jnp.einsum("ed,ekd->ek", v, u_neg)             # (E, K)

    # gensim skips a negative equal to the positive target; we zero it.
    neg_mask = (negatives != contexts[:, None]).astype(compute_dtype)

    # loss = -log σ(pos) - Σ_k log σ(-neg_k)
    loss = jax.nn.softplus(-pos_logit) + jnp.sum(
        neg_mask * jax.nn.softplus(neg_logit), axis=-1
    )

    g_pos = jax.nn.sigmoid(pos_logit) - 1.0                    # (E,)  dL/dpos_logit
    g_neg = jax.nn.sigmoid(neg_logit) * neg_mask               # (E, K)

    d_center = g_pos[:, None] * u_pos + jnp.einsum("ek,ekd->ed", g_neg, u_neg)
    d_pos = g_pos[:, None] * v
    d_neg = g_neg[:, :, None] * v[:, None, :]
    return jnp.mean(loss), (d_center, d_pos, d_neg), neg_mask


_CAP = 32.0  # "capped": sum up to this many duplicates, then scale as C x mean
# Shared mode draws this fraction of per-example mode's E*K noise draws per
# step (P = fraction * E * K).  Embedding quality tracks the TOTAL number of
# independent noise draws per step and nothing else: sweeping sub-batch size
# 32..256 at fixed P=4E left holdout AUC and planted-cluster separation
# identical to 3 decimals, while P=0.2*E*K..0.8*E*K moved holdout AUC
# 0.84 -> 0.879 (= per-example parity).  0.8 is the measured parity point.
_SHARED_DRAW_FRACTION = 0.8


def _row_divisor(cnt: jax.Array, combiner: str) -> jax.Array:
    """Divisor applied to each example's gradient given its row's duplicate
    count within the batch.

    * ``"sum"``    — 1 (sequential-SGD-like; diverges when a hot token is
      duplicated thousands of times per batch, since all those gradients are
      evaluated at the same stale parameter value);
    * ``"mean"``   — cnt (always stable, but under-trains hot rows: a row
      advances one averaged step per batch no matter how often it occurred);
    * ``"capped"`` — max(cnt / C, 1): exact sum while a row carries at most
      C = 32 example-units of gradient (bitwise-equal to "sum" on typical
      corpora), smoothly capped at C x mean beyond, which keeps the hot-row
      step bounded at any batch size.  The default (SURVEY §7 hard part 1).

    The cap is measured in *example units* — one positive occurrence or one
    per-example noise draw is 1; a shared-mode pool slot carries its
    importance-weighted aggregate (scale·Σ masks ≈ E·K/P units).  For the
    cap to track row load smoothly, one slot must carry only a few units —
    the pool auto-sizing invariant (see :func:`_step_shared`).  Round 2
    violated it (P=64 slots of ~2,560 units, divided ~80x), crushing the
    negative term and freezing the loss.
    """
    cnt = jnp.maximum(cnt, 1.0)
    if combiner == "sum":
        return jnp.ones_like(cnt)
    if combiner == "mean":
        return cnt
    if combiner == "capped":
        return jnp.maximum(cnt / _CAP, 1.0)
    raise ValueError(f"unknown combiner {combiner!r}")


def _acc_dtype_for(compute_dtype):
    return jnp.float32 if compute_dtype == jnp.bfloat16 else compute_dtype


def _stochastic_round_bf16(x: jax.Array, key: jax.Array) -> jax.Array:
    """Round f32 ``x`` to bfloat16 stochastically: add 16 uniform random
    bits below the bf16 mantissa, then truncate — E[result] = x exactly.

    This is what makes bf16 tables SAFE as a default (round 5): under
    round-to-nearest, a per-step SGD update smaller than half the
    weight's bf16 ulp (|w|/512) rounds away EVERY step and the row stops
    training — the measured round-4 failure in the small-scale smoke
    regime (config.py table_dtype note).  Under stochastic rounding the
    update survives with probability update/ulp, so the EXPECTED update
    equals the f32 update and training statistics are preserved at any
    scale.  Values already representable in bf16 (e.g. rows a step never
    touched, whose accumulated update is 0) have zero low bits and pass
    through bit-identically — the randomness never perturbs a row that
    did not train.  IEEE floats are sign+magnitude, so the low-bit add
    rounds the magnitude for either sign; a carry out of the exponent
    field correctly lands on the next binade (overflow to inf requires
    |x| at the f32 max, never reached by embedding tables).

    Noise source: a salted murmur3-finalizer hash of each element's flat
    index rather than ``jax.random.bits`` — threefry over the full (V, D)
    table (~10M words/step across both tables) measured 0.66 ms/step and
    erased the bf16 win; the 6-op avalanche hash is ~10x cheaper and SR
    only needs uniform decorrelated low bits, not cryptographic streams.
    The two salt words come from ONE threefry block of the step key, so
    every step (and each table) draws an independent hash family.
    """
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    salt = jax.random.bits(key, (2,), jnp.uint32)
    if x.ndim == 2:
        flat = (
            jax.lax.broadcasted_iota(jnp.uint32, x.shape, 0)
            * jnp.uint32(x.shape[1])
            + jax.lax.broadcasted_iota(jnp.uint32, x.shape, 1)
        )
    else:
        flat = jax.lax.iota(jnp.uint32, x.size).reshape(x.shape)
    h = (flat ^ salt[0]) * jnp.uint32(0x9E3779B1)
    h = (h ^ (h >> 15)) * jnp.uint32(0x85EBCA6B)
    h = (h ^ (h >> 13)) + salt[1]
    rnd = h & jnp.uint32(0xFFFF)
    # stay 32-bit wide end to end: mask the truncated mantissa in u32,
    # bitcast back to f32 (an exactly-representable bf16 value), and let
    # the final cast be the identity rounding — sub-word u16 bitcasts
    # lower poorly on the VPU
    return jax.lax.bitcast_convert_type(
        (bits + rnd) & jnp.uint32(0xFFFF0000), jnp.float32
    ).astype(jnp.bfloat16)


def _scatter_accumulator(
    v: int,
    idx: jax.Array,          # (R,) row per gradient
    grads: jax.Array,        # (R, D)
    weights: jax.Array,      # (R,) occurrence weight per gradient row
    acc_dtype,
) -> jax.Array:
    """(V, D+1) accumulator: gradients and occurrence weights scatter
    together — one scatter instead of a count scatter + count gather + grad
    scatter (profiling showed scatter count, not scatter payload,
    dominates).  Callers may add further dense contributions before
    :func:`_finalize_row_updates` applies the combiner divisor."""
    d = grads.shape[-1]
    payload = jnp.concatenate(
        [grads.astype(acc_dtype), weights.astype(acc_dtype)[:, None]], axis=1
    )
    return jnp.zeros((v, d + 1), acc_dtype).at[idx].add(payload)


def _finalize_row_updates(
    table: jax.Array, acc: jax.Array, lr: jax.Array, combiner: str,
    sr_key=None,
) -> jax.Array:
    """table − lr · (accumulated grads / per-row combiner divisor).

    With ``sr_key`` and a bfloat16 table, the write-back rounds
    stochastically (:func:`_stochastic_round_bf16`) so sub-ulp updates
    survive in expectation instead of absorbing."""
    d = table.shape[1]
    update = acc[:, :d] / _row_divisor(acc[:, d], combiner)[:, None]
    lr = jnp.asarray(lr, acc.dtype)
    new = table.astype(acc.dtype) - lr * update
    if sr_key is not None and table.dtype == jnp.bfloat16:
        return _stochastic_round_bf16(new, sr_key)
    return new.astype(table.dtype)


def _apply_row_updates(
    table: jax.Array,        # (V, D)
    idx: jax.Array,          # (R,) row per gradient
    grads: jax.Array,        # (R, D)
    weights: jax.Array,      # (R,) occurrence weight per gradient row
    lr: jax.Array,
    combiner: str,
    compute_dtype,
    sr_key=None,
    acc_constraint=None,
) -> jax.Array:
    """table − lr · combined row updates, via ONE fused scatter; see
    :func:`_scatter_accumulator` / :func:`_row_divisor` for semantics.
    ``acc_constraint`` pins the accumulator's sharding to the table's
    (parallel/sharding.py:constrain_acc)."""
    acc = _scatter_accumulator(
        table.shape[0], idx, grads, weights, _acc_dtype_for(compute_dtype)
    )
    if acc_constraint is not None:
        acc = acc_constraint(acc)
    return _finalize_row_updates(table, acc, lr, combiner, sr_key=sr_key)


def _step_per_example(
    params: SGNSParams,
    centers: jax.Array,
    contexts: jax.Array,
    negs: jax.Array,  # (E, K)
    lr: jax.Array,
    compute_dtype,
    combiner: str,
    sr_keys=None,  # (emb_key, ctx_key) for bf16 stochastic write-back
    acc_constraint=None,
) -> Tuple[SGNSParams, jax.Array]:
    loss, (d_center, d_pos, d_neg), neg_mask = sgns_loss_and_grads(
        params, centers, contexts, negs, compute_dtype
    )
    d = d_center.shape[-1]
    sk_emb, sk_ctx = sr_keys if sr_keys is not None else (None, None)
    emb = _apply_row_updates(
        params.emb,
        centers,
        d_center,
        jnp.ones_like(centers, compute_dtype),
        lr,
        combiner,
        compute_dtype,
        sr_key=sk_emb,
        acc_constraint=acc_constraint,
    )
    # One fused scatter for positive contexts + noise draws: in per-example
    # mode each noise draw carries weight ≤ 1 (its collision mask), the same
    # scale as a positive occurrence, so the configured combiner's duplicate
    # semantics apply uniformly (the cap binds only when a row is drawn
    # > _CAP times per batch — the sequential-staleness bound).
    ctx = _apply_row_updates(
        params.ctx,
        jnp.concatenate([contexts, negs.reshape(-1)]),
        jnp.concatenate([d_pos, d_neg.reshape(-1, d)]),
        jnp.concatenate(
            [jnp.ones_like(contexts, compute_dtype), neg_mask.reshape(-1)]
        ),
        lr,
        combiner,
        compute_dtype,
        sr_key=sk_ctx,
        acc_constraint=acc_constraint,
    )
    return SGNSParams(emb=emb, ctx=ctx), loss


def _step_shared(
    params: SGNSParams,
    centers: jax.Array,   # (E,)
    contexts: jax.Array,  # (E,)
    negs: jax.Array,      # (P,) — noise pool, split into `groups` slices
    k_negatives: int,     # the objective's K (negative-term weight)
    groups: int,          # sub-batches with independent pool slices
    lr: jax.Array,
    compute_dtype,
    combiner: str,
    sr_keys=None,  # (emb_key, ctx_key) for bf16 stochastic write-back
    acc_constraint=None,
) -> Tuple[SGNSParams, jax.Array]:
    emb_t, ctx_t = params.emb, params.ctx
    e, p = centers.shape[0], negs.shape[0]
    g = groups
    v = emb_t[centers].astype(compute_dtype)      # (E, D)
    u_pos = ctx_t[contexts].astype(compute_dtype) # (E, D)
    u_neg = ctx_t[negs].astype(compute_dtype)     # (P, D)
    d = v.shape[-1]

    pos_logit = jnp.sum(v * u_pos, axis=-1)                     # (E,)
    # Each of the G groups of E/G examples shares only its own P/G pool
    # slice: one batched (G, E/G, D) x (G, D, P/G) MXU matmul.  G=1 is the
    # classic single shared pool; the estimator-rank invariant (#3 below)
    # wants E/G small enough that pool noise stays high-rank.
    vg = v.reshape(g, e // g, d)
    u_negg = u_neg.reshape(g, p // g, d)
    neg_logit = jnp.einsum("ged,gpd->gep", vg, u_negg)          # MXU
    neg_mask = (
        negs.reshape(g, 1, p // g) != contexts.reshape(g, e // g, 1)
    ).astype(compute_dtype)

    # Each example sees P/G draws; weighting their mean by K keeps the SGNS
    # objective's negative-term weight unchanged in expectation (a K/(P/G)
    # importance weight per draw).
    scale = jnp.asarray(k_negatives * g / p, compute_dtype)
    loss = jax.nn.softplus(-pos_logit) + scale * jnp.sum(
        neg_mask * jax.nn.softplus(neg_logit), axis=-1
    ).reshape(e)
    g_pos = jax.nn.sigmoid(pos_logit) - 1.0                     # (E,)
    g_neg = scale * jax.nn.sigmoid(neg_logit) * neg_mask        # (G, E/G, P/G)

    d_center = g_pos[:, None] * u_pos + jnp.einsum(
        "gep,gpd->ged", g_neg, u_negg
    ).reshape(e, d)                                             # MXU
    d_pos = g_pos[:, None] * v                                  # (E, D)
    d_negrow = jnp.einsum("gep,ged->gpd", g_neg, vg).reshape(p, d)  # MXU

    sk_emb, sk_ctx = sr_keys if sr_keys is not None else (None, None)
    emb = _apply_row_updates(
        emb_t,
        centers,
        d_center,
        jnp.ones_like(centers, compute_dtype),
        lr,
        combiner,
        compute_dtype,
        sr_key=sk_emb,
        acc_constraint=acc_constraint,
    )
    # One fused scatter for positive contexts + pool slots, weighted in
    # example units (one positive occurrence = 1; one pool slot = its
    # importance-weighted aggregate, scale·Σ masks ≈ E·K/P units).  Two
    # measured invariants govern this design (docs/QUALITY_NOTES.md):
    #
    # 1. SYMMETRY — a row's positive and negative gradients must shrink by
    #    the SAME divisor.  Weakening only the negatives — round 2 divided
    #    pool slots ~80x via example-unit capping of ~2,560-unit slots; an
    #    intermediate design pre-divided noise by its expected load —
    #    freezes the loss at init or collapses all vectors onto one ray
    #    (planted-cluster inter-cluster cosine 0.97 vs 0.40 healthy).
    #    The fused accumulator applies one divisor per row to the sum of
    #    both, exactly like the per-example path.
    # 2. GRANULARITY — the divisor tracks example-unit load smoothly only
    #    if one slot carries few units, so the pool is sized at
    #    P = 0.8·E·K (~1.25-unit slots; see sgns_step).  Slots at ~_CAP
    #    units make the divisor jump integer multiples of the cap per
    #    draw, mean-ing every multi-slot row (measured −0.1 holdout AUC
    #    on the real corpus vs per-example draws).
    # 3. RANK — one pool shared by the whole batch repels ctx rows only
    #    along the span of σ-weighted batch means; that low-rank repulsion
    #    lets the bulk geometry contract (planted-cluster inter-cluster
    #    cosine drifts 0.56 → 0.82 over 20 epochs at E=2048, G=1, while
    #    per-example draws hold 0.41).  Grouped pools restore estimator
    #    rank at MXU-friendly shapes.
    ctx = _apply_row_updates(
        ctx_t,
        jnp.concatenate([contexts, negs]),
        jnp.concatenate([d_pos, d_negrow]),
        jnp.concatenate(
            [
                jnp.ones_like(contexts, jnp.float32),
                # f32 reduction: a bf16 sum of ones saturates at 256, which
                # would defeat the capped divisor for hot pool rows
                scale.astype(jnp.float32)
                * neg_mask.sum(axis=1, dtype=jnp.float32).reshape(p),
            ]
        ),
        lr,
        combiner,
        compute_dtype,
        sr_key=sk_ctx,
        acc_constraint=acc_constraint,
    )
    return SGNSParams(emb=emb, ctx=ctx), jnp.mean(loss)


#: Matmul precision for the dense-head positive path's one-hot gathers and
#: scatters.  ``None`` = the step's default policy (bf16-truncated inputs on
#: TPU, f32 accumulation) — a one-hot gather then returns the table value
#: truncated to bf16 and a one-hot scatter sums bf16-truncated payload rows
#: in f32, the same rounding class as :func:`_aggregate_tail_blocks`.
#: Tests pin exactness against the scatter path by setting this to
#: ``jax.lax.Precision.HIGHEST``.
_DENSE_HEAD_PRECISION = None


def _dense_segments(quotas, b: int, n_classes: int):
    """Static per-CLASS (start, length) example segments for the
    class-segmented batch layout (``data/pipeline.segment_corpus_by_head``):
    ``quotas[p]`` pairs of pool p (pools in :func:`_pool_class_pairs`
    order), emitted in both directions so example i and i + b are the two
    directions of pair i.

    Segments index the LOCAL example axis of the (shards, 2b) view — under
    data parallelism each device block carries its own class layout with
    per-device quotas, so every slice below stays device-local and the
    slab matmuls reduce over the shard axis (XLA's psum over ICI).

    Returns (center_segs, context_segs): each a tuple of n_classes tuples
    of (start, length) segments in ascending position order (adjacent
    same-class segments merged).  The last class is the tail (plain
    gathers); the rest are dense slabs.
    """
    pcs = _pool_class_pairs(n_classes)
    assert len(quotas) == len(pcs), (quotas, pcs)
    center = [[] for _ in range(n_classes)]
    context = [[] for _ in range(n_classes)]
    off = 0
    for (ca, cb), q in zip(pcs, quotas):
        if q:
            center[ca].append((off, q))       # forward: centers = first
            context[cb].append((off, q))
            center[cb].append((b + off, q))   # reverse direction
            context[ca].append((b + off, q))
        off += q
    assert off == b, (quotas, b)

    def merge(segs):
        out = []
        for s, l in sorted(segs):
            if out and out[-1][0] + out[-1][1] == s:
                out[-1] = (out[-1][0], out[-1][1] + l)
            else:
                out.append((s, l))
        return tuple((s, l) for s, l in out)

    return tuple(merge(c) for c in center), tuple(merge(x) for x in context)


def _split_classes(x: jax.Array, seg_lists):
    """Split the local example axis (axis 1) of ``x`` (shards, local, ...)
    into per-class parts, each part's segments concatenated in order."""
    return [
        jnp.concatenate([x[:, s : s + l] for s, l in segs], axis=1)
        if segs
        else x[:, :0]
        for segs in seg_lists
    ]


def _join_classes(parts, seg_lists):
    """Inverse of :func:`_split_classes`: reassemble local example order."""
    tagged = sorted(
        (s, l, c) for c, segs in enumerate(seg_lists) for s, l in segs
    )
    pieces = []
    offs = [0] * len(seg_lists)
    for s, l, c in tagged:
        pieces.append(parts[c][:, offs[c] : offs[c] + l])
        offs[c] += l
    return jnp.concatenate(pieces, axis=1)


def _dense_slab_gather(
    table: jax.Array,   # (V, D)
    idx: jax.Array,     # (S, L) — slab-class segments guaranteed in-slab
    slabs,              # tuple of (lo, hi) row ranges, one per dense class
    seg_lists,          # per-class segments from _dense_segments
    compute_dtype,
):
    """Gather ``table[idx]`` with slab-class rows produced by one-hot MXU
    matmuls against the contiguous ``table[lo:hi]`` slabs — zero dynamic
    row ops for slab examples (the positive-side analogue of the
    stratified noise head; docs/PERF_NOTES.md rounds 4-5).  Each level's
    one-hot FLOPs scale with ITS example count x ITS slab width, which is
    what lets a second mid slab cover rows the single-level head could
    not afford (coverage grows logarithmically but single-level FLOPs grow
    with all head examples).  Returns (rows (S, L, D), onehots per slab,
    idx_tail (S, Lt)) — the one-hots are reused by
    :func:`_dense_slab_scatter_acc` for the update direction.
    """
    parts = _split_classes(idx, seg_lists)
    onehots = []
    row_parts = []
    for (lo, hi), idx_c in zip(slabs, parts[:-1]):
        onehot = (
            idx_c[:, :, None] == jnp.arange(lo, hi)[None, None, :]
        ).astype(compute_dtype)
        rows = jax.lax.dot_general(
            onehot,
            table[lo:hi].astype(compute_dtype),
            (((2,), (0,)), ((), ())),
            precision=_DENSE_HEAD_PRECISION,
            preferred_element_type=compute_dtype,
        )                                               # (S, Lc, D)
        onehots.append(onehot)
        row_parts.append(rows)
    idx_t = parts[-1]
    row_parts.append(table[idx_t].astype(compute_dtype))  # (S, Lt, D)
    return _join_classes(row_parts, seg_lists), onehots, idx_t


def _dense_slab_scatter_acc(
    v_size: int,
    grads: jax.Array,     # (S, L, D) per-example gradients
    weights: jax.Array,   # (S, L) example-unit weights
    onehots,              # per-slab one-hots from _dense_slab_gather
    idx_tail: jax.Array,  # (S, Lt)
    slabs,                # tuple of (lo, hi) row ranges, one per dense class
    seg_lists,
    acc_dtype,
) -> jax.Array:
    """(V, D+1) accumulator for the dense-slab path: tail rows scatter as
    usual; each slab's rows land as ONE (W, S·Lc) x (S·Lc, D+1) MXU
    contraction added densely to the accumulator's [lo, hi) slab (exact
    f32 accumulation of bf16-truncated payload rows under the default
    policy).  Both the tail scatter and the shard-axis contractions reduce
    over ``S`` — under data parallelism XLA emits that reduction as the
    gradient psum."""
    d = grads.shape[-1]
    payload = jnp.concatenate(
        [grads, weights.astype(grads.dtype)[:, :, None]], axis=2
    )
    parts = _split_classes(payload, seg_lists)
    acc = jnp.zeros((v_size, d + 1), acc_dtype).at[
        idx_tail.reshape(-1)
    ].add(parts[-1].reshape(-1, d + 1).astype(acc_dtype))
    for (lo, hi), onehot, pay in zip(slabs, onehots, parts[:-1]):
        slab_rows = jax.lax.dot_general(
            onehot,
            pay,
            (((0, 1), (0, 1)), ((), ())),               # contract S, Lc
            precision=_DENSE_HEAD_PRECISION,
            preferred_element_type=acc_dtype,
        )                                               # (hi - lo, D+1)
        acc = acc.at[lo:hi].add(slab_rows.astype(acc_dtype))
    return acc


def _aggregate_tail_blocks(
    blocks: jax.Array,        # (G,) block index drawn by each group
    tail_payload: jax.Array,  # (G, S, D+1) per-group gradient+weight slabs
    nb: int,
) -> jax.Array:
    """Sum each group's tail slab into its block slot: (NB, S, D+1).

    Round 4 replaced the block-indexed scatter-add with a (NB, G) one-hot
    MXU matmul over the (G, S*(D+1)) payload: ~NB*G*S*D MACs (~free) that
    stream the ~100 MB payload once instead of re-writing it through
    scatter RMW — measured +7% on the whole epoch (docs/PERF_NOTES.md
    round 4).  Precision: the matmul runs at the step's default matmul
    precision, i.e. bf16-truncated inputs on TPU — the SAME policy every
    logit/gradient matmul in this module already uses — so tail
    aggregates carry ~0.4% relative rounding vs the old f32 scatter.
    Measured end to end: holdout AUC identical to 4 decimals (0.8971)
    and epoch loss identical to 4 decimals either way; the summation
    itself (indexing, clamped last block) is pinned exact by
    tests/test_stratified.py::test_aggregate_tail_blocks_matches_scatter.
    """
    g = blocks.shape[0]
    s, d1 = tail_payload.shape[1], tail_payload.shape[2]
    onehot = (blocks[None, :] == jnp.arange(nb)[:, None]).astype(
        tail_payload.dtype
    )
    return jax.lax.dot(
        onehot,
        tail_payload.reshape(g, s * d1),
        preferred_element_type=tail_payload.dtype,
    ).reshape(nb, s, d1)


def _step_stratified(
    params: SGNSParams,
    centers: jax.Array,   # (E,)
    contexts: jax.Array,  # (E,)
    spec,                 # StratifiedSpec (data/negative_sampling)
    key: jax.Array,
    k_negatives: int,
    group_size: int,
    lr: jax.Array,
    compute_dtype,
    combiner: str,
    pos_head: int = 0,
    pos_mid: int = 0,  # second dense slab [pos_head, pos_head + pos_mid)
    pos_quotas=None,  # static per-pool pair counts of the batch layout
    pos_shards: int = 1,  # data-parallel device blocks in the batch layout
    sr_keys=None,  # (emb_key, ctx_key) for bf16 stochastic write-back
    acc_constraint=None,
) -> Tuple[SGNSParams, jax.Array]:
    """Stratified negatives: exact head + per-group random tail blocks.

    The round-3 redesign of the noise term (docs/PERF_NOTES.md §round-3),
    re-tuned twice in round 4: the tail term's cost tracks the NUMBER of
    per-group dynamic slices and, once the dense-head positive split
    landed, the total tail row traffic G x S, so the default geometry
    moved (32, 128) → (128, 512) → (256, 512).  The shipped default
    measures 5.5-5.8M pairs/s at holdout AUC 0.8896 (oracle parity
    target 0.878; ``strat_group=128`` is the maximum-quality knob at
    0.8960) — authoritative numbers in the PERF_NOTES round-4 geometry
    tables (I and II).  The
    shared/per-example modes spend ~2/3 of their row ops gathering and
    scattering P = 0.8*E*K random noise rows; noise rows have no example
    coupling, so this mode restructures them into contiguous traffic:

    * HEAD (rows [0, head) of the frequency-sorted vocab): the negative
      term's expectation over the head mass is computed EXACTLY —
      K * q_j * softplus(v.u_j) via one dense (E, D) x (D, H) MXU matmul
      over a contiguous table slice.  Zero sampling variance where the
      noise mass concentrates, and the ctx update is a dense slice add.
    * TAIL: each group of ``group_size`` examples draws ONE contiguous
      block of ``spec.block`` rows (uniform over ``spec.nb`` blocks;
      ``spec.tail_w`` = q/p makes the estimator unbiased row-by-row, see
      StratifiedSpec).  Gathers are vmapped dynamic slices and the
      scatter is block-indexed — G block operations instead of G*S row
      operations.

    Cap symmetry (QUALITY_NOTES invariant 1) is preserved by adding the
    noise gradients AND their example-unit weights densely into the same
    (V, D+1) accumulator the positive scatter uses: each row still gets
    one combiner divisor over the sum of positive and negative load.
    Estimator rank (invariant 3) holds because each example sees
    head + block >= hundreds of distinct repulsion directions per step.
    """
    emb_t, ctx_t = params.emb, params.ctx
    v_size, d = ctx_t.shape
    e = centers.shape[0]
    g = max(1, e // group_size)
    while e % g:
        g -= 1
    if e // g > 8 * group_size:
        # mirror the shared-mode fallback warning: awkward example counts
        # (e.g. e = 2*8191) can collapse the divisor search to very few
        # groups, so thousands of examples share one tail-block draw per
        # step — higher estimator variance with no other signal.
        import warnings

        warnings.warn(
            f"batch example count {e} has no divisor near "
            f"e/{group_size}; falling back to {g} tail-block group(s) of "
            f"{e // g} examples, which raises stratified-estimator "
            f"variance.  Use a batch_pairs divisible by {group_size}.",
            stacklevel=2,
        )
    head, block, nb = spec.head, spec.block, spec.nb
    k = jnp.asarray(float(k_negatives), compute_dtype)

    # Positive-side row ops: plain gathers, or the dense-head split when the
    # trainer feeds class-segmented [HH|HT|TT] batches (positive_head > 0):
    # head-token rows come from one-hot MXU matmuls over the contiguous
    # table[:pos_head] slab, and only tail-token examples pay dynamic row
    # ops (docs/PERF_NOTES.md round 4 — the positive-side analogue of the
    # stratified noise head).
    dense_pos = pos_head > 0 and pos_quotas is not None
    if dense_pos:
        s = pos_shards
        slabs = [(0, pos_head)]
        if pos_mid > 0:
            slabs.append((pos_head, pos_head + pos_mid))
        c_segs, x_segs = _dense_segments(
            [q // s for q in pos_quotas], e // (2 * s), len(slabs) + 1
        )
        centers2 = centers.reshape(s, e // s)
        contexts2 = contexts.reshape(s, e // s)
        v2, oh_c, idx_ct = _dense_slab_gather(
            emb_t, centers2, slabs, c_segs, compute_dtype
        )
        u2, oh_x, idx_xt = _dense_slab_gather(
            ctx_t, contexts2, slabs, x_segs, compute_dtype
        )
        v = v2.reshape(e, d)
        u_pos = u2.reshape(e, d)
    else:
        v = emb_t[centers].astype(compute_dtype)      # (E, D)
        u_pos = ctx_t[contexts].astype(compute_dtype) # (E, D)
    pos_logit = jnp.sum(v * u_pos, axis=-1)
    g_pos = jax.nn.sigmoid(pos_logit) - 1.0

    # ---- head: exact expectation over rows [0, head) ---------------------
    ctx_head = ctx_t[:head].astype(compute_dtype)     # contiguous slice
    q_head = spec.q[:head].astype(compute_dtype)
    head_logit = v @ ctx_head.T                       # (E, H) MXU
    head_mask = (
        jnp.arange(head)[None, :] != contexts[:, None]
    ).astype(compute_dtype)                           # gensim skip parity
    g_head = k * q_head[None, :] * jax.nn.sigmoid(head_logit) * head_mask
    loss_head = k * jnp.sum(
        q_head[None, :] * head_mask * jax.nn.softplus(head_logit), axis=-1
    )

    # ---- tail: one random block per group --------------------------------
    # bounds derive from the spec's LOGICAL vocab, not the table rows:
    # vocab-sharded tables pad their row count to the model-axis multiple
    # (rows [v_noise, v_size) never train and carry no noise mass)
    v_noise = spec.q.shape[0]
    blocks = jax.random.randint(key, (g,), 0, nb)
    starts = jnp.minimum(head + blocks * block, v_noise - block)

    def slice_rows(tbl, s):
        return jax.lax.dynamic_slice(tbl, (s, 0), (block, tbl.shape[1]))

    ctx_blk = jax.vmap(slice_rows, in_axes=(None, 0))(
        ctx_t, starts
    ).astype(compute_dtype)                           # (G, S, D)
    w_blk = jax.vmap(
        lambda s: jax.lax.dynamic_slice(spec.tail_w, (s,), (block,))
    )(starts).astype(compute_dtype)                   # (G, S) q/p weights

    vg = v.reshape(g, e // g, d)
    cg = contexts.reshape(g, e // g)
    tail_logit = jnp.einsum("ged,gsd->ges", vg, ctx_blk)      # MXU
    row_ids = starts[:, None] + jnp.arange(block)[None, :]    # (G, S)
    tail_mask = (
        row_ids[:, None, :] != cg[:, :, None]
    ).astype(compute_dtype)
    w_tail = k * w_blk[:, None, :]
    g_tail = w_tail * jax.nn.sigmoid(tail_logit) * tail_mask
    loss_tail = jnp.sum(
        w_tail * tail_mask * jax.nn.softplus(tail_logit), axis=-1
    ).reshape(e)

    loss = jnp.mean(jax.nn.softplus(-pos_logit) + loss_head + loss_tail)

    # ---- center gradients: same per-example scatter path as other modes --
    d_center = (
        g_pos[:, None] * u_pos
        + g_head @ ctx_head                                        # MXU
        + jnp.einsum("ges,gsd->ged", g_tail, ctx_blk).reshape(e, d)
    )
    acc_dtype = _acc_dtype_for(compute_dtype)
    sk_emb, sk_ctx = sr_keys if sr_keys is not None else (None, None)
    if dense_pos:
        acc_emb = _dense_slab_scatter_acc(
            v_size, d_center.reshape(s, e // s, d),
            jnp.ones((s, e // s), compute_dtype),
            oh_c, idx_ct, slabs, c_segs, acc_dtype,
        )
        if acc_constraint is not None:
            acc_emb = acc_constraint(acc_emb)
        emb = _finalize_row_updates(
            emb_t, acc_emb, lr, combiner, sr_key=sk_emb
        )
    else:
        emb = _apply_row_updates(
            emb_t, centers, d_center,
            jnp.ones_like(centers, compute_dtype), lr, combiner,
            compute_dtype, sr_key=sk_emb, acc_constraint=acc_constraint,
        )

    # ---- ctx: positive scatter + DENSE noise adds into ONE accumulator ---
    d_pos = g_pos[:, None] * v
    if dense_pos:
        acc = _dense_slab_scatter_acc(
            v_size, d_pos.reshape(s, e // s, d),
            jnp.ones((s, e // s), compute_dtype),
            oh_x, idx_xt, slabs, x_segs, acc_dtype,
        )
    else:
        acc = _scatter_accumulator(
            v_size, contexts, d_pos, jnp.ones((e,), compute_dtype), acc_dtype
        )

    # Noise weight columns carry the rows' sigma-FREE example-unit loads —
    # k*q_j*sum(mask) for head, k*w_j*sum(mask) for tail — matching the
    # shared mode's scale*sum(mask) and per-example's mask<=1 exactly:
    # the cap divisor must track how much sequential-equivalent gradient a
    # row aggregated, not how much of it the current sigmoids pass (a
    # sigma-modulated load would vanish as training polarizes, decoupling
    # the divisor from row load — the asymmetric-cap failure class of
    # QUALITY_NOTES invariant 1).
    d_head_rows = g_head.T @ v                                     # MXU
    u_head = k * q_head * jnp.sum(head_mask, axis=0, dtype=jnp.float32)
    acc = acc.at[:head, :d].add(d_head_rows.astype(acc_dtype))
    acc = acc.at[:head, d].add(u_head.astype(acc_dtype))

    d_tail_rows = jnp.einsum("ges,ged->gsd", g_tail, vg)           # MXU
    u_tail = w_tail[:, 0, :] * jnp.sum(tail_mask, axis=1, dtype=jnp.float32)
    tail_payload = jnp.concatenate(
        [
            d_tail_rows.astype(acc_dtype),
            u_tail[:, :, None].astype(acc_dtype),
        ],
        axis=2,
    )
    acc_blocks = _aggregate_tail_blocks(blocks, tail_payload, nb)
    if nb > 1:
        acc = acc.at[head : head + (nb - 1) * block].add(
            acc_blocks[:-1].reshape((nb - 1) * block, d + 1)
        )
    acc = acc.at[v_noise - block : v_noise].add(acc_blocks[-1])

    if acc_constraint is not None:
        acc = acc_constraint(acc)
    ctx = _finalize_row_updates(ctx_t, acc, lr, combiner, sr_key=sk_ctx)
    return SGNSParams(emb=emb, ctx=ctx), loss


def sgns_step(
    params: SGNSParams,
    pairs: jax.Array,  # (B, 2) int32
    noise: "NoiseTable",  # alias-method noise table (see data/negative_sampling)
    key: jax.Array,
    lr: jax.Array,
    negatives: int = 5,
    both_directions: bool = True,
    compute_dtype=jnp.float32,
    combiner: str = "capped",
    negative_mode: str = "shared",
    shared_pool: int = 1024,
    shared_pool_auto: bool = True,
    shared_groups: int = 0,
    strat_group: int = 32,
    stratified=None,  # StratifiedSpec, required for negative_mode="stratified"
    positive_head: int = 0,
    positive_mid: int = 0,  # second dense slab [head, head + mid)
    pos_quotas=None,  # static per-pool pair counts of the batch layout
    pos_shards: int = 1,  # per-device class blocks (data parallelism)
    bf16_stochastic_round: bool = True,
    acc_constraint=None,  # pin accumulator sharding (constrain_acc)
) -> Tuple[SGNSParams, jax.Array]:
    """One fused SGD step over a batch of corpus pairs."""
    # bf16 tables write back with stochastic rounding by default (round 5)
    # so sub-ulp SGD updates survive in expectation instead of absorbing —
    # what makes table_dtype="bfloat16" safe at any scale.  Keys derive
    # via fold_in so the noise-draw streams are untouched vs round 4.
    sr_keys = None
    if bf16_stochastic_round and params.emb.dtype == jnp.bfloat16:
        sr_keys = (
            jax.random.fold_in(key, 0x51EB), jax.random.fold_in(key, 0x51EC)
        )
    dense_pos = positive_head > 0 and pos_quotas is not None
    if dense_pos:
        if negative_mode != "stratified":
            raise ValueError(
                "positive_head (dense-head positives) is implemented for "
                "negative_mode='stratified' only"
            )
        if not both_directions:
            raise ValueError(
                "positive_head requires both_directions=True (the class-"
                "segmented batch layout emits both directions of each pair)"
            )
        b = int(pairs.shape[0])
        n_classes = 3 if positive_mid > 0 else 2
        n_pools = len(_pool_class_pairs(n_classes))
        if len(pos_quotas) != n_pools:
            raise ValueError(
                f"pos_quotas {pos_quotas} must have {n_pools} entries (one "
                f"per {n_classes}-class pool of "
                "data/pipeline.segment_corpus_by_head)"
            )
        if any(q < 0 for q in pos_quotas) or sum(pos_quotas) != b:
            # inconsistent quotas would flow into _dense_segments where
            # Python slice clamping can silently misattribute examples to
            # the wrong segment instead of raising
            raise ValueError(
                f"pos_quotas {pos_quotas} inconsistent with batch {b}: "
                "need every quota >= 0 and sum(pos_quotas) == batch_pairs"
            )
        if any(q % pos_shards for q in (*pos_quotas, b)):
            raise ValueError(
                f"pos_quotas {pos_quotas} / batch {b} must be divisible by "
                f"pos_shards={pos_shards} (per-device segment layout)"
            )
    centers, contexts = _examples_from_pairs(
        pairs, both_directions, shards=pos_shards if dense_pos else 1
    )
    if negative_mode == "stratified":
        if stratified is None:
            raise ValueError(
                "negative_mode='stratified' needs a StratifiedSpec (built "
                "from vocab counts via build_stratified_spec); SGNSTrainer "
                "wires this automatically"
            )
        # shared_groups keeps its shared-mode meaning (number of groups)
        # and overrides; unset -> the configured group SIZE (strat_group)
        e = int(centers.shape[0])
        if shared_groups > 0 and (shared_groups > e or e % shared_groups):
            raise ValueError(
                f"shared_groups={shared_groups} does not divide the example "
                f"count {e} (= {'2x' if both_directions else ''}batch_pairs)"
            )
        group_size = e // shared_groups if shared_groups > 0 else strat_group
        return _step_stratified(
            params, centers, contexts, stratified, key, negatives,
            group_size, lr, compute_dtype, combiner,
            pos_head=positive_head, pos_mid=positive_mid,
            pos_quotas=pos_quotas, pos_shards=pos_shards, sr_keys=sr_keys,
            acc_constraint=acc_constraint,
        )
    if negative_mode == "shared":
        e = int(centers.shape[0])
        # groups of ~32 examples, each with its own pool slice (estimator-
        # rank invariant, _step_shared #3: sub-batch 32 measured at parity
        # with per-example draws on holdout AUC and planted-cluster
        # separation; larger groups trade quality for throughput — see
        # docs/QUALITY_NOTES.md frontier table); G must divide E
        if shared_groups > 0:
            g = shared_groups
            if e % g:
                raise ValueError(
                    f"shared_groups={g} does not divide the example count "
                    f"{e} (= {'2x' if both_directions else ''}batch_pairs)"
                )
            if not shared_pool_auto and shared_pool < g * negatives:
                # every group needs at least `negatives` draws; a pool
                # below g*K cannot be honored even before sublane rounding,
                # and silently inflating it would mislabel experiments (an
                # 'explicit P=64' run must not measure a 128-draw pool).
                # Pools >= g*K are realizable within sublane rounding and
                # fall through to the warn-and-adjust path below.
                raise ValueError(
                    f"shared_pool={shared_pool} cannot be split across "
                    f"shared_groups={g} groups of at least {negatives} "
                    "draws each; lower shared_groups or raise shared_pool"
                )
        else:
            g = max(1, e // 32)
            if not shared_pool_auto:
                # an explicit small pool (the documented degraded-throughput
                # escape hatch) must be honored: cap the group count so the
                # per-group floor (negatives rounded up to the 8-sublane
                # width) cannot silently inflate the total pool past the
                # request beyond that minimum slice
                slice_min = 8 * -(-negatives // 8)
                g = max(1, min(g, shared_pool // slice_min))
            while e % g:
                g -= 1
            if shared_pool_auto and e // g > 256 and e > 256:
                import warnings

                warnings.warn(
                    f"batch example count {e} has no divisor near e/32; "
                    f"falling back to {g} pool group(s) of {e // g} "
                    "examples, which degrades embedding quality (see "
                    "sgns/step.py invariant 3).  Use a batch_pairs "
                    "divisible by 32.",
                    stacklevel=2,
                )
        per_group = max(negatives, -(-max(shared_pool, 1) // g))
        if shared_pool_auto:
            # quality parity with per-example draws needs a total pool of
            # P = _SHARED_DRAW_FRACTION * E * K independent draws (see the
            # constant's measurement note); this also keeps one slot's
            # aggregated gradient to ~K/fraction ≈ 6 example units, well
            # under the capped combiner's granularity needs (invariant 2).
            # shared_pool is a FLOOR here, so round up to the f32 sublane
            # width (memory traffic and scatter rows scale with the true
            # pool size — no 128-lane padding).
            per_group = max(
                per_group,
                math.ceil(_SHARED_DRAW_FRACTION * (e // g) * negatives),
            )
            per_group = 8 * -(-per_group // 8)
        else:
            # explicit pool: honor the request from above — round DOWN to
            # the sublane width, never below the minimum slice
            per_group = max(8 * -(-negatives // 8), 8 * (per_group // 8))
            if g * per_group != shared_pool:
                import warnings

                warnings.warn(
                    f"explicit shared_pool={shared_pool} adjusted to "
                    f"{g * per_group} ({g} groups x {per_group}-draw "
                    "slices; slices are sublane-rounded and at least "
                    "`negatives` wide) — record the adjusted size when "
                    "labeling experiments",
                    stacklevel=2,
                )
        negs = sample_negatives(noise, key, (g * per_group,))
        return _step_shared(
            params, centers, contexts, negs, negatives, g, lr,
            compute_dtype, combiner, sr_keys=sr_keys,
            acc_constraint=acc_constraint,
        )
    if negative_mode != "per_example":
        raise ValueError(f"unknown negative_mode {negative_mode!r}")
    negs = sample_negatives(noise, key, (centers.shape[0], negatives))
    return _step_per_example(
        params, centers, contexts, negs, lr, compute_dtype, combiner,
        sr_keys=sr_keys, acc_constraint=acc_constraint,
    )
