"""SGNS parameters: an input ("emb") and output ("ctx") table.

Initialization follows the word2vec convention the reference relies on via
gensim (``src/gene2vec.py:70``): input vectors U(−0.5/D, 0.5/D), output
(context) vectors zero.  Published artifacts are the *input* table.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp


class SGNSParams(NamedTuple):
    emb: jax.Array  # (V, D) input/center vectors — the published embedding
    ctx: jax.Array  # (V, D) output/context vectors


def init_params(
    key: jax.Array, vocab_size: int, dim: int, dtype=jnp.float32
) -> SGNSParams:
    emb = jax.random.uniform(
        key, (vocab_size, dim), dtype=dtype, minval=-0.5 / dim, maxval=0.5 / dim
    )
    ctx = jnp.zeros((vocab_size, dim), dtype=dtype)
    return SGNSParams(emb=emb, ctx=ctx)


def init_params_numpy(
    seed: int, vocab_size: int, dim: int, dtype=np.float32
) -> SGNSParams:
    """Host-side init (used to hand identical starting points to the CPU
    oracle in parity tests)."""
    rng = np.random.RandomState(seed)
    emb = rng.uniform(-0.5 / dim, 0.5 / dim, (vocab_size, dim)).astype(dtype)
    ctx = np.zeros((vocab_size, dim), dtype=dtype)
    return SGNSParams(emb=jnp.asarray(emb), ctx=jnp.asarray(ctx))
