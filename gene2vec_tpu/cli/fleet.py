"""Fleet CLI: N supervised serve replicas behind one front-door proxy.

::

    python -m gene2vec_tpu.cli.fleet --export-dir exports/ --replicas 3

Spawns ``--replicas`` ``cli.serve`` children over the same export dir,
health-checks and restarts them (``serve/fleet.py``), and serves the
round-robin ``/v1/*`` proxy on ``--port``.  Emits exactly ONE JSON line
on stdout once the front door is listening::

    {"url": ..., "replicas": 3, "replica_urls": [...],
     "replica_pids": [...], "run_dir": ...}

— the same machine contract as ``cli.serve`` (``scripts/serve_loadgen``
and ``scripts/chaos_drill.py`` parse it; the drill SIGKILLs replicas by
the advertised pids).  Human chatter goes to stderr; every fleet session
stamps an obs ``Run`` manifest (default run dir
``<export_dir>/fleet_runs/<unix-ts>``) whose registry backs the front
door's ``/metrics``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="fleet",
        description="Supervised multi-replica serving fleet with a "
        "resilient front-door proxy.",
    )
    p.add_argument("--export-dir", required=True,
                   help="io/checkpoint.py export dir every replica serves")
    p.add_argument("--replicas", type=int, default=3,
                   help="initial replica count")
    p.add_argument("--min-replicas", type=int, default=0,
                   help="autoscaling floor (0 = --replicas)")
    p.add_argument("--max-replicas", type=int, default=0,
                   help="autoscaling ceiling; > 0 turns the SLO-driven "
                        "scaler ON (serve/autoscale.py: scale-up on "
                        "queue/rejection/availability breach, slow "
                        "hysteresis scale-down with a zero-drop drain; "
                        "docs/SERVING.md#elastic-fleet).  Requires "
                        "--scrape-interval > 0 — the scaler reads the "
                        "aggregator's snapshot each tick")
    p.add_argument("--scale-up-queue", type=float, default=8.0,
                   help="scale-up breach threshold: fleet queue depth "
                        "PER replica")
    p.add_argument("--scale-up-rejection", type=float, default=0.02,
                   help="scale-up breach threshold: windowed rejection "
                        "rate (delta per tick, not lifetime)")
    p.add_argument("--scale-up-after", type=int, default=2,
                   help="consecutive breach ticks before scaling up")
    p.add_argument("--scale-down-queue", type=float, default=1.0,
                   help="scale-down clear threshold: fleet queue depth "
                        "per replica must sit at or below this for the "
                        "whole clear window (the hysteresis band is the "
                        "gap up to --scale-up-queue)")
    p.add_argument("--scale-down-after", type=int, default=30,
                   help="consecutive CLEAR ticks before scaling down "
                        "(asymmetric on purpose: ramps are emergencies, "
                        "idle capacity is not)")
    p.add_argument("--scale-cooldown", type=float, default=10.0,
                   help="seconds after an action completes before the "
                        "next may fire (anti-flap)")
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   help="max seconds to wait for a draining replica's "
                        "in-flight requests to settle before it is "
                        "terminated anyway (also bounds the fleet-wide "
                        "shutdown drain)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8100,
                   help="front-door port; 0 picks an ephemeral one "
                        "(printed in the JSON status line)")
    p.add_argument("--health-interval", type=float, default=0.5,
                   help="seconds between replica readiness probes")
    p.add_argument("--unhealthy-after", type=int, default=3,
                   help="consecutive probe failures before ejection")
    p.add_argument("--readmit-after", type=int, default=2,
                   help="consecutive probe passes before re-admission")
    p.add_argument("--backoff-base", type=float, default=0.5,
                   help="restart backoff base (doubles per attempt, "
                        "jittered)")
    p.add_argument("--storm-max-restarts", type=int, default=5,
                   help="restarts within --storm-window before a slot "
                        "is abandoned")
    p.add_argument("--storm-window", type=float, default=60.0)
    p.add_argument("--proxy-attempts", type=int, default=3,
                   help="front-door max attempts per request "
                        "(failover across replicas)")
    p.add_argument("--proxy-timeout-ms", type=float, default=5000.0,
                   help="front-door default per-request deadline")
    p.add_argument("--proxy-workers", type=int, default=16,
                   help="front-door forwarding worker pool size "
                        "(each forward blocks on a replica round "
                        "trip); saturation answers 429")
    p.add_argument("--proxy-acceptors", type=int, default=1,
                   help="front-door acceptor event loops (> 1 uses "
                        "SO_REUSEPORT)")
    p.add_argument("--hedge", action="store_true",
                   help="enable p95 hedging on the front-door client")
    p.add_argument("--trace-sample", type=float, default=0.0,
                   help="proxy-originated root-trace sampling rate for "
                        "requests without a traceparent header (0..1; "
                        "sampled client contexts always propagate)")
    p.add_argument("--scrape-interval", type=float, default=2.0,
                   help="seconds between replica /metrics scrapes for "
                        "the merged /metrics/fleet view (0 disables "
                        "aggregation)")
    p.add_argument("--alert-rules", default="default", metavar="PATH",
                   help="SLO alert rules evaluated on every scrape tick "
                        "(obs/alerts.py): an alerts.json path, "
                        "'default' for the built-in availability/"
                        "p99/rejection/queue rules, 'none' to disable; "
                        "firings append to <run-dir>/alerts.jsonl and "
                        "assemble incident bundles under "
                        "<run-dir>/incidents/ "
                        "(docs/OBSERVABILITY.md#alerting)")
    p.add_argument("--seed", type=int, default=None,
                   help="restart-jitter seed (reproducible drills)")
    p.add_argument("--run-dir", default=None,
                   help="obs run dir (default: "
                        "<export-dir>/fleet_runs/<unix-ts>)")
    p.add_argument("--serve-arg", action="append", default=[],
                   help="extra flag passed to EVERY replica's cli.serve "
                        "(repeatable)")
    p.add_argument("--replica-arg", action="append", default=[],
                   metavar="IDX:FLAG",
                   help="extra flag for ONE replica, as <index>:<flag> "
                        "(repeatable; the drill injects faults into a "
                        "single replica this way)")
    p.add_argument("--enable-shadow", action="store_true",
                   help="enable the continuous-learning shadow canary "
                        "(loop/shadow.py): the front door exposes "
                        "/v1/shadow/start|stop|report and, while a "
                        "canary is active, duplicates a sampled "
                        "fraction of live /v1/similar traffic to the "
                        "candidate replica off the caller's latency "
                        "path (cli.loop drives this; "
                        "docs/CONTINUOUS.md)")
    p.add_argument("--shard-by-rows", type=int, default=0, metavar="N",
                   help="fleet-sharded index serving: run N row shards "
                        "each owning a CONTIGUOUS row range of the "
                        "table (+ its inverted lists), with the front "
                        "door scatter-gathering /v1/similar across all "
                        "shards and merging shard-local top-k "
                        "(serve/shardgroup.py; docs/SERVING.md"
                        "#sharded-index-serving).  Overrides "
                        "--replicas (total = N x --replicas-per-shard)."
                        "  With --max-replicas the bounds apply PER "
                        "SHARD POOL (shard-aware autoscaling).  Hot "
                        "swap becomes shard-ATOMIC: every (shard, "
                        "replica) cell stages the new iteration, then "
                        "all flip under one epoch token")
    p.add_argument("--replicas-per-shard", type=int, default=1,
                   metavar="R",
                   help="replica GROUP size per row shard (sharded "
                        "mode only): the front door scatters each "
                        "shard leg to any live sibling and fails over "
                        "within the leg's deadline, so a single "
                        "replica death costs zero degraded answers "
                        "(docs/SERVING.md#replicated-shards)")
    p.add_argument("--ggipnn-checkpoint", default=None,
                   help="models/ggipnn_obs checkpoint npz backing the "
                        "FRONT DOOR's cross-shard /v1/interaction "
                        "scorer (sharded mode; without it the MLP head "
                        "keeps its random init and trained_head is "
                        "echoed false).  Unsharded fleets pass the "
                        "flag to replicas via --serve-arg instead")
    p.add_argument("--shard-deadline-ms", type=float, default=2000.0,
                   help="per-shard scatter-leg deadline; a dead or "
                        "slow shard costs at most this before the "
                        "merge proceeds without it (the answer is "
                        "flagged degraded, never a 5xx)")
    p.add_argument("--swap-interval", type=float, default=2.0,
                   help="seconds between the shard swap coordinator's "
                        "export-dir polls (sharded mode only)")
    p.add_argument("--catalog", default=None, metavar="SPEC.json",
                   help="serve a multi-model catalog (serve/catalog.py "
                        "spec): replicas partition into one pool PER "
                        "MODEL (each pool sized by the entry's "
                        "'replicas'), the front door routes "
                        "/v1/<model>/* to the owning pool and "
                        "unprefixed /v1/* to the spec's default, "
                        "per-model token buckets 429 a hot model "
                        "before it starves a cold one, and with "
                        "--max-replicas the autoscaler runs one "
                        "policy per (model) pool — hottest signal "
                        "wins, one action per tick.  Overrides "
                        "--replicas; excludes --shard-by-rows "
                        "(docs/SERVING.md#multi-model-catalog)")
    p.add_argument("--jobs-dir", default=None, metavar="DIR",
                   help="batch-job store root: mounts the /v1/jobs "
                        "lifecycle surface on the front door "
                        "(docs/BATCH.md); jobs query the fleet at "
                        "background priority — scatter-gather when "
                        "sharded, the resilient client otherwise — "
                        "and resume from their committed cursor "
                        "across fleet restarts")
    return p


def parse_replica_args(pairs: List[str]) -> dict:
    out: dict = {}
    for pair in pairs:
        idx, sep, flag = pair.partition(":")
        if not sep:
            raise ValueError(
                f"--replica-arg must be <index>:<flag>, got {pair!r}"
            )
        out.setdefault(int(idx), []).append(flag)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    import random
    import signal

    from gene2vec_tpu.obs.run import Run
    from gene2vec_tpu.serve.client import RetryPolicy
    from gene2vec_tpu.serve.fleet import (
        FleetConfig,
        FleetProxy,
        FleetSupervisor,
    )

    # validate the shard flags BEFORE paying N replica spawns
    if args.shard_by_rows < 0:
        print("error: --shard-by-rows must be >= 0", file=sys.stderr)
        return 2
    if args.replicas_per_shard < 1:
        print("error: --replicas-per-shard must be >= 1",
              file=sys.stderr)
        return 2
    if args.replicas_per_shard > 1 and not args.shard_by_rows:
        print(
            "error: --replicas-per-shard needs --shard-by-rows (an "
            "unsharded fleet's replicas are already one "
            "interchangeable pool; use --replicas)",
            file=sys.stderr,
        )
        return 2
    if args.ggipnn_checkpoint and not os.path.isfile(
        args.ggipnn_checkpoint
    ):
        print(
            f"error: --ggipnn-checkpoint {args.ggipnn_checkpoint!r} "
            "does not exist",
            file=sys.stderr,
        )
        return 2
    if args.catalog and args.shard_by_rows:
        # a catalog partitions replicas by MODEL, row sharding by row
        # range of ONE model's table — combining them would need a
        # (model, shard) grid per entry, which nothing routes yet
        print(
            "error: --catalog cannot combine with --shard-by-rows "
            "(model pools and row shards are different fleet "
            "partitions)",
            file=sys.stderr,
        )
        return 2
    if args.shard_by_rows:
        args.replicas = args.shard_by_rows * args.replicas_per_shard

    # parse + validate the catalog spec BEFORE paying N replica spawns;
    # slots partition into contiguous per-model pools in spec order,
    # and each pool's flags override the supervisor's defaults via
    # argparse last-wins (same mechanism as per-shard flags)
    catalog_spec = None
    model_admission = None
    model_of = None
    model_args = None
    if args.catalog:
        from gene2vec_tpu.serve.catalog import (
            ModelAdmission,
            load_catalog_spec,
        )

        try:
            catalog_spec = load_catalog_spec(args.catalog)
        except (ValueError, OSError) as e:
            print(
                f"error: bad catalog spec {args.catalog!r}: {e}",
                file=sys.stderr,
            )
            return 2
        model_of = {}
        model_args = {}
        slot = 0
        for entry in catalog_spec.entries:
            for _ in range(entry.replicas):
                model_of[slot] = entry.name
                slot += 1
            flags = ["--export-dir", entry.export_dir,
                     "--model-name", entry.name,
                     "--index", entry.index_mode]
            if entry.dim is not None:
                flags += ["--dim", str(entry.dim)]
            if entry.ggipnn_checkpoint:
                flags += ["--ggipnn-checkpoint", entry.ggipnn_checkpoint]
            flags += list(entry.extra_args)
            model_args[entry.name] = flags
        args.replicas = slot
        model_admission = ModelAdmission(catalog_spec)

    # validate the autoscale flags BEFORE paying N replica spawns.  In
    # sharded mode the min/max bounds apply to each SHARD's replica
    # pool: the scaler grows the hot shard's group, never the shard
    # count (shards partition one table — a fixed set)
    autoscale_cfg = None
    if args.shard_by_rows:
        pool_base = args.replicas_per_shard
    elif catalog_spec is not None:
        # default floor for every model pool: the smallest boot-time
        # pool (a per-model floor above some entry's own size would
        # scale it up at the first tick)
        pool_base = min(e.replicas for e in catalog_spec.entries)
    else:
        pool_base = args.replicas
    if args.max_replicas > 0:
        from gene2vec_tpu.serve.autoscale import AutoscaleConfig

        if args.scrape_interval <= 0:
            print(
                "error: --max-replicas needs --scrape-interval > 0 — "
                "the scaler reads the fleet aggregator's snapshot each "
                "scrape tick",
                file=sys.stderr,
            )
            return 2
        try:
            autoscale_cfg = AutoscaleConfig(
                min_replicas=args.min_replicas or pool_base,
                max_replicas=args.max_replicas,
                up_queue_per_replica=args.scale_up_queue,
                up_rejection_rate=args.scale_up_rejection,
                up_after_ticks=args.scale_up_after,
                down_queue_per_replica=args.scale_down_queue,
                down_after_ticks=args.scale_down_after,
                cooldown_s=args.scale_cooldown,
            )
        except ValueError as e:
            print(f"error: bad autoscale flags: {e}", file=sys.stderr)
            return 2
        if catalog_spec is not None:
            # the bounds apply to each MODEL's pool: every entry's
            # boot-time size must sit inside them or the scaler's
            # first tick would immediately fight the spec
            for entry in catalog_spec.entries:
                if not (autoscale_cfg.min_replicas <= entry.replicas
                        <= autoscale_cfg.max_replicas):
                    print(
                        f"error: catalog model {entry.name!r} "
                        f"replicas {entry.replicas} outside "
                        f"[{autoscale_cfg.min_replicas}, "
                        f"{autoscale_cfg.max_replicas}]",
                        file=sys.stderr,
                    )
                    return 2
        elif pool_base < autoscale_cfg.min_replicas or (
            pool_base > autoscale_cfg.max_replicas
        ):
            what = (
                "--replicas-per-shard" if args.shard_by_rows
                else "--replicas"
            )
            print(
                f"error: {what} {pool_base} outside "
                f"[{autoscale_cfg.min_replicas}, "
                f"{autoscale_cfg.max_replicas}]",
                file=sys.stderr,
            )
            return 2

    run_dir = args.run_dir or os.path.join(
        args.export_dir, "fleet_runs", str(int(time.time()))
    )
    run = Run(run_dir, name="fleet", config=vars(args))

    # installed BEFORE any replica exists: a SIGTERM during the (long,
    # jax-importing) start window must still tear the replicas down —
    # dying silently would orphan N serving processes
    def _on_term(signum, frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _on_term)
    replica_args = parse_replica_args(args.replica_arg)
    shard_of = None
    shard_args = None
    if args.shard_by_rows:
        # the (shard, replica) grid: slot i serves shard i // R —
        # shard flags are keyed by SHARD (not slot), so supervisor
        # restarts AND elastically-added siblings reload exactly their
        # shard's row range
        shard_of = {
            i: i // args.replicas_per_shard
            for i in range(args.replicas)
        }
        shard_args = {
            s: ["--shard-index", str(s),
                "--num-shards", str(args.shard_by_rows)]
            for s in range(args.shard_by_rows)
        }
    supervisor = FleetSupervisor(
        args.export_dir,
        config=FleetConfig(
            replicas=args.replicas,
            health_interval_s=args.health_interval,
            unhealthy_after=args.unhealthy_after,
            readmit_after=args.readmit_after,
            backoff_base_s=args.backoff_base,
            storm_max_restarts=args.storm_max_restarts,
            storm_window_s=args.storm_window,
        ),
        serve_args=args.serve_arg,
        replica_args=replica_args,
        metrics=run.registry,
        rng=random.Random(args.seed),
        shard_of=shard_of,
        shard_args=shard_args,
        model_of=model_of,
        model_args=model_args,
    )
    # validate the alert rules BEFORE paying N replica spawns — a typo'd
    # alerts.json must fail in milliseconds
    alert_rules = None
    if args.alert_rules and args.alert_rules != "none":
        from gene2vec_tpu.obs import alerts as alerts_mod

        try:
            alert_rules = (
                alerts_mod.default_rules()
                if args.alert_rules == "default"
                else alerts_mod.load_rules(args.alert_rules)
            )
        except (OSError, ValueError) as e:
            print(f"error: bad --alert-rules: {e}", file=sys.stderr)
            run.close()
            return 2
        if args.scrape_interval <= 0:
            print(
                "warning: --alert-rules given but --scrape-interval 0 "
                "disables the aggregator tick; alerting is off",
                file=sys.stderr,
            )
    try:
        supervisor.start()
    except BaseException as e:
        # start() already tears down its own replicas on failure; the
        # extra stop() here is an idempotent belt for interrupt timing
        supervisor.stop()
        print(f"error: fleet failed to start: {e!r}", file=sys.stderr)
        run.close()
        return 2
    shadow = None
    if args.enable_shadow:
        from gene2vec_tpu.loop.shadow import ShadowManager

        shadow = ShadowManager(metrics=run.registry)
    proxy = FleetProxy(
        supervisor,
        metrics=run.registry,
        policy=RetryPolicy(
            max_attempts=args.proxy_attempts,
            default_timeout_s=args.proxy_timeout_ms / 1000.0,
            hedge=args.hedge,
        ),
        trace_sample=args.trace_sample,
        scrape_interval_s=args.scrape_interval,
        telemetry_csv=os.path.join(run.run_dir, "fleet_telemetry.csv"),
        flight_dir=run.run_dir,
        proxy_workers=args.proxy_workers,
        acceptors=args.proxy_acceptors,
        alert_rules=alert_rules,
        shadow=shadow,
        catalog=catalog_spec,
        model_admission=model_admission,
    )
    if catalog_spec is not None and proxy.aggregator is not None:
        # per-model telemetry projections: queue depth, staleness, and
        # replica-up gauges keyed by the supervisor's slot->model map
        proxy.aggregator.model_of = supervisor.model_of_url
        proxy.aggregator.model_pool_facts = supervisor.model_up_counts
    coordinator = None
    group = None
    if args.shard_by_rows:
        from gene2vec_tpu.serve.shardgroup import (
            RoutingTable,
            ShardGroup,
            ShardGroupConfig,
            SwapCoordinator,
        )

        routing = RoutingTable(
            args.export_dir, args.shard_by_rows, dim=None
        )
        if not routing.reload():
            print(
                "error: no verified checkpoint to derive the "
                "gene->shard routing table from",
                file=sys.stderr,
            )
            supervisor.stop()
            run.close()
            return 2
        group = ShardGroup(
            ShardGroupConfig(
                num_shards=args.shard_by_rows,
                shard_deadline_s=args.shard_deadline_ms / 1000.0,
                default_timeout_s=args.proxy_timeout_ms / 1000.0,
            ),
            # the whole replica GROUP per shard: the client round-
            # robins siblings and fails over within the leg deadline
            supervisor.shard_urls,
            metrics=run.registry,
            policy=RetryPolicy(
                max_attempts=args.proxy_attempts,
                default_timeout_s=args.shard_deadline_ms / 1000.0,
                hedge=args.hedge,
            ),
            inflight=proxy.inflight,
            routing=routing,
            ggipnn_checkpoint=args.ggipnn_checkpoint,
        )
        proxy.shard_group = group
        if proxy.aggregator is not None:
            # per-shard telemetry projections + the redundancy view
            def _shard_of(url: str):
                u = url.rstrip("/")
                for r in supervisor.replicas:
                    if r.url == u:
                        return r.shard
                return None

            proxy.aggregator.shard_of = _shard_of
            # supervisor-truth redundancy: desired tracks the CURRENT
            # per-shard promise (drained slots excluded), so a
            # deliberate autoscale scale-down below the boot-time
            # --replicas-per-shard does not page shard-redundancy-lost
            proxy.aggregator.shard_facts = (
                supervisor.shard_redundancy_facts
            )
        coordinator = SwapCoordinator(
            args.export_dir,
            group,
            interval_s=args.swap_interval,
            metrics=run.registry,
        )
        coordinator.start()
    controller = None
    if autoscale_cfg is not None:
        if args.shard_by_rows:
            from gene2vec_tpu.serve.autoscale import (
                ShardElasticController,
            )

            controller = ShardElasticController(
                supervisor,
                proxy,
                autoscale_cfg,
                num_shards=args.shard_by_rows,
                metrics=run.registry,
                drain_timeout_s=args.drain_timeout,
            )
        elif catalog_spec is not None:
            from gene2vec_tpu.serve.autoscale import (
                PoolElasticController,
            )

            # one policy per MODEL pool; the hottest pool's signal
            # wins the tick, scale-down never drains a model's last
            # UP replica (the default's surface must stay answerable)
            controller = PoolElasticController(
                supervisor,
                proxy,
                autoscale_cfg,
                pools=[(name, None) for name in catalog_spec.names],
                metrics=run.registry,
                drain_timeout_s=args.drain_timeout,
            )
        else:
            from gene2vec_tpu.serve.autoscale import ElasticController

            controller = ElasticController(
                supervisor,
                proxy,
                autoscale_cfg,
                metrics=run.registry,
                drain_timeout_s=args.drain_timeout,
            )
        # the scaler rides the aggregator's scrape tick, after the
        # alert evaluator — same snapshot, zero serve-path cost
        assert proxy.aggregator is not None
        proxy.aggregator.observers.append(controller.observe)
    if args.jobs_dir:
        from gene2vec_tpu.batch.jobs import JobManager
        from gene2vec_tpu.batch.runner import (
            ClientBackend,
            ShardGroupBackend,
        )

        # the sharded backend's Pacer yield guard: Σ replica queue
        # depth (the aggregator publishes it every scrape tick; the
        # same signal the autoscaler scales on), normalized so ~2
        # queued interactive requests per replica reads as 1.0 —
        # batch pauses between chunks while the fleet is backlogged
        batch_pressure = {"value": 0.0}
        if proxy.aggregator is not None:

            def _note_batch_pressure(snapshot, wall=None) -> None:
                depth = float(
                    snapshot.get("fleet_queue_depth", 0.0) or 0.0
                )
                n = max(1, len(supervisor.replicas))
                batch_pressure["value"] = depth / (2.0 * n)

            proxy.aggregator.observers.append(_note_batch_pressure)

        def _job_backend():
            # built per job RUN so each pins the iteration the fleet
            # serves at that moment (batch/runner.py determinism
            # contract); sharded fleets scatter-gather, unsharded ones
            # go through the resilient client on the batch tenant lane
            if proxy.shard_group is not None:
                return ShardGroupBackend(
                    proxy.shard_group,
                    pressure_fn=lambda: batch_pressure["value"],
                )
            return ClientBackend(proxy.client)

        proxy.jobs = JobManager(
            args.jobs_dir, _job_backend, metrics=run.registry,
        )
    url = proxy.serve(args.host, args.port)
    run.annotate(fleet_url=url)
    run.event(
        "fleet_start", url=url, replicas=args.replicas,
        replica_urls=[r.url for r in supervisor.replicas],
    )
    print(
        json.dumps(
            {
                "url": url,
                "replicas": args.replicas,
                "replica_urls": [r.url for r in supervisor.replicas],
                "replica_pids": [r.pid for r in supervisor.replicas],
                "run_dir": run.run_dir,
                "shadow": bool(args.enable_shadow),
                "jobs_dir": args.jobs_dir,
                "autoscale": (
                    {
                        "min": autoscale_cfg.min_replicas,
                        "max": autoscale_cfg.max_replicas,
                    }
                    if autoscale_cfg is not None else None
                ),
                "catalog": (
                    {
                        "default": catalog_spec.default,
                        # slot indices per model — the drill targets
                        # one model's pool (kill, swap, scale) by these
                        "models": {
                            e.name: {
                                "replicas": e.replicas,
                                "slots": [
                                    r.index
                                    for r in supervisor.replicas
                                    if r.model == e.name
                                ],
                            }
                            for e in catalog_spec.entries
                        },
                    }
                    if catalog_spec is not None else None
                ),
                "shards": (
                    {
                        "num_shards": args.shard_by_rows,
                        "replicas_per_shard": args.replicas_per_shard,
                        "total_rows": proxy.shard_group.routing
                        .total_rows,
                        "ranges": [
                            list(r) for r in
                            proxy.shard_group.routing.ranges
                        ],
                        # slot indices per shard — the drill SIGKILLs
                        # one sibling of a group by these
                        "groups": {
                            str(s): [
                                r.index for r in supervisor.replicas
                                if r.shard == s
                            ]
                            for s in range(args.shard_by_rows)
                        },
                    }
                    if args.shard_by_rows else None
                ),
            }
        ),
        flush=True,
    )
    print(
        f"fleet of {args.replicas} replicas over {args.export_dir} "
        f"fronted at {url}; run dir {run.run_dir}"
        + (
            f"; autoscaling [{autoscale_cfg.min_replicas}, "
            f"{autoscale_cfg.max_replicas}]"
            if autoscale_cfg is not None else ""
        ),
        file=sys.stderr,
    )
    try:
        while True:
            time.sleep(3600.0)
    except KeyboardInterrupt:
        print("shutting down fleet", file=sys.stderr)
    finally:
        # graceful, zero-drop shutdown ordering: stop scaling, stop
        # accepting (front door down), DRAIN the forwards the proxy
        # already dispatched, and only then SIGTERM the replicas —
        # tearing children down under the proxy's in-flight requests
        # was exactly the drop scale-down exists to prevent
        if controller is not None:
            controller.stop()
        if coordinator is not None:
            coordinator.stop()
        proxy.stop()
        proxy.drain(timeout_s=args.drain_timeout)
        supervisor.stop()
        run.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
