"""Embedding plot CLI — ``src/plot_gene2vec.py`` parity."""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="plot",
        description="Reduce an embedding to 2-D/3-D and export an "
                    "interactive scatter (json + html/png).",
    )
    p.add_argument("emb_file")
    p.add_argument("out_prefix", help="output path prefix (no extension)")
    p.add_argument(
        "--method", choices=("auto", "umap", "tsne", "pca"), default="auto"
    )
    p.add_argument("--components", type=int, choices=(2, 3), default=2)
    p.add_argument(
        "--annotate", action="store_true",
        help="query NCBI gene info via mygene (network; gated)",
    )
    p.add_argument("--seed", type=int, default=0)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    from gene2vec_tpu.viz.plot import plot_gene2vec

    plot_gene2vec(
        args.emb_file,
        args.out_prefix,
        method=args.method,
        n_components=args.components,
        annotate=args.annotate,
        seed=args.seed,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
