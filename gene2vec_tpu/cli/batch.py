"""Batch-job driver: submit/status/cancel/fetch against a serve front
door, or run a job locally against an export dir.

``python -m gene2vec_tpu.cli.batch submit --url http://... --type
knn_graph --k 10 --wait --out graph_dir`` drives the whole lifecycle:
submit (idempotent under ``--job-id``), poll to completion, and
reassemble the artifact dir locally — CRC-verified against the
manifest, so a torn fetch never masquerades as a graph.

``--export-dir`` instead of ``--url`` runs the job in-process against
the newest verified checkpoint (no serving stack; the bench's oracle
path and the chaos drill's SIGKILL target).  Local runs write straight
into ``--out`` under the same cursor commit protocol, so re-running
the identical command after a kill RESUMES from the committed chunk
and converges to the bit-identical final artifact (docs/BATCH.md
#resume-semantics).

Emits the repo's one-line JSON contract on stdout.
"""

from __future__ import annotations

import argparse
import base64
import json
import sys
import time
import urllib.error
import urllib.request
from typing import List, Optional, Tuple


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="batch",
        description="Offline batch jobs (kNN graph / pair scores / "
                    "embedding export) on a serve fleet or a local "
                    "checkpoint.",
    )
    p.add_argument("verb",
                   choices=("submit", "status", "cancel", "fetch",
                            "list"),
                   help="lifecycle verb; 'submit' with --export-dir "
                        "runs locally instead of through a front door")
    p.add_argument("--url", default=None,
                   help="serve front door (single replica with "
                        "--jobs-dir, or the fleet proxy)")
    p.add_argument("--export-dir", default=None,
                   help="local mode: run the job in-process against "
                        "the newest verified checkpoint here")
    p.add_argument("--type", default="knn_graph",
                   choices=("knn_graph", "pair_scores", "export"),
                   dest="job_type")
    p.add_argument("--k", type=int, default=10,
                   help="neighbors per row (knn_graph)")
    p.add_argument("--chunk-rows", type=int, default=256,
                   help="records per committed chunk")
    p.add_argument("--pairs-file", default=None,
                   help="pair_scores input: one 'GENE_A<TAB>GENE_B' "
                        "per line")
    p.add_argument("--job-id", default=None,
                   help="explicit job id (submit is idempotent under "
                        "it; required for status/cancel/fetch)")
    p.add_argument("--wait", action="store_true",
                   help="poll after submit until the job settles")
    p.add_argument("--poll-s", type=float, default=0.5)
    p.add_argument("--timeout-s", type=float, default=3600.0,
                   help="--wait gives up (exit 1, job keeps running) "
                        "after this long")
    p.add_argument("--out", default=None, metavar="DIR",
                   help="artifact destination dir: local mode writes "
                        "the job here directly; remote fetch "
                        "reassembles the artifact here (CRC-verified)")
    p.add_argument("--index", default="exact",
                   choices=("exact", "quant", "ivf"),
                   help="local mode retrieval index")
    p.add_argument("--ggipnn-checkpoint", default=None,
                   help="local mode: trained GGIPNN head for "
                        "pair_scores")
    return p


def _http(url: str, method: str = "GET",
          body: Optional[dict] = None) -> Tuple[int, dict]:
    data = json.dumps(body).encode("utf-8") if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=60.0) as resp:
            return resp.status, json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode("utf-8"))
        except (ValueError, OSError):
            return e.code, {"error": f"HTTP {e.code}"}


def _read_pairs(path: str) -> List[List[str]]:
    pairs: List[List[str]] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2:
                pairs.append([parts[0], parts[1]])
    if not pairs:
        raise SystemExit(f"error: no pairs in {path}")
    return pairs


def _fetch_part(url: str, job_id: str, part: str) -> Tuple[bytes, dict]:
    blob = b""
    offset = 0
    while True:
        status, doc = _http(
            f"{url}/v1/jobs/{job_id}/artifact"
            f"?offset={offset}&part={part}"
        )
        if status != 200:
            raise SystemExit(
                f"error: artifact fetch -> {status}: {doc.get('error')}"
            )
        blob += base64.b64decode(doc["data_b64"])
        offset = len(blob)
        if doc["eof"]:
            return blob, doc


def _fetch(url: str, job_id: str, out_dir: str) -> dict:
    from gene2vec_tpu.batch.artifact import write_fetched_artifact

    data, doc = _fetch_part(url, job_id, "data")
    tokens: Optional[bytes] = None
    if doc.get("meta", {}).get("type") == "knn_graph":
        tokens, _ = _fetch_part(url, job_id, "tokens")
    write_fetched_artifact(
        out_dir, data, doc.get("meta", {}), doc["chunks"],
        doc["records"], doc["data_crc32"], tokens_bytes=tokens,
    )
    return {
        "job_id": job_id,
        "artifact_dir": out_dir,
        "data_bytes": len(data),
        "data_crc32": doc["data_crc32"],
        "records": doc["records"],
        "meta": doc.get("meta", {}),
    }


def _wait(url: str, job_id: str, poll_s: float,
          timeout_s: float) -> dict:
    deadline = time.monotonic() + timeout_s
    while True:
        status, doc = _http(f"{url}/v1/jobs/{job_id}")
        if status != 200:
            raise SystemExit(
                f"error: status -> {status}: {doc.get('error')}"
            )
        if doc.get("state") in ("done", "failed", "cancelled"):
            return doc
        if time.monotonic() > deadline:
            doc["wait_timeout"] = True
            return doc
        time.sleep(poll_s)


def _run_local(args) -> dict:
    import os

    from gene2vec_tpu.batch.artifact import ChunkedArtifact
    from gene2vec_tpu.batch.jobs import JobSpec
    from gene2vec_tpu.batch.runner import EngineBackend, run_job
    from gene2vec_tpu.serve.engine import SimilarityEngine
    from gene2vec_tpu.serve.registry import ModelRegistry

    if not args.out:
        raise SystemExit("error: local mode needs --out DIR")
    registry = ModelRegistry(args.export_dir, index_mode=args.index)
    if not registry.refresh():
        raise SystemExit(
            f"error: no verified checkpoint under {args.export_dir}"
        )
    backend = EngineBackend(
        registry.model,
        SimilarityEngine(index=args.index),
        ggipnn_checkpoint=args.ggipnn_checkpoint,
    )
    spec = JobSpec(
        type=args.job_type, k=args.k, chunk_rows=args.chunk_rows,
        pairs=_read_pairs(args.pairs_file)
        if args.job_type == "pair_scores" else None,
        job_id=args.job_id,
    )
    art = ChunkedArtifact(args.out)
    result = run_job(spec, backend, art)
    result["mode"] = "local"
    result["type"] = args.job_type
    result["iteration"] = int(backend.iteration)
    result["artifact_dir"] = os.path.abspath(args.out)
    return result


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.verb == "submit" and args.export_dir:
        print(json.dumps(_run_local(args)))
        return 0
    if not args.url:
        raise SystemExit(
            "error: need --url (or --export-dir for local submit)"
        )
    url = args.url.rstrip("/")
    if args.verb == "list":
        status, doc = _http(f"{url}/v1/jobs")
    elif args.verb == "submit":
        body = {
            "type": args.job_type, "k": args.k,
            "chunk_rows": args.chunk_rows,
        }
        if args.job_type == "pair_scores":
            if not args.pairs_file:
                raise SystemExit(
                    "error: pair_scores needs --pairs-file"
                )
            body["pairs"] = _read_pairs(args.pairs_file)
        if args.job_id:
            body["job_id"] = args.job_id
        status, doc = _http(f"{url}/v1/jobs", "POST", body)
        if status == 200 and args.wait:
            doc = _wait(url, doc["job_id"], args.poll_s, args.timeout_s)
            if doc.get("state") == "done" and args.out:
                doc["fetch"] = _fetch(url, doc["job_id"], args.out)
    else:
        if not args.job_id:
            raise SystemExit(f"error: {args.verb} needs --job-id")
        if args.verb == "status":
            status, doc = _http(f"{url}/v1/jobs/{args.job_id}")
        elif args.verb == "cancel":
            status, doc = _http(
                f"{url}/v1/jobs/{args.job_id}/cancel", "POST"
            )
        else:  # fetch
            if not args.out:
                raise SystemExit("error: fetch needs --out DIR")
            doc = _fetch(url, args.job_id, args.out)
            status = 200
    print(json.dumps(doc))
    if status != 200:
        return 1
    return 1 if doc.get("state") in ("failed",) or doc.get(
        "wait_timeout"
    ) else 0


if __name__ == "__main__":
    sys.exit(main())
