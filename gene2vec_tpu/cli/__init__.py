"""Command-line front-ends.

One argparse CLI per reference script, unified over the dataclass config
system (SURVEY §5 "config/flag system"):

* ``python -m gene2vec_tpu.cli.gene2vec data_dir out_dir txt``
  (+ ``--backend``, training flags) — ``src/gene2vec.py:8-15`` parity;
* ``python -m gene2vec_tpu.cli.generate_pairs --query Q --out O ...``
  — ``src/generate_gene_pairs.py:12-42`` parity;
* ``python -m gene2vec_tpu.cli.ggipnn --data-dir D --emb E ...``
  — ``src/GGIPNN_Classification.py:14-32`` parity;
* ``python -m gene2vec_tpu.cli.evaluate emb.txt msigdb.gmt``
  — ``src/evaluation_target_function.py`` parity;
* ``python -m gene2vec_tpu.cli.tsne`` / ``...cli.plot``
  — ``src/tsne_multi_core.py`` / ``src/plot_gene2vec.py`` parity;
* ``python -m gene2vec_tpu.cli.dashboard --figure-json fig.json``
  — ``src/gene2vec_dash_app.py:17-27`` parity (GeneView, needs dash);
* ``python -m gene2vec_tpu.cli.obs report <run_dir>``
  — summarize any observed run directory (docs/OBSERVABILITY.md);
* ``python -m gene2vec_tpu.cli.analyze [--hlo all] [--sanitizers ...]``
  — graftcheck static analysis + sanitizer gates
  (docs/STATIC_ANALYSIS.md).
"""
