"""Intrinsic-evaluation CLI: the pathway/random "target function".

``python -m gene2vec_tpu.cli.evaluate emb_file gmt_file`` prints the score
the reference's ``src/evaluation_target_function.py`` computes (pathways
over 50 genes skipped, fixed seed 35 for the random-pair denominator).

``--json`` (optionally with ``--out PATH``) emits a provenance-stamped
JSON product instead — ``schema_version``/``command``/``created_unix``
through the ledger's canonical stamp (the same convention ``bench.py``'s
``bench_stamp()`` uses), so a committed evaluation artifact ingests
into the bench ledger with provenance instead of ``legacy_unstamped``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from gene2vec_tpu.eval.target_function import (
    MAX_PATHWAY_GENES,
    RANDOM_PAIR_GENES,
    RANDOM_SEED,
    target_function,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="evaluate",
        description="Pathway-vs-random cosine similarity ratio of an "
                    "embedding file.",
    )
    p.add_argument("emb_file", nargs="?", default=None,
                   help="matrix-txt or word2vec-format embedding "
                        "(optional when --graph-dir evaluates a "
                        "precomputed kNN graph instead)")
    p.add_argument("gmt_file", help="MSigDB .gmt pathway file")
    p.add_argument("--graph-dir", default=None, metavar="DIR",
                   help="evaluate a finalized knn_graph batch artifact "
                        "(gene2vec_tpu/batch/, docs/BATCH.md) instead "
                        "of an embedding file: pathway neighborhood "
                        "hit rate vs degree-matched random, as served "
                        "by the fleet that built the graph")
    p.add_argument("--max-pathway-genes", type=int, default=MAX_PATHWAY_GENES)
    p.add_argument("--num-random-genes", type=int, default=RANDOM_PAIR_GENES)
    p.add_argument("--seed", type=int, default=RANDOM_SEED)
    p.add_argument("--json", action="store_true",
                   help="emit a provenance-stamped JSON document "
                        "(schema_version/command/created_unix) instead "
                        "of the bare score")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="also write the stamped JSON document to PATH "
                        "(implies --json semantics for the file; "
                        "stdout format still follows --json)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.graph_dir:
        from gene2vec_tpu.eval.target_function import (
            graph_neighborhood_ratio,
        )

        facts = graph_neighborhood_ratio(
            args.graph_dir,
            args.gmt_file,
            max_pathway_genes=args.max_pathway_genes,
            seed=args.seed,
        )
        body = {
            "schema": "gene2vec-tpu/graph-eval/v1",
            "graph_dir": args.graph_dir,
            "gmt_file": args.gmt_file,
            **facts,
        }
        score = facts["ratio"]
    else:
        if not args.emb_file:
            raise SystemExit(
                "error: need an emb_file (or --graph-dir)"
            )
        score = target_function(
            args.emb_file,
            args.gmt_file,
            max_pathway_genes=args.max_pathway_genes,
            num_random_genes=args.num_random_genes,
            seed=args.seed,
        )
        body = {
            "schema": "gene2vec-tpu/intrinsic-eval/v1",
            "trained_target_func_ratio": score,
            "emb_file": args.emb_file,
            "gmt_file": args.gmt_file,
            "max_pathway_genes": args.max_pathway_genes,
            "num_random_genes": args.num_random_genes,
            "seed": args.seed,
        }
    if args.json or args.out:
        from gene2vec_tpu.obs.ledger import provenance_stamp

        doc = provenance_stamp(body)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1)
    if args.json:
        print(json.dumps(doc))
    else:
        print(score)
    return 0


if __name__ == "__main__":
    sys.exit(main())
