"""GGIPNN train/eval CLI.

Flag parity with the reference's TF1 flags
(``src/GGIPNN_Classification.py:14-32``): embedding dim, embedTrain,
use_pre_trained, batch size, epochs, eval/checkpoint cadence; data layout is
a ``predictionData/``-shaped directory with train/valid/test
``_text.txt`` + ``_label.txt`` files (``README.md:71-87``).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from gene2vec_tpu.config import GGIPNNConfig


def build_parser() -> argparse.ArgumentParser:
    d = GGIPNNConfig()
    p = argparse.ArgumentParser(
        prog="ggipnn",
        description="Train the gene-gene-interaction MLP and print test AUC.",
    )
    p.add_argument("--data-dir", required=True,
                   help="predictionData/-shaped directory")
    p.add_argument("--emb", default=None,
                   help="pretrained embedding file (matrix-txt or w2v format)")
    p.add_argument("--embedding-dim", type=int, default=d.embedding_dim)
    p.add_argument("--embed-train", action="store_true",
                   help="fine-tune the embedding table (default frozen)")
    p.add_argument("--no-pretrained", action="store_true",
                   help="skip pretrained embedding (random table)")
    p.add_argument("--batch-size", type=int, default=d.batch_size)
    p.add_argument("--num-epochs", type=int, default=d.num_epochs)
    p.add_argument("--learning-rate", type=float, default=d.learning_rate)
    p.add_argument("--dropout-keep-prob", type=float, default=d.dropout_keep_prob)
    p.add_argument("--l2-lambda", type=float, default=d.l2_lambda)
    p.add_argument("--evaluate-every", type=int, default=d.evaluate_every)
    p.add_argument("--checkpoint-every", type=int, default=d.checkpoint_every)
    p.add_argument("--seed", type=int, default=d.seed)
    p.add_argument("--run-dir", default=None,
                   help="write a runs/<ts>-style artifact dir (train/dev "
                   "TensorBoard summaries with grad histograms, keep-5 "
                   "step checkpoints) at the reference cadence; slower "
                   "than the default scanned fast path")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    config = GGIPNNConfig(
        embedding_dim=args.embedding_dim,
        embed_train=args.embed_train,
        use_pretrained=not args.no_pretrained and args.emb is not None,
        batch_size=args.batch_size,
        num_epochs=args.num_epochs,
        learning_rate=args.learning_rate,
        dropout_keep_prob=args.dropout_keep_prob,
        l2_lambda=args.l2_lambda,
        evaluate_every=args.evaluate_every,
        checkpoint_every=args.checkpoint_every,
        seed=args.seed,
    )
    from gene2vec_tpu.models.ggipnn_train import run_classification
    from gene2vec_tpu.resilience.preempt import EXIT_PREEMPTED, PreemptionHandler

    with PreemptionHandler() as handler:
        run_classification(
            args.data_dir, args.emb, config, run_dir=args.run_dir,
            preempt=handler,
        )
    if handler.triggered:
        # 113 here means "drained cleanly", NOT "resume me": this
        # harness has no resume path — a rerun retrains from scratch
        # (--run-dir step checkpoints are artifacts for analysis, not
        # resume points).  docs/RESILIENCE.md exit-code table.
        print(
            f"preempted (signal {handler.received}); training drained "
            "cleanly. NOTE: ggipnn has no resume path — rerunning "
            "restarts training"
            + (
                " (step checkpoints are under --run-dir)"
                if args.run_dir
                else ""
            ),
            file=sys.stderr,
        )
        return EXIT_PREEMPTED
    return 0


if __name__ == "__main__":
    sys.exit(main())
