"""Co-expression pair-corpus construction CLI.

Flag-compatible with the reference (``src/generate_gene_pairs.py:12-42``):
``--query --out --corr-threshold --min-study-samples --parallel --ensembl``,
plus ``--backend`` to run the correlation matmul on TPU.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="generate-pairs",
        description="Build a gene co-expression pair corpus from a query "
                    "directory (data/SRARunTable.csv, data/gene_counts_TPM.csv, "
                    "data/gene_counts.csv).",
    )
    p.add_argument("--query", required=True, help="query directory")
    p.add_argument("--out", required=True, help="output pair-file path")
    p.add_argument("--corr-threshold", type=float, default=0.9)
    p.add_argument("--min-study-samples", type=int, default=20)
    p.add_argument("--min-total-counts", type=float, default=10.0)
    p.add_argument(
        "--parallel", action="store_true",
        help="per-study multiprocessing (the reference used a Ray cluster)",
    )
    p.add_argument("--num-workers", type=int, default=0)
    p.add_argument(
        "--ensembl", action="store_true",
        help="keep ENSEMBL ids instead of annotating gene symbols",
    )
    p.add_argument(
        "--backend", choices=("numpy", "jax"), default="numpy",
        help="correlation matmul backend (jax = TPU MXU)",
    )
    p.add_argument(
        "--run-dir", default=None,
        help="observe the build: manifest.json + events.jsonl with "
             "per-study spans (summarize with "
             "`python -m gene2vec_tpu.cli.obs report <run_dir>`)",
    )
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    from gene2vec_tpu.corpus.builder import build_pairs

    build_pairs(
        args.query,
        args.out,
        corr_threshold=args.corr_threshold,
        min_study_samples=args.min_study_samples,
        min_total_counts=args.min_total_counts,
        ensembl=args.ensembl,
        parallel=args.parallel,
        num_workers=args.num_workers or None,
        backend=args.backend,
        run_dir=args.run_dir,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
