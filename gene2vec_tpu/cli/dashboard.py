"""GeneView dashboard CLI — ``src/gene2vec_dash_app.py:17-27`` parity
(``--figure-json``), extended with the annotation-source flags the
reference hardcodes as absolute paths (``:37,84``)."""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dashboard",
        description="Interactive GeneView dashboard for a gene embedding "
        "figure (requires the dash package).",
    )
    p.add_argument("--figure-json", required=True, dest="json",
                   help="plotly-json scatter exported by the plot CLI")
    p.add_argument("--go-obo", default=None, help="go-basic.obo path")
    p.add_argument("--gene2go", default=None, help="NCBI gene2go path")
    p.add_argument("--reactome", default=None,
                   help="NCBI2Reactome_All_Levels.txt path")
    p.add_argument("--go-table", default=None,
                   help="flat TSV (term, gene, description) alternative")
    p.add_argument("--reactome-table", default=None)
    p.add_argument("--taxid", type=int, action="append", default=None,
                   help="filter gene2go to these tax ids (repeatable)")
    p.add_argument("--species", action="append", default=None,
                   help="filter the reactome table to these species")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8050)
    p.add_argument("--debug", action="store_true")
    p.add_argument("--serve-url", default=None,
                   help="base URL of a running `python -m gene2vec_tpu."
                        "cli.serve` instance; adds a live neighbor-search "
                        "box backed by its /v1/similar endpoint (lookups "
                        "fall back to the figure-json path on failure)")
    p.add_argument("--serve-k", type=int, default=10,
                   help="neighbors fetched per --serve-url lookup")
    p.add_argument("--graph-dir", default=None,
                   help="finalized knn_graph batch artifact (cli.batch, "
                        "docs/BATCH.md): powers the Neighbors box "
                        "offline, and is the fallback when --serve-url "
                        "is unreachable")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    from gene2vec_tpu.viz.dash_app import serve

    serve(
        args.json,
        go_table=args.go_table,
        reactome_table=args.reactome_table,
        go_obo=args.go_obo,
        gene2go=args.gene2go,
        reactome_file=args.reactome,
        taxids=args.taxid,
        species=args.species,
        host=args.host,
        port=args.port,
        debug=args.debug,
        serve_url=args.serve_url,
        serve_k=args.serve_k,
        graph_dir=args.graph_dir,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
