"""Embedding-training CLI — reference-shape compatible.

The reference invocation is ``python gene2vec.py data_dir out_dir txt``
(positional; ``src/gene2vec.py:8-15``, ``README.md:36-38``).  Same three
positionals here, plus flags for everything the reference hardcodes
(``src/gene2vec.py:57-63``) and the BASELINE-mandated ``--backend`` switch.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from gene2vec_tpu.config import MeshConfig, SGNSConfig
from gene2vec_tpu.sgns.backends import BACKENDS


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gene2vec",
        description="Train gene embeddings from a directory of pair files.",
    )
    p.add_argument("data_dir", help="directory of gene-pair text files")
    p.add_argument("export_dir", help="output directory for embeddings")
    p.add_argument(
        "ending_pattern", nargs="?", default="txt",
        help="filename suffix of corpus files (default: txt)",
    )
    p.add_argument(
        "--backend", choices=BACKENDS, default="jax",
        help="jax = TPU path (default); numpy/hogwild/gensim = CPU oracles "
             "(hogwild = native C++ multithreaded)",
    )
    d = SGNSConfig()
    p.add_argument("--dim", type=int, default=d.dim)
    p.add_argument("--iters", type=int, default=d.num_iters)
    p.add_argument(
        "--objective", choices=("sgns", "cbow", "sg_hs", "cbow_hs"),
        default=d.objective,
    )
    p.add_argument("--min-count", type=int, default=d.min_count)
    p.add_argument("--negatives", type=int, default=d.negatives)
    p.add_argument("--lr", type=float, default=d.lr)
    p.add_argument("--min-lr", type=float, default=d.min_lr)
    p.add_argument("--batch-pairs", type=int, default=d.batch_pairs)
    p.add_argument("--seed", type=int, default=d.seed)
    p.add_argument(
        "--combiner", choices=("capped", "mean", "sum"), default=d.combiner
    )
    p.add_argument(
        "--negative-mode",
        choices=("stratified", "shared", "per_example"),
        default=d.negative_mode,
    )
    p.add_argument(
        "--strat-head", type=int, default=d.strat_head,
        help="stratified: exact-expectation noise head rows",
    )
    p.add_argument(
        "--strat-group", type=int, default=d.strat_group,
        help="stratified: examples per tail-block draw (128 = the "
             "maximum-quality point, 256 = the default throughput point; "
             "docs/PERF_NOTES.md geometry II)",
    )
    p.add_argument(
        "--strat-block", type=int, default=d.strat_block,
        help="stratified: rows per random tail block",
    )
    p.add_argument(
        "--positive-head", type=int, default=d.positive_head,
        help="dense-head positives: head rows moved via one-hot MXU "
             "matmuls (0 disables; single-host stratified runs only)",
    )
    p.add_argument(
        "--positive-mid", type=int, default=d.positive_mid,
        help="second dense positive slab: rows [positive_head, "
             "positive_head + positive_mid) also move via one-hot MXU "
             "matmuls (6-class batch layout; 0 disables)",
    )
    p.add_argument(
        "--table-dtype", choices=("float32", "bfloat16"),
        default=d.table_dtype,
        help="emb/ctx storage width; bfloat16 = measured +7%% at "
             "real-scale quality parity, NOT safe for tiny corpora "
             "(see config.py)",
    )
    p.add_argument(
        "--hs-dense-depth", type=int, default=d.hs_dense_depth,
        help="cbow_hs/sg_hs: Huffman-tree levels scored densely against "
             "the contiguous shallow-node prefix (0 = classic)",
    )
    p.add_argument(
        "--vocab-sharded", action="store_true",
        help="shard embedding-table rows over the mesh model axis "
             "(BASELINE config 5)",
    )
    p.add_argument(
        "--mesh-data", type=int, default=-1,
        help="mesh data-axis size (-1: all remaining devices)",
    )
    p.add_argument(
        "--mesh-model", type=int, default=1, help="mesh model-axis size"
    )
    p.add_argument(
        "--no-txt-output", action="store_true",
        help="skip matrix-txt / word2vec-format exports per iteration",
    )
    p.add_argument(
        "--async-checkpoint", action="store_true",
        help="write per-iteration checkpoints on the resilience/ "
             "background writer (disk I/O overlaps the next epoch; "
             "docs/RESILIENCE.md); jax sgns backend only",
    )
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    config = SGNSConfig(
        dim=args.dim,
        num_iters=args.iters,
        objective=args.objective,
        min_count=args.min_count,
        negatives=args.negatives,
        lr=args.lr,
        min_lr=args.min_lr,
        batch_pairs=args.batch_pairs,
        seed=args.seed,
        combiner=args.combiner,
        negative_mode=args.negative_mode,
        strat_head=args.strat_head,
        strat_group=args.strat_group,
        strat_block=args.strat_block,
        positive_head=args.positive_head,
        positive_mid=args.positive_mid,
        table_dtype=args.table_dtype,
        hs_dense_depth=args.hs_dense_depth,
        vocab_sharded=args.vocab_sharded,
        txt_output=not args.no_txt_output,
        async_checkpoint=args.async_checkpoint,
    )

    from gene2vec_tpu.data.pipeline import PairCorpus
    from gene2vec_tpu.io.pair_reader import load_corpus

    print(f"loading corpus from {args.data_dir} (*.{args.ending_pattern})")
    vocab, pairs = load_corpus(
        args.data_dir, args.ending_pattern, min_count=config.min_count
    )
    corpus = PairCorpus(vocab, pairs)
    print(f"{corpus.num_pairs:,} pairs, vocab {corpus.vocab_size:,}")

    wants_mesh = args.vocab_sharded or args.mesh_model > 1 or args.mesh_data > 0
    if args.backend == "jax" and wants_mesh:
        import jax

        from gene2vec_tpu.parallel.mesh import make_mesh
        from gene2vec_tpu.parallel.sharding import SGNSSharding
        from gene2vec_tpu.sgns.train import SGNSTrainer

        if config.objective != "sgns":
            raise SystemExit("--vocab-sharded supports the sgns objective")
        mesh = make_mesh(
            MeshConfig(data=args.mesh_data, model=args.mesh_model)
        )
        print(f"mesh {dict(zip(mesh.axis_names, mesh.devices.shape))} "
              f"over {len(jax.devices())} devices")
        # multi-process runtime: every host read the full corpus above;
        # feed this host's shard and keep the full corpus for the
        # dense-head global quotas (docs/DISTRIBUTED.md data feeding —
        # passing the full corpus as the shard would train every pair
        # process_count times per epoch)
        local, full = corpus, None
        if jax.process_count() > 1:
            local, full = corpus.process_shard(), corpus
            print(
                f"process {jax.process_index()}/{jax.process_count()}: "
                f"feeding {local.num_pairs:,} of {corpus.num_pairs:,} pairs"
            )
        trainer = SGNSTrainer(
            local, config,
            sharding=SGNSSharding(mesh, vocab_sharded=args.vocab_sharded),
            full_corpus=full,
        )
    else:
        from gene2vec_tpu.sgns.backends import make_backend_trainer

        trainer = make_backend_trainer(corpus, config, backend=args.backend)

    # SIGTERM/SIGINT → finish the iteration, commit its checkpoint, exit
    # EXIT_PREEMPTED so schedulers can tell "resume me" from failure
    # (docs/RESILIENCE.md)
    from gene2vec_tpu.resilience.preempt import EXIT_PREEMPTED, PreemptionHandler

    with PreemptionHandler() as handler:
        trainer.run(args.export_dir, preempt=handler)
    if handler.triggered:
        print(
            f"preempted (signal {handler.received}); checkpoints are "
            "committed — rerun the same command to resume",
            file=sys.stderr,
        )
        return EXIT_PREEMPTED
    return 0


if __name__ == "__main__":
    sys.exit(main())
