"""t-SNE sweep CLI — ``src/tsne_multi_core.py`` parity on TPU.

One exact t-SNE run snapshots the layout at every requested iteration count
(the reference spawned 6 processes, each redoing all earlier iterations).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from gene2vec_tpu.config import TSNEConfig


def build_parser() -> argparse.ArgumentParser:
    d = TSNEConfig()
    p = argparse.ArgumentParser(
        prog="tsne",
        description="Project an embedding to 2-D, writing labels + "
                    "coordinates per snapshot iteration.",
    )
    p.add_argument("emb_file")
    p.add_argument("out_dir")
    p.add_argument(
        "--iters", type=int, nargs="+",
        default=[100, 5000, 10000, 20000, 50000, 100000],
        help="snapshot iteration counts (reference sweep values)",
    )
    p.add_argument("--pca-dims", type=int, default=d.pca_dims)
    p.add_argument("--perplexity", type=float, default=d.perplexity)
    p.add_argument("--learning-rate", type=float, default=d.learning_rate)
    p.add_argument("--seed", type=int, default=d.seed)
    p.add_argument("--no-shuffle", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    config = TSNEConfig(
        pca_dims=args.pca_dims,
        perplexity=args.perplexity,
        learning_rate=args.learning_rate,
        n_iter=max(args.iters),
        seed=args.seed,
    )
    from gene2vec_tpu.viz.tsne import run_tsne_sweep

    run_tsne_sweep(
        args.emb_file,
        args.out_dir,
        iters=args.iters,
        config=config,
        shuffle_seed=None if args.no_shuffle else args.seed,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
