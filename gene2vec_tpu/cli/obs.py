"""Observability CLI: summarize observed run directories.

* ``python -m gene2vec_tpu.cli.obs report <run_dir>`` — render the
  per-phase/throughput/HBM/stall summary of any run directory that
  holds the standard artifacts (``manifest.json`` + ``events.jsonl``,
  written by every trainer's ``run()`` and by ``bench.py``);
* ``python -m gene2vec_tpu.cli.obs list <root>`` — find observed run
  directories under a root;
* ``python -m gene2vec_tpu.cli.obs trace <run_dir> <trace_id>`` —
  reassemble one distributed trace from every ``events.jsonl`` and
  flight-recorder dump under ``run_dir`` (pass a fleet export dir to
  cover the proxy's run AND every replica's) and render the
  cross-process tree: proxy hop → client attempts (retries/hedges) →
  replica request → batcher item → compute subtree;
* ``python -m gene2vec_tpu.cli.obs timeline <run_dir> [--out f]`` —
  export every ``timeline.jsonl`` phase record AND ``events.jsonl``
  span/hop record under ``run_dir`` as one Chrome-trace-event JSON,
  loadable in Perfetto (https://ui.perfetto.dev) or
  ``chrome://tracing`` — train step-phase swimlanes and serve request
  traces in one viewer;
* ``python -m gene2vec_tpu.cli.obs kernels <run_dir>`` — render the
  kernel cost-attribution records (``kernels.jsonl``, written by
  :mod:`gene2vec_tpu.obs.profiler` when a run enables
  ``kernel_profile``) as a roofline table: static XLA flops/bytes,
  best observed wall, achieved-vs-peak utilization and the binding
  resource per kernel (exit 1 when no records exist);
* ``python -m gene2vec_tpu.cli.obs ledger [root]`` — ingest every
  root bench artifact through the per-family adapters
  (gene2vec_tpu/obs/ledger.py, docs/BENCHMARKS.md) into the unified
  ledger; ``--out/--csv`` persist it, ``--check`` exits 1 when the
  trailing-window regression rules (budgets.json ``perf.regression``)
  fire;
* ``python -m gene2vec_tpu.cli.obs alerts <run_dir>`` — render the
  SLO alert transition timeline from every ``alerts.jsonl`` under a
  run dir (obs/alerts.py; exit 1 when no transitions were recorded);
* ``python -m gene2vec_tpu.cli.obs incident <bundle>`` — CRC-verify an
  incident bundle's ``incident.MANIFEST.json`` and render it (rule,
  firing snapshot, raw metric window, flight dumps, reassembled
  traces; obs/incident.py; exit 1 on a torn/empty bundle).

Schema and run-dir layout: docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="obs",
        description="Summarize observed run directories "
                    "(manifest.json + events.jsonl).",
    )
    sub = p.add_subparsers(dest="command", required=True)
    rep = sub.add_parser("report", help="summarize one run directory")
    rep.add_argument("run_dir", help="directory holding events.jsonl / "
                     "manifest.json (e.g. a trainer export dir)")
    rep.add_argument("--json", action="store_true",
                     help="emit the structured summary as JSON instead of "
                     "the human-readable report")
    ls = sub.add_parser("list", help="find observed run dirs under a root")
    ls.add_argument("root", nargs="?", default=".")
    tr = sub.add_parser(
        "trace",
        help="reassemble one distributed trace across every "
             "events.jsonl / flight dump under a directory",
    )
    tr.add_argument("run_dir", help="directory tree to scan (a fleet "
                    "export dir covers the proxy and all replicas)")
    tr.add_argument("trace_id", help="32-hex trace id (from loadgen "
                    "--trace-sample, a ClientResponse, or a flight dump)")
    tr.add_argument("--json", action="store_true",
                    help="emit the reassembled tree as JSON")
    tml = sub.add_parser(
        "timeline",
        help="export timeline.jsonl + events.jsonl under a run dir as "
             "Perfetto-loadable Chrome trace JSON",
    )
    tml.add_argument("run_dir", help="run directory tree to scan")
    tml.add_argument("--out", default=None,
                     help="output path (default <run_dir>/trace.json; "
                     "'-' writes the document to stdout)")
    al = sub.add_parser(
        "alerts",
        help="render the SLO alert transition timeline under a run dir",
    )
    al.add_argument("run_dir", help="directory tree holding alerts.jsonl "
                    "(a fleet run dir, or an export dir covering several)")
    al.add_argument("--json", action="store_true",
                    help="emit the transition records as JSON")
    inc = sub.add_parser(
        "incident",
        help="verify + render one incident bundle "
             "(<run_dir>/incidents/<ts>_<rule>/)",
    )
    inc.add_argument("bundle", help="incident bundle directory")
    inc.add_argument("--json", action="store_true",
                     help="emit the bundle facts as JSON")
    ker = sub.add_parser(
        "kernels",
        help="render the kernel cost-attribution records "
             "(kernels.jsonl) of a run dir as a roofline table",
    )
    ker.add_argument("run_dir", help="run directory holding kernels.jsonl "
                     "(a trainer export dir, or one level above)")
    ker.add_argument("--json", action="store_true",
                     help="emit the kernel records as JSON")
    led = sub.add_parser(
        "ledger",
        help="unified bench ledger over the root bench artifacts",
    )
    led.add_argument("root", nargs="?", default=".",
                     help="directory holding the BENCH_*/MULTICHIP_*/... "
                     "artifacts (default: cwd)")
    led.add_argument("--out", default=None, metavar="JSONL",
                     help="write the ledger records as JSON lines")
    led.add_argument("--csv", default=None, metavar="CSV",
                     help="write the ledger as CSV")
    led.add_argument("--json", action="store_true",
                     help="emit records + regression evaluations as one "
                     "JSON document on stdout")
    led.add_argument("--check", action="store_true",
                     help="run the budgets.json perf.regression rules and "
                     "exit 1 on any detected regression")
    return p


def _ledger(args) -> int:
    from gene2vec_tpu.obs import ledger

    if not os.path.isdir(args.root):
        print(f"obs ledger: {args.root} is not a directory", file=sys.stderr)
        return 2
    records = ledger.ingest_root(args.root)
    evaluations = []
    if args.check or args.json:
        from gene2vec_tpu.analysis.passes_hlo import load_budgets

        rules = load_budgets().get("perf", {}).get("regression", {})
        evaluations = ledger.detect_regressions(records, rules)
    if args.out:
        ledger.write_jsonl(records, args.out)
        print(f"wrote {args.out} ({len(records)} records)", file=sys.stderr)
    if args.csv:
        ledger.write_csv(records, args.csv)
        print(f"wrote {args.csv}", file=sys.stderr)

    regressed = [e for e in evaluations if e.get("regressed")]
    if args.json:
        print(json.dumps(
            {"schema": ledger.SCHEMA, "records": records,
             "regressions": evaluations},
            indent=1, default=str,
        ))
    else:
        fmt = "{:<14} {:<28} {:>5} {:<7} {}"
        print(fmt.format("family", "source", "round", "legacy", "headline"))
        for rec in records:
            headline = rec.get("headline_metric")
            value = (rec.get("metrics") or {}).get(headline)
            shown = (
                f"{headline}={value:g}" if value is not None
                else rec.get("error") or "(no headline)"
            )
            print(fmt.format(
                rec["family"], rec["source"],
                rec["round"] if rec["round"] is not None else "-",
                "legacy" if rec.get("legacy_unstamped") else "",
                shown,
            ))
        for ev in evaluations:
            if ev.get("skipped"):
                continue
            state = "REGRESSED" if ev["regressed"] else "ok"
            print(
                f"regression[{ev['metric']}]: {state} newest "
                f"{ev.get('newest_value')} vs band median "
                f"{ev.get('band_median')} "
                f"(frac {ev.get('regression_frac')}, max "
                f"{ev['max_regression_frac']})"
            )
    if args.check and regressed:
        for ev in regressed:
            print(
                f"obs ledger: REGRESSION {ev['metric']}: newest "
                f"{ev.get('newest_value')} vs band median "
                f"{ev.get('band_median')} exceeds max_regression_frac "
                f"{ev['max_regression_frac']:g}",
                file=sys.stderr,
            )
        return 1
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    from gene2vec_tpu.obs import report

    if args.command == "list":
        for d in report.find_runs(args.root):
            print(d)
        return 0

    if args.command == "trace":
        from gene2vec_tpu.obs import flight

        if not os.path.isdir(args.run_dir):
            print(f"obs trace: {args.run_dir} is not a directory",
                  file=sys.stderr)
            return 2
        doc = flight.collect_trace(args.run_dir, args.trace_id)
        if args.json:
            print(json.dumps(doc, indent=1, default=str))
        else:
            print(flight.format_trace(doc))
        # exit 1 when the trace is entirely absent, so drills/scripts
        # can assert "reassembly found something" without parsing
        return 0 if (doc["roots"] or doc["flight"]) else 1

    if args.command == "timeline":
        from gene2vec_tpu.obs import timeline as timeline_mod

        if not os.path.isdir(args.run_dir):
            print(f"obs timeline: {args.run_dir} is not a directory",
                  file=sys.stderr)
            return 2
        doc = timeline_mod.collect_run(args.run_dir)
        n = len(doc["traceEvents"])
        if not n:
            print(
                f"obs timeline: no timeline.jsonl/events.jsonl records "
                f"under {args.run_dir}",
                file=sys.stderr,
            )
            return 1
        if args.out == "-":
            json.dump(doc, sys.stdout)
            print()
            return 0
        out = args.out or os.path.join(args.run_dir, "trace.json")
        with open(out, "w", encoding="utf-8") as f:
            json.dump(doc, f)
            f.write("\n")
        # one machine-readable product line: where the trace went and
        # which phase tracks it contains
        print(json.dumps({
            "out": os.path.abspath(out),
            "trace_events": n,
            "phase_tracks": doc["otherData"]["phase_tracks"],
        }))
        return 0

    if args.command == "alerts":
        from gene2vec_tpu.obs import alerts as alerts_mod

        if not os.path.isdir(args.run_dir):
            print(f"obs alerts: {args.run_dir} is not a directory",
                  file=sys.stderr)
            return 2
        records = alerts_mod.collect_transitions(args.run_dir)
        if args.json:
            print(json.dumps(records, indent=1, default=str))
        else:
            print(alerts_mod.format_timeline(records))
        # exit 1 when no transitions exist — drills/scripts assert
        # "alerting saw something" without parsing (the trace contract)
        return 0 if records else 1

    if args.command == "incident":
        from gene2vec_tpu.obs import incident as incident_mod

        if not os.path.isdir(args.bundle):
            print(f"obs incident: {args.bundle} is not a directory",
                  file=sys.stderr)
            return 2
        verify = incident_mod.verify_bundle(args.bundle)
        if args.json:
            print(json.dumps({
                "bundle": os.path.abspath(args.bundle),
                "verified": bool(verify),
                "reason": verify.reason,
                "manifest": verify.manifest,
            }, indent=1, default=str))
        else:
            print(incident_mod.format_bundle(args.bundle, verify))
        if not verify:
            print(
                f"obs incident: bundle failed verification "
                f"({verify.reason})",
                file=sys.stderr,
            )
            return 1
        return 0

    if args.command == "kernels":
        from gene2vec_tpu.obs import profiler as profiler_mod

        if not os.path.isdir(args.run_dir):
            print(f"obs kernels: {args.run_dir} is not a directory",
                  file=sys.stderr)
            return 2
        records = profiler_mod.read_kernels(args.run_dir)
        if not records:
            # exit 1 when no attribution exists — scripts assert "the
            # profiler recorded something" without parsing
            print(
                f"obs kernels: no kernels.jsonl records under "
                f"{args.run_dir} (enable kernel_profile / "
                "--kernel-profile on the producing run)",
                file=sys.stderr,
            )
            return 1
        if args.json:
            print(json.dumps(records, indent=1, default=str))
        else:
            print(profiler_mod.format_kernels(records))
        return 0

    if args.command == "ledger":
        return _ledger(args)

    run_dir = args.run_dir
    if not os.path.isdir(run_dir):
        print(f"obs report: {run_dir} is not a directory", file=sys.stderr)
        return 2
    has_artifacts = any(
        os.path.exists(os.path.join(run_dir, f))
        for f in ("events.jsonl", "manifest.json")
    )
    if not has_artifacts:
        nested = report.find_runs(run_dir)
        if len(nested) == 1:
            run_dir = nested[0]
        else:
            print(
                f"obs report: {run_dir} holds no events.jsonl/manifest.json"
                + (f"; candidates:\n  " + "\n  ".join(nested) if nested else ""),
                file=sys.stderr,
            )
            return 2
    if args.json:
        print(json.dumps(report.summarize(run_dir), indent=1, default=str))
    else:
        print(report.format_report(run_dir))
    return 0


if __name__ == "__main__":
    sys.exit(main())
