"""Observability CLI: summarize observed run directories.

* ``python -m gene2vec_tpu.cli.obs report <run_dir>`` — render the
  per-phase/throughput/HBM/stall summary of any run directory that
  holds the standard artifacts (``manifest.json`` + ``events.jsonl``,
  written by every trainer's ``run()`` and by ``bench.py``);
* ``python -m gene2vec_tpu.cli.obs list <root>`` — find observed run
  directories under a root;
* ``python -m gene2vec_tpu.cli.obs trace <run_dir> <trace_id>`` —
  reassemble one distributed trace from every ``events.jsonl`` and
  flight-recorder dump under ``run_dir`` (pass a fleet export dir to
  cover the proxy's run AND every replica's) and render the
  cross-process tree: proxy hop → client attempts (retries/hedges) →
  replica request → batcher item → compute subtree.

Schema and run-dir layout: docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="obs",
        description="Summarize observed run directories "
                    "(manifest.json + events.jsonl).",
    )
    sub = p.add_subparsers(dest="command", required=True)
    rep = sub.add_parser("report", help="summarize one run directory")
    rep.add_argument("run_dir", help="directory holding events.jsonl / "
                     "manifest.json (e.g. a trainer export dir)")
    rep.add_argument("--json", action="store_true",
                     help="emit the structured summary as JSON instead of "
                     "the human-readable report")
    ls = sub.add_parser("list", help="find observed run dirs under a root")
    ls.add_argument("root", nargs="?", default=".")
    tr = sub.add_parser(
        "trace",
        help="reassemble one distributed trace across every "
             "events.jsonl / flight dump under a directory",
    )
    tr.add_argument("run_dir", help="directory tree to scan (a fleet "
                    "export dir covers the proxy and all replicas)")
    tr.add_argument("trace_id", help="32-hex trace id (from loadgen "
                    "--trace-sample, a ClientResponse, or a flight dump)")
    tr.add_argument("--json", action="store_true",
                    help="emit the reassembled tree as JSON")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    from gene2vec_tpu.obs import report

    if args.command == "list":
        for d in report.find_runs(args.root):
            print(d)
        return 0

    if args.command == "trace":
        from gene2vec_tpu.obs import flight

        if not os.path.isdir(args.run_dir):
            print(f"obs trace: {args.run_dir} is not a directory",
                  file=sys.stderr)
            return 2
        doc = flight.collect_trace(args.run_dir, args.trace_id)
        if args.json:
            print(json.dumps(doc, indent=1, default=str))
        else:
            print(flight.format_trace(doc))
        # exit 1 when the trace is entirely absent, so drills/scripts
        # can assert "reassembly found something" without parsing
        return 0 if (doc["roots"] or doc["flight"]) else 1

    run_dir = args.run_dir
    if not os.path.isdir(run_dir):
        print(f"obs report: {run_dir} is not a directory", file=sys.stderr)
        return 2
    has_artifacts = any(
        os.path.exists(os.path.join(run_dir, f))
        for f in ("events.jsonl", "manifest.json")
    )
    if not has_artifacts:
        nested = report.find_runs(run_dir)
        if len(nested) == 1:
            run_dir = nested[0]
        else:
            print(
                f"obs report: {run_dir} holds no events.jsonl/manifest.json"
                + (f"; candidates:\n  " + "\n  ".join(nested) if nested else ""),
                file=sys.stderr,
            )
            return 2
    if args.json:
        print(json.dumps(report.summarize(run_dir), indent=1, default=str))
    else:
        print(report.format_report(run_dir))
    return 0


if __name__ == "__main__":
    sys.exit(main())
