"""Continuous-learning loop CLI: one ingest→train→shadow→promote cycle.

::

    python -m gene2vec_tpu.cli.loop \\
        --loop-root loop/ --serving-export exports/ \\
        --batch new_study_pairs.txt --batch-id geo_2026_08 \\
        --fleet-url http://127.0.0.1:8100

Drives the journaled state machine (``loop/promote.py``) against a
REAL fleet started with ``cli.fleet --enable-shadow``:

1. **INGESTING** — append the batch to the loop corpus under the
   durable CRC-stamped cursor (``loop/ingest.py``; idempotent).
2. **TRAINING** — warm-start continued SGNS from the serving export's
   latest verified checkpoint into this cycle's candidate export
   (``loop/trainer.py``; SIGKILL-resume bit-exact).
3. **QUALITY_GATE** — holdout AUC band + intrinsic ratio; a failing
   candidate is DEMOTED (quarantined) without seeing traffic.
4. **SHADOWING** — spawn a candidate ``cli.serve`` replica, start the
   fleet's shadow canary, wait for enough scored live-traffic
   duplicates, and judge answer churn + p99 delta against the budgets.
5. **PROMOTING** — publish the candidate iteration into the serving
   export (manifest-committed LAST) and wait for the fleet to adopt it
   through its existing swap machinery (per-replica atomic refresh, or
   the shard-atomic stage/flip coordinator).
6. **SERVING** — terminal; the cycle report goes to stdout as exactly
   ONE JSON line (the machine contract, like every serve-family CLI).

A SIGKILL anywhere resumes: re-run the same command and the journal
(``<loop_root>/loop_runs/<batch-id>/loop.jsonl``) skips committed
states.  ``--crash-at STATE`` is the chaos drill's fault hook.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
import urllib.request
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="loop",
        description="Continuous-learning cycle: incremental ingest -> "
        "warm-start SGNS -> quality gate -> shadow canary -> gated "
        "promotion (docs/CONTINUOUS.md).",
    )
    p.add_argument("--loop-root", required=True,
                   help="loop state root (ingest store, candidate "
                        "exports, journals, quarantine)")
    p.add_argument("--serving-export", required=True,
                   help="the export dir the fleet serves — warm-start "
                        "source and promotion target")
    p.add_argument("--batch", required=True,
                   help="new study batch: a pair-lines file ('GENE_A "
                        "GENE_B' per line), or a reference-format "
                        "query dir to run through corpus/builder.py")
    p.add_argument("--batch-id", default=None,
                   help="stable batch id (default: the --batch "
                        "basename); ingest and the journal are "
                        "idempotent per id — rerunning a killed cycle "
                        "resumes it")
    p.add_argument("--seed-corpus", default=None,
                   help="pair-lines file ingested as batch id 'seed' "
                        "when the loop root is brand new (the corpus "
                        "the serving model was trained on)")
    p.add_argument("--fleet-url", required=True,
                   help="front door of a cli.fleet started with "
                        "--enable-shadow")
    p.add_argument("--dim", type=int, default=None,
                   help="table width (default: the serving export's "
                        "newest checkpoint dim)")
    p.add_argument("--train-iters", type=int, default=2,
                   help="continued iterations per cycle")
    p.add_argument("--batch-pairs", type=int, default=4096)
    p.add_argument("--sgns-seed", type=int, default=1,
                   help="SGNSConfig.seed — MUST match the serving "
                        "model's training seed for the RNG cursor to "
                        "line up")
    p.add_argument("--holdout-frac", type=float, default=0.2,
                   help="stable-hash held-out fraction feeding the "
                        "quality gate (never trained on)")
    p.add_argument("--min-auc", type=float, default=None,
                   help="quality-gate AUC floor (default: the "
                        "canonical eval/holdout.py band)")
    p.add_argument("--max-auc", type=float, default=None,
                   help="quality-gate AUC ceiling (degeneration "
                        "guard; default: the canonical band)")
    p.add_argument("--shadow-sample", type=float, default=0.5,
                   help="fraction of live /v1/similar traffic "
                        "duplicated to the candidate")
    p.add_argument("--shadow-min-requests", type=int, default=50,
                   help="scored shadow pairs required before a "
                        "verdict; fewer within --shadow-max-wait "
                        "demotes (insufficient evidence)")
    p.add_argument("--shadow-max-wait", type=float, default=120.0,
                   help="max seconds to wait for shadow evidence")
    p.add_argument("--max-churn", type=float, default=0.25,
                   help="promotion ceiling on mean top-k answer churn "
                        "(Jaccard) between live and candidate")
    p.add_argument("--max-p99-delta-ms", type=float, default=250.0,
                   help="promotion ceiling on (shadow p99 - live p99)")
    p.add_argument("--promote-timeout", type=float, default=120.0,
                   help="max seconds to wait for the fleet to adopt "
                        "the published iteration")
    p.add_argument("--crash-at", default=None, metavar="STATE",
                   help="chaos hook: SIGKILL self right after entering "
                        "STATE (or 'TRAINING_MID' = after the first "
                        "continued iteration completes); the drill "
                        "injects crashes into every loop state this "
                        "way ($GENE2VEC_TPU_LOOP_CRASH works too)")
    return p


def _log(msg: str) -> None:
    print(f"[loop] {msg}", file=sys.stderr, flush=True)


def _http_json(url: str, body: Optional[dict] = None,
               timeout: float = 10.0) -> dict:
    data = None
    headers = {}
    if body is not None:
        data = json.dumps(body).encode("utf-8")
        headers["Content-Type"] = "application/json"
    req = urllib.request.Request(url, data=data, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _read_batch_lines(path: str) -> List[str]:
    if os.path.isdir(path):
        from gene2vec_tpu.loop.ingest import batch_from_study_dir

        return batch_from_study_dir(path, log=_log)
    with open(path, "r", encoding="utf-8") as f:
        return [ln for ln in f.read().splitlines() if ln.strip()]


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    crash_at = args.crash_at or os.environ.get("GENE2VEC_TPU_LOOP_CRASH")

    import dataclasses

    from gene2vec_tpu.config import SGNSConfig
    from gene2vec_tpu.io import checkpoint as ckpt
    from gene2vec_tpu.io.vocab import Vocab
    from gene2vec_tpu.loop import ingest as ingest_mod
    from gene2vec_tpu.loop import trainer as trainer_mod
    from gene2vec_tpu.loop.promote import (
        CycleDriver,
        LoopJournal,
        LoopState,
        journal_path,
        quarantine_candidate,
    )
    from gene2vec_tpu.resilience.preempt import PreemptionHandler
    from gene2vec_tpu.serve.fleet import read_contract_line

    batch_id = args.batch_id or os.path.basename(args.batch)
    loop_root = args.loop_root
    serving = args.serving_export
    candidate_dir = os.path.join(loop_root, "candidates", batch_id)

    newest = None
    for d, it, path in ckpt.iter_checkpoints_newest_first(
        serving, verified_only=True, dim=args.dim
    ):
        newest = (d, it, path)
        break
    if newest is None:
        print(
            f"error: no verified checkpoint in {serving!r} to "
            "warm-start from",
            file=sys.stderr,
        )
        return 2
    dim, serving_iter, newest_path = newest
    config = SGNSConfig(
        dim=dim, batch_pairs=args.batch_pairs, seed=args.sgns_seed,
        txt_output=False,
    )

    # loop-root bootstrap (idempotent): the serving vocab anchors every
    # future row id; an optional seed batch carries the original corpus
    if ingest_mod.init_ingest(
        loop_root, Vocab.load(ckpt.vocab_path_for(newest_path))
    ):
        _log(f"initialized ingest store under {loop_root}")
    if args.seed_corpus:
        facts = ingest_mod.ingest_batch(
            loop_root, "seed", _read_batch_lines(args.seed_corpus),
            replaces_base_counts=True,
        )
        if not facts["skipped"]:
            _log(f"seed corpus ingested: {facts['appended_pairs']} pairs")

    journal = LoopJournal(journal_path(loop_root, batch_id), batch_id)
    preempt = PreemptionHandler().install()

    # -- the real steps ----------------------------------------------------

    def step_ingest(context) -> dict:
        return ingest_mod.ingest_batch(
            loop_root, batch_id, _read_batch_lines(args.batch)
        )

    def step_train(context) -> dict:
        corpus, held = ingest_mod.load_loop_corpus(
            loop_root, args.holdout_frac
        )
        log = _log
        if crash_at == "TRAINING_MID":
            # mid-state chaos: a genuine SIGKILL after the FIRST
            # continued iteration finishes (its checkpoint may or may
            # not have committed — exactly the window resume must cover)
            import signal as _signal

            seen = {"n": 0}

            def log(msg: str, _inner=_log) -> None:  # noqa: ANN001
                _inner(msg)
                if " done: " in msg:
                    seen["n"] += 1
                    if seen["n"] == 1:
                        _inner("CHAOS: SIGKILL self mid-TRAINING")
                        os.kill(os.getpid(), _signal.SIGKILL)

        params, base_it, final_it = trainer_mod.train_candidate(
            serving, candidate_dir, corpus, config, args.train_iters,
            preempt=preempt, log=log,
        )
        if preempt.triggered:
            raise SystemExit(113)  # drained; resume finishes the cycle
        return {
            "candidate_dir": candidate_dir,
            "dim": dim,
            "base_iteration": base_it,
            "final_iteration": final_it,
            "vocab_size": corpus.vocab_size,
            "held_pairs": len(held),
        }

    def step_quality(context) -> dict:
        final_it = context[LoopState.TRAINING]["final_iteration"]
        params, vocab, _meta = ckpt.load_iteration(
            candidate_dir, dim, final_it, table_dtype=None
        )
        import numpy as np

        _corpus, held = ingest_mod.load_loop_corpus(
            loop_root, args.holdout_frac
        )
        report = trainer_mod.quality_report(
            vocab, np.asarray(params.emb), held,
            min_auc=args.min_auc, max_auc=args.max_auc,
        )
        _log(f"quality gate: {report}")
        return report

    def _spawn_candidate(final_it: int) -> dict:
        proc = subprocess.Popen(
            [sys.executable, "-m", "gene2vec_tpu.cli.serve",
             "--export-dir", candidate_dir, "--port", "0",
             "--poll-interval", "3600"],
            stdout=subprocess.PIPE, text=True,
            env={**os.environ, "PYTHONUNBUFFERED": "1"},
        )
        info = read_contract_line(proc, 180.0)
        if info.get("iteration") != final_it:
            proc.kill()
            raise RuntimeError(
                f"candidate replica loaded iteration "
                f"{info.get('iteration')}, expected {final_it}"
            )
        # warm the candidate's jit buckets BEFORE shadowing starts: the
        # canary's p99 delta must measure the MODEL, not first-query
        # compile time (a real rollout warms before it canaries)
        try:
            g = _http_json(
                info["url"] + "/v1/genes?limit=1", timeout=30.0
            )["genes"][0]
            for k in (5, 10, 32):
                _http_json(
                    info["url"] + "/v1/similar",
                    {"genes": [g], "k": k}, timeout=60.0,
                )
        except Exception as e:
            _log(f"candidate warmup failed (continuing): {e!r}")
        return {"url": info["url"], "pid": proc.pid}

    def step_shadow(context) -> dict:
        final_it = context[LoopState.TRAINING]["final_iteration"]
        # reap any candidate a killed earlier attempt left behind (its
        # pid was journaled the moment it spawned) before starting ours
        for rec in journal.replay():
            pid = (rec.get("facts", {}).get("candidate") or {}).get("pid")
            if pid:
                try:
                    os.kill(int(pid), 15)
                except (OSError, ValueError):
                    pass
        cand = _spawn_candidate(final_it)
        # journal the spawn immediately — a SIGKILL between here and
        # this state's "done" must not orphan a serving process
        journal.enter(LoopState.SHADOWING, candidate=cand)
        _log(f"candidate replica up at {cand['url']} (pid {cand['pid']})")
        t0 = time.monotonic()
        _http_json(
            args.fleet_url + "/v1/shadow/start",
            {"url": cand["url"], "sample": args.shadow_sample},
        )
        deadline = time.monotonic() + args.shadow_max_wait
        report: dict = {}
        while time.monotonic() < deadline:
            doc = _http_json(args.fleet_url + "/v1/shadow/report")
            report = doc.get("report", {})
            if report.get("scored", 0) >= args.shadow_min_requests:
                break
            time.sleep(0.5)
        _http_json(args.fleet_url + "/v1/shadow/stop", {})
        facts = {
            "candidate": cand,
            "final_iteration": final_it,
            "shadow_sample": args.shadow_sample,
            "shadow_wait_s": round(time.monotonic() - t0, 3),
            "report": report,
        }
        churn = report.get("answer_churn")
        delta = report.get("p99_delta_ms")
        scored = report.get("scored", 0)
        if scored < args.shadow_min_requests:
            facts.update(verdict="demote", reason=(
                f"insufficient shadow evidence: {scored} scored < "
                f"{args.shadow_min_requests} within "
                f"{args.shadow_max_wait}s"
            ))
        elif churn is None or churn > args.max_churn:
            facts.update(verdict="demote", reason=(
                f"answer churn {churn} over the {args.max_churn} budget"
            ))
        elif delta is not None and delta > args.max_p99_delta_ms:
            facts.update(verdict="demote", reason=(
                f"shadow p99 delta {delta}ms over the "
                f"{args.max_p99_delta_ms}ms budget"
            ))
        else:
            facts["verdict"] = "promote"
        _log(f"shadow verdict: {facts['verdict']}")
        return facts

    def _kill_candidate(context) -> None:
        cand = (context.get(LoopState.SHADOWING) or {}).get("candidate")
        if cand and cand.get("pid"):
            try:
                os.kill(int(cand["pid"]), 15)
            except (OSError, ValueError):
                pass

    def step_promote(context) -> dict:
        final_it = context[LoopState.TRAINING]["final_iteration"]
        t0 = time.monotonic()
        ckpt.publish_iteration(candidate_dir, serving, dim, final_it)
        _log(f"published iteration {final_it} into {serving}")
        deadline = time.monotonic() + args.promote_timeout
        adopted = False
        while time.monotonic() < deadline:
            try:
                health = _http_json(args.fleet_url + "/healthz")
            except Exception:
                time.sleep(0.5)
                continue
            if "shards" in health:
                adopted = health.get("epoch") == final_it and all(
                    s.get("epoch") == final_it
                    for s in health.get("shards", [])
                )
            else:
                urls = [
                    r.get("url") for r in health.get("replicas", [])
                    if r.get("state") == "up" and r.get("url")
                ]
                up_iters = []
                for u in urls:
                    try:
                        h = _http_json(u + "/healthz", timeout=5.0)
                        up_iters.append(
                            (h.get("model") or {}).get("iteration")
                        )
                    except Exception:
                        up_iters.append(None)
                adopted = bool(up_iters) and all(
                    it == final_it for it in up_iters
                )
            if adopted:
                break
            time.sleep(0.5)
        if not adopted:
            raise TimeoutError(
                f"fleet did not adopt iteration {final_it} within "
                f"{args.promote_timeout}s — journal holds at PROMOTING "
                "(re-run to retry)"
            )
        return {
            "promoted_iteration": final_it,
            "adoption_s": round(time.monotonic() - t0, 3),
        }

    def step_serving(context) -> dict:
        _kill_candidate(context)
        return {
            "promoted_iteration":
                context[LoopState.PROMOTING]["promoted_iteration"],
        }

    def step_demote(context) -> dict:
        _kill_candidate(context)
        q = quarantine_candidate(loop_root, candidate_dir, batch_id)
        return {"quarantined": q}

    driver = CycleDriver(
        journal,
        steps={
            LoopState.INGESTING: step_ingest,
            LoopState.TRAINING: step_train,
            LoopState.QUALITY_GATE: step_quality,
            LoopState.SHADOWING: step_shadow,
            LoopState.PROMOTING: step_promote,
            LoopState.SERVING: step_serving,
        },
        demote_step=step_demote,
        crash_at=crash_at,
        log=_log,
    )
    result = driver.run()
    walls = journal.state_walls()
    contract = {
        "batch_id": batch_id,
        "state": result["state"],
        "dim": dim,
        "serving_iteration_before": serving_iter,
        "journal": journal.path,
        "facts": result["context"],
        "state_walls": walls,
    }
    print(json.dumps(contract, default=str), flush=True)
    return 0 if result["state"] == LoopState.SERVING else 3


if __name__ == "__main__":
    sys.exit(main())
