"""graftcheck CLI: static analysis + sanitizer gates for the hot paths.

Tiers (docs/STATIC_ANALYSIS.md):

* default — the fast AST lint passes over ``gene2vec_tpu/`` (+
  ``experiments/`` for stdout discipline), the concurrency tier
  (threadflow role inference: lock-discipline, loop-thread-blocking,
  blocking-while-locked, lock-order), the dead-budget lint
  (``budget-lint``), and the round-summary claim scan; jax never
  imports;
* ``--hlo hot`` — compile small SGNS / CBOW-HS / GGIPNN instances on the
  virtual 8-device CPU backend and check host callbacks, dtype
  discipline, jit cache stability;
* ``--hlo budgets`` — compile the budgeted mesh configs at full geometry
  and enforce the per-pair collective-bytes ceilings in
  ``gene2vec_tpu/analysis/budgets.json``;
* ``--sanitizers asan,ubsan[,tsan]`` — build the instrumented native
  libraries and run the pairio + Hogwild parity workload under each.

Exit status: 0 clean, 1 when any gating (error/warning) finding exists,
2 on internal failure.  ``--json`` emits the findings document
(schema ``gene2vec-tpu/findings/v1``) on stdout.

Examples::

    python -m gene2vec_tpu.cli.analyze
    python -m gene2vec_tpu.cli.analyze --json --select bare-print
    python -m gene2vec_tpu.cli.analyze --hlo all --sanitizers asan,ubsan
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List


def _pin_cpu_backend(devices: int = 8) -> None:
    """Force the virtual multi-device CPU backend before jax initializes
    (the scripts/hlo_comm_audit.py pattern: the session env may pin a
    real accelerator; analysis always runs on CPU).  In-process env
    mutation is required here — jax reads these at first import — which
    is why only the ``--hlo`` tiers call this; the sanitizer tier pins
    its *children* inside sanitize.run_parity instead."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={devices}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", devices)
    except AttributeError:
        pass  # pre-0.5 jax: the XLA flag above is read at backend init


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m gene2vec_tpu.cli.analyze",
        description="graftcheck: JAX-aware static analysis for gene2vec-tpu",
    )
    ap.add_argument("files", nargs="*", help=(
        "explicit .py files to lint (default: gene2vec_tpu/ and "
        "experiments/ per-pass roots)"
    ))
    ap.add_argument("--json", action="store_true",
                    help="emit the findings JSON document on stdout")
    ap.add_argument("--select", default=None,
                    help="comma-separated pass ids to run (default all)")
    ap.add_argument("--skip", default=None,
                    help="comma-separated pass ids to skip")
    ap.add_argument("--list-passes", action="store_true",
                    help="list AST pass ids and exit")
    ap.add_argument("--no-summaries", action="store_true",
                    help="skip the round-summary claim scan")
    ap.add_argument("--collect", action="store_true", help=(
        "run `pytest --collect-only` to enforce summary claims against "
        "the live test count (slow: imports the whole suite)"
    ))
    ap.add_argument("--hlo", choices=("hot", "budgets", "all"), default=None,
                    help="add tier-2 jaxpr/HLO invariant checks")
    ap.add_argument("--sanitizers", default=None, metavar="KINDS",
                    help="comma-separated sanitizer parity runs "
                         "(asan,ubsan,tsan)")
    args = ap.parse_args(argv)
    try:
        return _run(args)
    except ValueError as e:  # bad pass/config selection
        print(f"error: {e}", file=sys.stderr)
        return 2
    except Exception:  # the documented "2 on internal failure" contract
        import traceback

        traceback.print_exc()
        print("error: internal analyzer failure (traceback above)",
              file=sys.stderr)
        return 2


def _run(args) -> int:
    from gene2vec_tpu.analysis import (
        REPO_ROOT,
        dumps,
        gating,
        pass_ids,
        run_ast_passes,
    )

    from gene2vec_tpu.analysis.budget_lint import PASS_ID as BUDGET_LINT
    from gene2vec_tpu.analysis.passes_concurrency import (
        CONCURRENCY_PASS_IDS,
    )

    if args.list_passes:
        for pid in list(pass_ids()) + list(CONCURRENCY_PASS_IDS) + [
            BUDGET_LINT
        ]:
            print(pid)
        return 0

    select = args.select.split(",") if args.select else None
    skip = args.skip.split(",") if args.skip else None

    # the concurrency tier and budget lint are project-level passes with
    # their own ids: split them out so `--select lock-discipline` runs
    # just that pass and the AST runner never sees a foreign id
    project_ids = set(CONCURRENCY_PASS_IDS) | {BUDGET_LINT}
    conc_select = list(CONCURRENCY_PASS_IDS)
    run_lint = True
    if select is not None:
        conc_select = [p for p in select if p in CONCURRENCY_PASS_IDS]
        run_lint = BUDGET_LINT in select
        select = [p for p in select if p not in project_ids]
    if skip is not None:
        conc_select = [p for p in conc_select if p not in skip]
        run_lint = run_lint and BUDGET_LINT not in skip
        skip = [p for p in skip if p not in project_ids] or None

    # validate sanitizer kinds up front — a typo must fail in
    # milliseconds, not after minutes of HLO compilation
    kinds: List[str] = []
    if args.sanitizers:
        from gene2vec_tpu.analysis.sanitize import KINDS

        kinds = [k for k in args.sanitizers.split(",") if k]
        unknown = [k for k in kinds if k not in KINDS]
        if unknown:
            print(f"error: unknown sanitizer(s) {unknown}", file=sys.stderr)
            return 2

    findings = []
    if select is None or select:
        findings.extend(run_ast_passes(
            select=select, skip=skip, files=args.files or None,
        ))

    # concurrency tier: default, or whatever --select asked for (it
    # honors explicit files the way the AST passes do)
    if conc_select and (args.select or not args.files):
        from gene2vec_tpu.analysis.passes_concurrency import (
            concurrency_findings,
        )

        findings.extend(concurrency_findings(
            files=args.files or None,
            select=conc_select,
        ))
    if run_lint and not args.files:
        from gene2vec_tpu.analysis.budget_lint import budget_lint_findings

        findings.extend(budget_lint_findings())

    if not args.no_summaries and not args.files and select is None:
        from gene2vec_tpu.analysis.summaries import (
            check_summaries,
            collect_count_via_pytest,
        )

        count = collect_count_via_pytest(REPO_ROOT) if args.collect else None
        findings.extend(
            check_summaries(os.path.join(REPO_ROOT, "docs"), count)
        )
        # the fleet availability gate is two JSON reads — it rides the
        # default tier so a regressed BENCH_FLEET record fails analyze
        # without anyone remembering to pass a flag
        from gene2vec_tpu.analysis.passes_fleet import fleet_budget_findings

        findings.extend(fleet_budget_findings())
        # same shape for the tracing-overhead budget (BENCH_OBS vs the
        # budgets.json "obs" section)
        from gene2vec_tpu.analysis.passes_obs import obs_budget_findings

        findings.extend(obs_budget_findings())
        # ... and the perf plane: timeline-overhead budget (BENCH_PERF
        # vs "perf") + the unified-ledger trajectory regression rules
        from gene2vec_tpu.analysis.passes_perf import perf_findings

        findings.extend(perf_findings())
        # ... and the kernel-attribution gate (BENCH_KERNELS roofline
        # records: required kernels/fields + profiling-overhead ceiling
        # vs budgets.json "kernels.profile", recipe-pinned)
        from gene2vec_tpu.analysis.passes_kernels import kernels_findings

        findings.extend(kernels_findings())
        # ... and the serve front-end capacity gate (BENCH_SERVE's
        # capacity/fleet_capacity sections vs budgets.json
        # "serve.capacity_rps", recipe-pinned)
        from gene2vec_tpu.analysis.passes_serve import (
            serve_capacity_findings,
        )

        findings.extend(serve_capacity_findings())
        # ... and the ANN retrieval gate (BENCH_ANN recall@10 +
        # scaling factors vs budgets.json "ann.recall", recipe-pinned)
        from gene2vec_tpu.analysis.passes_ann import ann_recall_findings

        findings.extend(ann_recall_findings())
        # ... and the alert-detection gate (BENCH_ALERTS detection
        # latency / false positives / bundle integrity vs budgets.json
        # "alerts", recipe-pinned)
        from gene2vec_tpu.analysis.passes_alerts import alerts_findings

        findings.extend(alerts_findings())
        # ... and the elastic-fleet gate (BENCH_AUTOSCALE scale-up
        # detection ticks / zero-drop scale-down / steady-state no-flap
        # / tenant isolation vs budgets.json "autoscale", recipe-pinned)
        from gene2vec_tpu.analysis.passes_autoscale import (
            autoscale_findings,
        )

        findings.extend(autoscale_findings())
        # ... and the fleet-sharded serving gate (BENCH_SHARD recall/
        # p99/degradation + drill availability & answer integrity vs
        # budgets.json "shard.scatter", recipe-pinned)
        from gene2vec_tpu.analysis.passes_shard import shard_findings

        findings.extend(shard_findings())
        # ... and the continuous-learning gate (BENCH_LOOP promotion
        # integrity: churn/p99-delta budgets, zero wrong/mixed answers,
        # bit-exact SIGKILL resume vs budgets.json "loop",
        # recipe-pinned)
        from gene2vec_tpu.analysis.passes_loop import loop_findings

        findings.extend(loop_findings())
        # ... and the batch-plane gate (BENCH_BATCH graph throughput/
        # oracle recall/SIGKILL-resume bit-identity + mixed-workload
        # p99 delta vs budgets.json "batch.graph", recipe-pinned)
        from gene2vec_tpu.analysis.passes_batch import batch_findings

        findings.extend(batch_findings())
        # ... and the multi-model catalog gate (BENCH_CATALOG verified
        # isolation: 0 wrong/mixed/cross-model answers, per-model
        # scale-up with the cold pool untouched, vs budgets.json
        # "catalog.isolation", recipe-pinned)
        from gene2vec_tpu.analysis.passes_catalog import (
            catalog_findings,
        )

        findings.extend(catalog_findings())

    if args.hlo:
        _pin_cpu_backend()
    if args.hlo in ("hot", "all"):
        from gene2vec_tpu.analysis.passes_hlo import hot_path_findings

        findings.extend(hot_path_findings())
    if args.hlo in ("budgets", "all"):
        from gene2vec_tpu.analysis.passes_hlo import budget_findings

        findings.extend(budget_findings())
    if kinds:
        from gene2vec_tpu.analysis.sanitize import sanitizer_findings

        findings.extend(sanitizer_findings(kinds))

    gate = gating(findings)
    if args.json:
        print(dumps(findings, meta={"argv": sys.argv[1:]}))
    else:
        for f in gate:
            print(f.format())
        infos = len(findings) - len(gate)
        print(
            f"graftcheck: {len(gate)} gating finding(s), "
            f"{infos} informational"
        )
    return 1 if gate else 0


if __name__ == "__main__":
    sys.exit(main())
