"""Serve CLI: the embedding query engine over an export dir.

::

    python -m gene2vec_tpu.cli.serve --export-dir exports/ --port 8000

Emits exactly ONE JSON line on stdout once the server is listening —
``{"url": ..., "dim": ..., "iteration": ..., "run_dir": ...}`` — so
``scripts/serve_loadgen.py --spawn`` (and any other harness) can parse
the bound address; human-readable status goes to stderr.  Every serve
session stamps a ``manifest.json`` run record via
:class:`gene2vec_tpu.obs.run.Run` (default run dir
``<export_dir>/serve_runs/<unix-ts>``); ``/metrics`` serves that run's
registry and the span timeline lands in its ``events.jsonl``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="serve",
        description="Batched embedding query server over a checkpoint "
        "export dir (similar / embedding / interaction endpoints).",
    )
    p.add_argument("--export-dir", required=True,
                   help="io/checkpoint.py export dir (npz + vocab.tsv; "
                        "*_w2v.txt text exports work as a fallback)")
    p.add_argument("--dim", type=int, default=None,
                   help="serve only this table width (default: newest of "
                        "any dim)")
    p.add_argument("--model-name", default="default",
                   help="catalog name this replica serves under "
                        "(serve/catalog.py): /v1/<name>/* aliases the "
                        "unprefixed routes, metrics gain a bounded "
                        "model= label, and healthz reports the name.  "
                        "'default' (the default) keeps every label set "
                        "and response byte-identical to a pre-catalog "
                        "replica")
    p.add_argument("--catalog", default=None, metavar="SPEC.json",
                   help="serve a multi-model catalog spec instead of "
                        "one export dir: one registry + engine + "
                        "watcher per named model, addressed at "
                        "/v1/<model>/* (unprefixed /v1/* serves the "
                        "spec's default model).  --export-dir still "
                        "anchors the run dir; per-model export dirs "
                        "come from the spec.  Incompatible with row "
                        "sharding (docs/SERVING.md#multi-model-catalog)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000,
                   help="0 picks an ephemeral port (printed in the JSON "
                        "status line)")
    p.add_argument("--max-batch", type=int, default=64)
    p.add_argument("--max-delay-ms", type=float, default=5.0,
                   help="micro-batch admission window")
    p.add_argument("--max-queue", type=int, default=256,
                   help="bounded queue depth; beyond it requests get 429")
    p.add_argument("--cache-size", type=int, default=4096,
                   help="LRU entries keyed by (model version, gene, k); "
                        "0 disables")
    p.add_argument("--timeout-ms", type=float, default=2000.0,
                   help="default per-request deadline")
    p.add_argument("--read-timeout", type=float, default=10.0,
                   help="per-request read deadline in seconds (slow "
                        "clients get 408 + close instead of pinning "
                        "front-end state)")
    p.add_argument("--idle-timeout", type=float, default=30.0,
                   help="keep-alive connections idle longer than this "
                        "are closed")
    p.add_argument("--max-conn-requests", type=int, default=0,
                   help="requests served per keep-alive connection "
                        "before it is closed (0 = unbounded)")
    p.add_argument("--acceptors", type=int, default=1,
                   help="acceptor event loops; > 1 binds SO_REUSEPORT "
                        "listening sockets so the kernel spreads "
                        "connections across loops")
    p.add_argument("--http-workers", type=int, default=8,
                   help="bounded worker pool for the full-dispatch "
                        "path (POSTs, traced/fault-injected requests); "
                        "saturation answers 429")
    p.add_argument("--burst-threshold", type=int, default=10,
                   help="5xx responses within --burst-window that dump "
                        "the flight-recorder ring to the run dir "
                        "(rate-limited; docs/OBSERVABILITY.md#alerting)")
    p.add_argument("--burst-window", type=float, default=5.0,
                   help="the 5xx-burst detection window in seconds")
    p.add_argument("--trace-sample", type=float, default=0.0,
                   help="root-trace sampling rate for requests without "
                        "a traceparent header (0..1; propagated sampled "
                        "contexts are always honored; "
                        "docs/OBSERVABILITY.md#distributed-tracing)")
    p.add_argument("--faults", default=None, metavar="JSON",
                   help="resilience/faults.py FaultSpec as JSON — "
                        "deterministic HTTP fault injection for drills "
                        "(default: $GENE2VEC_TPU_FAULTS when set, else "
                        "no injection)")
    p.add_argument("--poll-interval", type=float, default=5.0,
                   help="seconds between export-dir rescans (hot swap)")
    p.add_argument("--run-dir", default=None,
                   help="obs run dir (default: "
                        "<export-dir>/serve_runs/<unix-ts>)")
    p.add_argument("--ggipnn-checkpoint", default=None,
                   help="models/ggipnn_obs checkpoint npz backing "
                        "/v1/interaction (without it the MLP head is "
                        "untrained and responses say so)")
    p.add_argument("--shard-rows", action="store_true",
                   help="row-shard the table over every visible device "
                        "(parallel/sharding.py row_sharding)")
    p.add_argument("--shard-index", type=int, default=None,
                   help="serve ONE contiguous row shard of the table: "
                        "this replica's shard index in [0, "
                        "--num-shards).  Loads only the shard's rows + "
                        "inverted lists, exposes the /v1/shard/* "
                        "scatter + stage/flip surface, and DISABLES "
                        "the self-swap watcher — hot swap becomes the "
                        "fleet coordinator's shard-atomic stage/flip "
                        "(serve/shardgroup.py; normally set by "
                        "cli.fleet --shard-by-rows)")
    p.add_argument("--num-shards", type=int, default=None,
                   help="total shard count for --shard-index")
    p.add_argument("--index", choices=("exact", "quant", "ivf"),
                   default="exact",
                   help="retrieval index (serve/ann.py; docs/SERVING.md "
                        "'Index modes & capacity planning'): exact = "
                        "full f32 brute force (default, bitwise-"
                        "identical to the pre-index engine); quant = "
                        "int8 compressed scan + exact-rescore tail; "
                        "ivf = k-means centroid scan -> --nprobe "
                        "inverted lists -> int8 candidates -> exact "
                        "rescore (centroids cached under "
                        "<export-dir>/ann_cache keyed by table CRC)")
    p.add_argument("--nprobe", type=int, default=8,
                   help="IVF lists probed per query (recall/latency "
                        "knob; ignored unless --index ivf)")
    p.add_argument("--rescore-mult", type=int, default=4,
                   help="exact-rescore tail size as a multiple of k "
                        "(quant/ivf modes; higher = more recall "
                        "headroom per query)")
    p.add_argument("--ann-clusters", type=int, default=None,
                   help="IVF centroid count (default ~4*sqrt(vocab))")
    p.add_argument("--kernel-profile", action="store_true",
                   help="AOT-compile every engine batch bucket at "
                        "startup and publish per-bucket kernel cost "
                        "gauges (flops/bytes/compile seconds) on "
                        "/metrics; costs one compile pass per bucket "
                        "(docs/OBSERVABILITY.md"
                        "#kernel-attribution--rooflines)")
    p.add_argument("--tenant-quota", type=float, default=0.0,
                   metavar="RATE",
                   help="per-tenant token-bucket quota in requests/s "
                        "(X-Tenant header; untagged traffic is the "
                        "'default' tenant).  0 disables multi-tenant "
                        "admission entirely (docs/SERVING.md"
                        "#multi-tenant-admission).  Quotas are "
                        "per-replica: a fleet of N admits N x RATE per "
                        "tenant in aggregate")
    p.add_argument("--tenant-burst", type=float, default=0.0,
                   help="tenant bucket burst headroom "
                        "(0 = 2 x --tenant-quota)")
    p.add_argument("--tenant-override", action="append", default=[],
                   metavar="ID:RATE[:BURST[:WEIGHT]]",
                   help="explicit quota for one tenant (repeatable); "
                        "WEIGHT is its weighted-fair-dequeue share in "
                        "the batcher (default 1)")
    p.add_argument("--jobs-dir", default=None, metavar="DIR",
                   help="batch-job store root: mounts the /v1/jobs "
                        "lifecycle surface (docs/BATCH.md); jobs query "
                        "this replica's own batcher on the low-weight "
                        "'batch' tenant lane and resume from their "
                        "committed cursor across restarts")
    p.add_argument("--batch-weight", type=float, default=0.05,
                   help="the batch lane's weighted-fair share against "
                        "interactive lanes when the queue is contended")
    p.add_argument("--batch-duty", type=float, default=1.0,
                   help="fraction of wall time a batch job may consume "
                        "(1.0 = no idle gap between chunks)")
    p.add_argument("--batch-guard-max", type=float, default=0.5,
                   help="queue-fullness fraction above which batch "
                        "chunks yield entirely until pressure drops")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    from gene2vec_tpu.obs.run import Run
    from gene2vec_tpu.serve.registry import ModelRegistry
    from gene2vec_tpu.serve.server import (
        ServeApp,
        ServeConfig,
        make_server,
    )
    from gene2vec_tpu.serve.tenancy import TenantPolicy

    # a typo'd tenant quota must fail in milliseconds, before the model
    # load (the cli.fleet --alert-rules lesson)
    try:
        TenantPolicy.from_args(
            args.tenant_quota, args.tenant_burst or None,
            args.tenant_override,
        )
    except ValueError as e:
        print(f"error: bad tenant quota flags: {e}", file=sys.stderr)
        return 2

    shard = None
    if (args.shard_index is None) != (args.num_shards is None):
        print(
            "error: --shard-index and --num-shards go together",
            file=sys.stderr,
        )
        return 2
    if args.catalog and (
        args.shard_rows or args.shard_index is not None
    ):
        # a catalog partitions replicas by MODEL, row sharding by row
        # range; one replica cannot sit in both grids at once
        print(
            "error: --catalog cannot combine with --shard-rows/"
            "--shard-index (model pools and row shards are different "
            "fleet partitions)",
            file=sys.stderr,
        )
        return 2
    if args.shard_index is not None:
        if not 0 <= args.shard_index < args.num_shards:
            print(
                f"error: --shard-index {args.shard_index} outside "
                f"[0, {args.num_shards})",
                file=sys.stderr,
            )
            return 2
        shard = (args.shard_index, args.num_shards)

    run_dir = args.run_dir or os.path.join(
        args.export_dir, "serve_runs", str(int(time.time()))
    )
    run = Run(run_dir, name="serve", config=vars(args))
    fault_injector = None
    if args.faults is not None:
        from gene2vec_tpu.resilience.faults import FaultInjector, FaultSpec

        fault_injector = FaultInjector(FaultSpec.from_json(args.faults))
    else:
        from gene2vec_tpu.resilience.faults import FaultInjector

        fault_injector = FaultInjector.from_env()
    if fault_injector is not None:
        print(
            f"FAULT INJECTION ACTIVE: {fault_injector.spec.to_json()}",
            file=sys.stderr,
        )
    mesh = None
    partition_rules = None
    if args.shard_rows:
        import jax

        from gene2vec_tpu.config import MeshConfig
        from gene2vec_tpu.parallel.mesh import make_mesh
        from gene2vec_tpu.parallel.partition_rules import (
            DEFAULT_SERVE_RULES,
        )

        mesh = make_mesh(MeshConfig(data=1, model=len(jax.devices())))
        # declarative placement (parallel/partition_rules.py): the
        # registry derives the row sharding by matching the rule table
        # against its table names, replacing the imperative
        # row_sharding() construction this path used to hand-build
        partition_rules = DEFAULT_SERVE_RULES
    serve_config = ServeConfig(
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        max_queue=args.max_queue,
        cache_size=args.cache_size,
        timeout_ms=args.timeout_ms,
        read_timeout_s=args.read_timeout,
        trace_sample=args.trace_sample,
        idle_timeout_s=args.idle_timeout,
        max_conn_requests=args.max_conn_requests,
        acceptors=args.acceptors,
        http_workers=args.http_workers,
        index=args.index,
        nprobe=args.nprobe,
        rescore_mult=args.rescore_mult,
        kernel_profile=args.kernel_profile,
        burst_threshold=args.burst_threshold,
        burst_window_s=args.burst_window,
        tenant_rate=args.tenant_quota,
        tenant_burst=args.tenant_burst,
        tenant_overrides=tuple(args.tenant_override),
        jobs_dir=args.jobs_dir,
        batch_weight=args.batch_weight,
        batch_duty=args.batch_duty,
        batch_guard_max=args.batch_guard_max,
    )
    catalog = None
    if args.catalog:
        from gene2vec_tpu.serve.catalog import (
            ModelCatalog,
            load_catalog_spec,
        )

        try:
            spec = load_catalog_spec(args.catalog)
        except (ValueError, OSError) as e:
            print(
                f"error: bad catalog spec {args.catalog!r}: {e}",
                file=sys.stderr,
            )
            run.close()
            return 2
        try:
            catalog = ModelCatalog(
                spec,
                config=serve_config,
                metrics=run.registry,
                mesh=mesh,
                fault_injector=fault_injector,
            ).build()
        except RuntimeError as e:
            print(f"error: {e}", file=sys.stderr)
            run.close()
            return 2
        catalog.start(watch_interval_s=args.poll_interval)
        app = catalog.default_app
        print(
            f"catalog {args.catalog}: serving "
            f"{', '.join(catalog.names)} (default {spec.default})",
            file=sys.stderr,
        )
    else:
        registry = ModelRegistry(
            args.export_dir, dim=args.dim,
            metrics=run.registry, index_mode=args.index,
            ann_clusters=args.ann_clusters,
            shard=shard,
            name=args.model_name,
            partition_rules=partition_rules,
            mesh=mesh,
        )
        if not registry.refresh():
            print(
                f"error: no checkpoint found in {args.export_dir!r} "
                "(expected gene2vec_dim_<D>_iter_<N>.npz or *_w2v.txt)",
                file=sys.stderr,
            )
            run.close()
            return 2
        if shard is None:
            registry.start_watcher(args.poll_interval)
        else:
            # shard mode: NO self-swap — the fleet's SwapCoordinator
            # stages + flips every shard as one logical version; a replica
            # swapping on its own poll cadence is exactly the
            # mixed-iteration merge the epoch protocol exists to prevent
            print(
                f"shard {shard[0]}/{shard[1]}: self-swap watcher disabled "
                "(coordinator-driven stage/flip)",
                file=sys.stderr,
            )
        app = ServeApp(
            registry,
            config=serve_config,
            metrics=run.registry,
            ggipnn_checkpoint=args.ggipnn_checkpoint,
            mesh=mesh,
            fault_injector=fault_injector,
            model_name=args.model_name,
        ).start()
    # flight recorder: 5xx bursts dump into the run dir automatically;
    # SIGQUIT dumps on demand (kill -QUIT <pid> during an incident)
    app.flight_dir = run.run_dir
    if args.kernel_profile:
        profiled = app.profile_kernels()
        print(
            f"kernel profile: {len(profiled)} engine buckets "
            f"attributed ({args.index})",
            file=sys.stderr,
        )

    import signal

    def _on_sigquit(signum, frame):
        try:
            path = app.flight.dump(run.run_dir, "sigquit")
            print(f"flight recorder dumped to {path}", file=sys.stderr)
        except Exception as e:
            print(f"flight dump failed: {e!r}", file=sys.stderr)

    try:
        signal.signal(signal.SIGQUIT, _on_sigquit)
    except (ValueError, AttributeError, OSError):
        pass  # non-main thread or platform without SIGQUIT
    server = make_server(app, args.host, args.port)
    host, port = server.server_address[:2]
    url = f"http://{host}:{port}"
    model = app.registry.model
    run.annotate(serve_url=url)
    run.event("serve_start", url=url, iteration=model.iteration)
    # the one stdout line is the machine-readable contract (loadgen
    # --spawn parses it); everything else goes to stderr
    contract = {
        "url": url,
        "dim": model.dim,
        "iteration": model.iteration,
        "run_dir": run.run_dir,
        "index": args.index,
    }
    if shard is not None:
        base = model.row_base
        contract["shard"] = {
            "index": shard[0],
            "num_shards": shard[1],
            "rows": [base, base + len(model)],
            "total_rows": model.total_rows,
            "epoch": model.epoch,
        }
    if args.model_name != "default":
        contract["model_name"] = args.model_name
    if catalog is not None:
        contract["catalog"] = {
            "default": catalog.spec.default,
            "models": list(catalog.names),
        }
    print(json.dumps(contract), flush=True)
    print(
        f"serving {args.export_dir} (dim {model.dim}, iteration "
        f"{model.iteration}, vocab {len(model)}) on {url}; "
        f"run dir {run.run_dir}",
        file=sys.stderr,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        print("shutting down", file=sys.stderr)
    finally:
        server.shutdown()
        server.server_close()
        if catalog is not None:
            catalog.stop()  # stops every per-model app + watcher
        else:
            app.stop()
        run.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
