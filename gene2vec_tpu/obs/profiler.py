"""Kernel cost attribution: rooflines for the compiled hot paths.

The serving/ops planes report *what the system did*; this module reports
*what the compiled kernels cost*.  A :class:`KernelProfiler` registers
named jitted hot paths (the SGNS train step, the CBOW-HS step, the
GGIPNN step, each serve top-k bucket per index mode, the int8 ANN scan)
and captures, per kernel:

* **static cost** — XLA's compiled-computation cost analysis (FLOPs,
  bytes accessed, peak memory) plus the lowering and compile wall time,
  via the AOT path (``fn.lower(...).compile()``);
* **dynamic throughput** — wall time of timed executions
  (:meth:`KernelProfiler.observe` / :meth:`KernelProfiler.measure`),
  from which achieved FLOP/s and bytes/s are derived;
* **roofline position** — achieved-vs-peak utilization against a
  per-backend peak table (:func:`peak_table`): conservative constants
  on CPU, device-fact lookups on TPU, and an explicitly-labeled
  conservative fallback on anything unknown.

Records flow to ``kernels.jsonl`` in the run dir (one JSON object per
kernel, schema :data:`RECORD_SCHEMA`) and, when a registry is attached,
surface as ``kernel_*`` gauges labeled by kernel name — so
``metrics.prom`` and the serve ``/metrics`` endpoint carry the same
numbers ``cli.obs kernels`` renders.

Attribution is warm-time/epoch-level by design: ``attribute`` runs once
per kernel (AOT lower+compile, off the hot path) and ``observe`` costs
one ``perf_counter`` subtraction per *epoch or batch of executions* —
never per step inside a scan.  The ``profiler-hook-in-jit`` static
gate enforces the same discipline at review time.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence

KERNELS_LOG_NAME = "kernels.jsonl"

#: informational — the fields every kernels.jsonl record carries
RECORD_SCHEMA = (
    "name", "flops", "bytes_accessed", "peak_memory_bytes",
    "lower_s", "compile_s", "calls", "wall_s", "best_wall_s",
    "achieved_flops_per_sec", "achieved_bytes_per_sec",
    "flops_util", "bytes_util", "utilization", "bound", "backend",
)

# -- peak table --------------------------------------------------------------

#: deliberately conservative single-core-ish CPU ceilings: a few-wide
#: AVX2 port budget and dual-channel-DDR4-order bandwidth.  Utilization
#: against these reads optimistic on a big server — which is the safe
#: direction for a *regression* gate (the baseline and the candidate
#: share the same table).
CPU_PEAK_FLOPS = 5.0e10
CPU_PEAK_BYTES = 2.0e10

#: per-device (one jax device) peak dense FLOP/s and HBM bytes/s from
#: published TPU specs, keyed by substring of ``device_kind``.  v2/v3
#: expose cores as devices (half-chip numbers); v4+ expose chips.
TPU_DEVICE_PEAKS = {
    "v2": (22.5e12, 300e9),
    "v3": (61.5e12, 450e9),
    "v4": (275e12, 1200e9),
    "v5e": (197e12, 819e9),
    "v5litepod": (197e12, 819e9),
    "v5p": (459e12, 2765e9),
    "v6e": (918e12, 1640e9),
}


def backend_facts() -> Dict[str, Optional[str]]:
    """``{"platform", "device_kind"}`` of the default jax backend, or
    Nones when jax/devices are unavailable (never raises)."""
    try:
        import jax

        dev = jax.devices()[0]
        return {
            "platform": str(dev.platform),
            "device_kind": str(getattr(dev, "device_kind", "")),
        }
    except Exception:
        return {"platform": None, "device_kind": None}


def peak_table(
    platform: Optional[str] = None, device_kind: Optional[str] = None
) -> Dict:
    """Per-backend peak rates: ``{"peak_flops_per_sec",
    "peak_bytes_per_sec", "provenance"}``.

    * CPU → conservative constants (``provenance="cpu-conservative"``);
    * TPU → device-fact lookup by ``device_kind``
      (``provenance="tpu-device-facts"``), falling back to the
      conservative constants when the kind is unrecognized;
    * anything else (gpu, unknown, no backend) → conservative constants
      with ``provenance="unknown-conservative"`` so the record is
      honest about what the utilization number means.
    """
    if platform is None and device_kind is None:
        facts = backend_facts()
        platform = facts["platform"]
        device_kind = facts["device_kind"]
    plat = (platform or "").lower()
    kind = (device_kind or "").lower()
    if plat == "cpu":
        return {
            "peak_flops_per_sec": CPU_PEAK_FLOPS,
            "peak_bytes_per_sec": CPU_PEAK_BYTES,
            "provenance": "cpu-conservative",
        }
    if plat == "tpu":
        # longest-match so "v5litepod" wins over "v5"
        for key in sorted(TPU_DEVICE_PEAKS, key=len, reverse=True):
            if key in kind:
                flops, byps = TPU_DEVICE_PEAKS[key]
                return {
                    "peak_flops_per_sec": flops,
                    "peak_bytes_per_sec": byps,
                    "provenance": "tpu-device-facts",
                }
    return {
        "peak_flops_per_sec": CPU_PEAK_FLOPS,
        "peak_bytes_per_sec": CPU_PEAK_BYTES,
        "provenance": "unknown-conservative",
    }


# -- static cost extraction --------------------------------------------------


def extract_costs(compiled) -> Optional[Dict[str, float]]:
    """FLOPs / bytes accessed / peak memory from a compiled computation.

    Consumes the object returned by ``jitted.lower(...).compile()``.
    Tolerates every shape ``cost_analysis`` has had across jax versions
    (dict, list-of-dict, absent) and backends where ``memory_analysis``
    is unimplemented; returns ``None`` only when no cost channel worked
    at all — the probes-module degrade-gracefully contract.
    """
    costs: Dict[str, float] = {}
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        if isinstance(analysis, dict):
            flops = analysis.get("flops")
            if flops is not None:
                costs["flops"] = float(flops)
            by = analysis.get("bytes accessed", analysis.get("bytes_accessed"))
            if by is not None:
                costs["bytes_accessed"] = float(by)
    except Exception:
        pass
    try:
        mem = compiled.memory_analysis()
        total = 0.0
        for attr in (
            "temp_size_in_bytes",
            "argument_size_in_bytes",
            "output_size_in_bytes",
        ):
            v = getattr(mem, attr, None)
            if v is not None:
                total += float(v)
        if total > 0:
            costs["peak_memory_bytes"] = total
    except Exception:
        pass
    return costs or None


def utilization(
    flops: Optional[float],
    bytes_accessed: Optional[float],
    wall_s: Optional[float],
    peaks: Dict,
) -> Dict:
    """Roofline position of one timed execution: achieved rates, their
    fraction of the peak table, and which wall the kernel leans on
    (``bound="compute"`` when the FLOP fraction dominates, else
    ``"memory"``).  Utilization is the max of the two fractions — the
    roofline convention: a kernel at 80% of memory bandwidth is 80%
    utilized no matter how few FLOPs it does."""
    out: Dict = {
        "achieved_flops_per_sec": None,
        "achieved_bytes_per_sec": None,
        "flops_util": None,
        "bytes_util": None,
        "utilization": None,
        "bound": None,
    }
    if not wall_s or wall_s <= 0:
        return out
    if flops is not None:
        out["achieved_flops_per_sec"] = flops / wall_s
        pf = peaks.get("peak_flops_per_sec")
        if pf:
            out["flops_util"] = out["achieved_flops_per_sec"] / pf
    if bytes_accessed is not None:
        out["achieved_bytes_per_sec"] = bytes_accessed / wall_s
        pb = peaks.get("peak_bytes_per_sec")
        if pb:
            out["bytes_util"] = out["achieved_bytes_per_sec"] / pb
    fu, bu = out["flops_util"], out["bytes_util"]
    if fu is not None or bu is not None:
        out["utilization"] = max(fu or 0.0, bu or 0.0)
        out["bound"] = "compute" if (fu or 0.0) >= (bu or 0.0) else "memory"
    return out


# -- the profiler ------------------------------------------------------------


class KernelProfiler:
    """Named-kernel attribution for one run.

    * :meth:`attribute` — AOT lower+compile a jitted fn under a name,
      timing both phases and extracting static costs.  Warm-time only
      (it does not populate the jit call cache — the first real call
      still compiles; the duplicate compile is the accepted price of
      attribution and is itself what ``compile_s`` measures).
    * :meth:`register_costs` — adopt costs a caller already extracted
      (the serve engine compiles its buckets itself).
    * :meth:`observe` — account executed wall time to a kernel: one
      float add per call site, cheap enough for per-epoch use.
    * :meth:`measure` — timed executions of a compiled/jitted fn with
      ``block_until_ready``, feeding :meth:`observe`.
    * :meth:`flush` — write ``kernels.jsonl`` + ``kernel_*`` gauges.
    """

    def __init__(
        self,
        run_dir: Optional[str] = None,
        registry=None,
        peaks: Optional[Dict] = None,
        backend: Optional[Dict] = None,
    ):
        self.run_dir = run_dir
        self.registry = registry
        self.backend = dict(backend) if backend else backend_facts()
        self.peaks = dict(peaks) if peaks else peak_table(
            self.backend.get("platform"), self.backend.get("device_kind")
        )
        self._static: Dict[str, Dict] = {}
        self._calls: Dict[str, int] = {}
        self._wall: Dict[str, float] = {}
        self._best: Dict[str, float] = {}
        self._order: List[str] = []

    # -- registration --------------------------------------------------------

    def _touch(self, name: str) -> None:
        if name not in self._order:
            self._order.append(name)

    def attribute(
        self,
        name: str,
        fn: Callable,
        args: Sequence = (),
        kwargs: Optional[Dict] = None,
    ) -> Dict:
        """Lower + compile ``fn(*args, **kwargs)`` ahead of time under
        ``name``, recording lowering/compile wall seconds and the XLA
        static costs.  Never raises: a backend that cannot lower still
        yields a record (with ``lower_s`` alone or empty costs)."""
        self._touch(name)
        rec: Dict = {}
        t0 = time.perf_counter()
        try:
            lowered = fn.lower(*args, **(kwargs or {}))
            rec["lower_s"] = time.perf_counter() - t0
            t1 = time.perf_counter()
            compiled = lowered.compile()
            rec["compile_s"] = time.perf_counter() - t1
            costs = extract_costs(compiled)
            if costs:
                rec.update(costs)
        except Exception:
            rec.setdefault("lower_s", time.perf_counter() - t0)
        self._static[name] = {**self._static.get(name, {}), **rec}
        return dict(self._static[name])

    def register_costs(self, name: str, costs: Dict) -> None:
        """Adopt externally-extracted static costs (flops /
        bytes_accessed / peak_memory_bytes / lower_s / compile_s) for
        ``name`` — the serve engine path, which owns its own AOT
        compiles."""
        self._touch(name)
        merged = self._static.get(name, {})
        merged.update(
            {k: v for k, v in costs.items() if v is not None}
        )
        self._static[name] = merged

    # -- dynamic observation -------------------------------------------------

    def observe(self, name: str, wall_s: float, calls: int = 1) -> None:
        """Account ``wall_s`` seconds of executed wall time covering
        ``calls`` executions of ``name``.  Per-epoch granularity: the
        per-call best (min) drives the roofline, the total drives the
        wall-share column."""
        if wall_s < 0:
            return
        self._touch(name)
        self._calls[name] = self._calls.get(name, 0) + int(calls)
        self._wall[name] = self._wall.get(name, 0.0) + float(wall_s)
        if calls > 0:
            per = float(wall_s) / calls
            prev = self._best.get(name)
            if prev is None or per < prev:
                self._best[name] = per

    def measure(
        self,
        name: str,
        fn: Callable,
        args: Sequence = (),
        iters: int = 3,
        warmup: int = 1,
    ) -> Optional[float]:
        """Run ``fn(*args)`` ``warmup`` + ``iters`` times with
        ``block_until_ready``, feeding each timed iteration to
        :meth:`observe`.  Returns the best per-call wall seconds (None
        when execution failed)."""
        try:
            import jax

            for _ in range(max(warmup, 0)):
                jax.block_until_ready(fn(*args))
            for _ in range(max(iters, 1)):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(*args))
                self.observe(name, time.perf_counter() - t0)
            return self._best.get(name)
        except Exception:
            return None

    def attributed_seconds(self) -> Dict[str, float]:
        """Total observed wall seconds per kernel — the goodput
        ``compute`` bucket's per-kernel breakdown feed."""
        return dict(self._wall)

    # -- records + flush -----------------------------------------------------

    def records(self) -> List[Dict]:
        """One merged record per kernel in registration order, with the
        roofline derived from the best observed per-call wall."""
        out = []
        for name in self._order:
            static = self._static.get(name, {})
            best = self._best.get(name)
            rec = {
                "name": name,
                "flops": static.get("flops"),
                "bytes_accessed": static.get("bytes_accessed"),
                "peak_memory_bytes": static.get("peak_memory_bytes"),
                "lower_s": static.get("lower_s"),
                "compile_s": static.get("compile_s"),
                "calls": self._calls.get(name, 0),
                "wall_s": round(self._wall.get(name, 0.0), 9),
                "best_wall_s": (
                    round(best, 9) if best is not None else None
                ),
                "backend": {**self.backend, **self.peaks},
            }
            rec.update(
                utilization(
                    rec["flops"], rec["bytes_accessed"], best, self.peaks
                )
            )
            out.append(rec)
        return out

    def flush(self) -> List[Dict]:
        """Write ``kernels.jsonl`` into the run dir (atomic replace) and
        set the ``kernel_*`` gauges on the attached registry.  Returns
        the records written."""
        recs = self.records()
        if self.run_dir is not None and recs:
            path = os.path.join(self.run_dir, KERNELS_LOG_NAME)
            os.makedirs(self.run_dir, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                for rec in recs:
                    f.write(json.dumps(rec, sort_keys=True) + "\n")
            os.replace(tmp, path)
        if self.registry is not None:
            stamp_records(self.registry, recs)
        return recs


def stamp_records(registry, records: List[Dict]) -> None:
    """Export kernel records as ``kernel_*`` gauges labeled by kernel
    name — the shape both run snapshots and the serve ``/metrics``
    endpoint expose."""
    for rec in records:
        labels = {"kernel": str(rec["name"])}
        for field, metric in (
            ("flops", "kernel_flops"),
            ("bytes_accessed", "kernel_bytes_accessed"),
            ("peak_memory_bytes", "kernel_peak_memory_bytes"),
            ("compile_s", "kernel_compile_seconds"),
            ("lower_s", "kernel_lower_seconds"),
            ("wall_s", "kernel_wall_seconds"),
            ("best_wall_s", "kernel_best_wall_seconds"),
            ("utilization", "kernel_utilization"),
        ):
            v = rec.get(field)
            if v is not None:
                registry.gauge(metric, labels=labels).set(float(v))


# -- reading back ------------------------------------------------------------


def read_kernels(run_dir: str) -> List[Dict]:
    """Parse ``kernels.jsonl`` from a run dir (searching one directory
    level down when the top level has none — the multi-run layout
    ``cli.obs report`` already accepts).  Malformed lines are skipped;
    a missing file is just an empty list."""
    paths = [os.path.join(run_dir, KERNELS_LOG_NAME)]
    if not os.path.isfile(paths[0]) and os.path.isdir(run_dir):
        for entry in sorted(os.listdir(run_dir)):
            sub = os.path.join(run_dir, entry, KERNELS_LOG_NAME)
            if os.path.isfile(sub):
                paths.append(sub)
    out: List[Dict] = []
    for path in paths:
        if not os.path.isfile(path):
            continue
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and rec.get("name"):
                        out.append(rec)
        except OSError:
            continue
    return out


def _fmt_num(v: Optional[float]) -> str:
    if v is None:
        return "-"
    v = float(v)
    for div, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(v) >= div:
            return f"{v / div:.2f}{suffix}"
    return f"{v:.3g}"


def _fmt_pct(v: Optional[float]) -> str:
    return "-" if v is None else f"{100.0 * float(v):.1f}%"


def format_kernels(records: List[Dict]) -> str:
    """Fixed-width roofline table over kernel records (the
    ``cli.obs kernels`` rendering)."""
    header = (
        f"{'kernel':<28} {'flops':>8} {'bytes':>8} {'best_ms':>9} "
        f"{'wall_s':>8} {'util':>7} {'bound':>7} {'compile_s':>9}"
    )
    lines = [header, "-" * len(header)]
    for rec in records:
        best = rec.get("best_wall_s")
        wall = rec.get("wall_s")
        compile_s = rec.get("compile_s")
        best_ms = f"{1e3 * float(best):.3f}" if best is not None else "-"
        wall_str = f"{float(wall):.3f}" if wall is not None else "-"
        comp_str = (
            f"{float(compile_s):.3f}" if compile_s is not None else "-"
        )
        lines.append(
            f"{str(rec.get('name', '')):<28} "
            f"{_fmt_num(rec.get('flops')):>8} "
            f"{_fmt_num(rec.get('bytes_accessed')):>8} "
            f"{best_ms:>9} "
            f"{wall_str:>8} "
            f"{_fmt_pct(rec.get('utilization')):>7} "
            f"{str(rec.get('bound') or '-'):>7} "
            f"{comp_str:>9}"
        )
    if records:
        backend = records[0].get("backend") or {}
        prov = backend.get("provenance")
        if prov:
            lines.append(
                f"peaks: {_fmt_num(backend.get('peak_flops_per_sec'))}F/s "
                f"{_fmt_num(backend.get('peak_bytes_per_sec'))}B/s "
                f"({prov})"
            )
    return "\n".join(lines)


def kernel_summary(records: List[Dict], top: int = 5) -> Dict:
    """Compact per-kernel block for ``cli.obs report``: top kernels by
    observed wall share, plus utilization and compile seconds."""
    total_wall = sum(float(r.get("wall_s") or 0.0) for r in records)
    total_compile = sum(float(r.get("compile_s") or 0.0) for r in records)
    ranked = sorted(
        records, key=lambda r: float(r.get("wall_s") or 0.0), reverse=True
    )
    rows = []
    for rec in ranked[: max(top, 1)]:
        wall = float(rec.get("wall_s") or 0.0)
        rows.append({
            "name": rec.get("name"),
            "wall_s": round(wall, 6),
            "wall_share": (
                round(wall / total_wall, 4) if total_wall > 0 else 0.0
            ),
            "utilization": rec.get("utilization"),
            "bound": rec.get("bound"),
            "compile_s": rec.get("compile_s"),
        })
    return {
        "kernels": len(records),
        "wall_s": round(total_wall, 6),
        "compile_s": round(total_compile, 6),
        "top": rows,
    }
