"""Per-step phase timeline: where does step time actually go?

PR-1 observability stops at per-epoch spans (``iteration``,
``checkpoint``); optimizing the hot paths (ROADMAP items 1 and 3)
needs the breakdown *inside* a step — how long the host spent
assembling inputs, how long the dispatch of the jitted epoch took, how
long the device computed, how long checkpoint staging held the loop.
:class:`PhaseTimeline` records exactly that, into a bounded ring so a
million-step run cannot grow host memory, and flushes once — at run
close — to ``timeline.jsonl`` next to ``events.jsonl``.

Canonical phase names (:data:`PHASES`) cover the training step anatomy:

* ``host_ingest``      — host-side input work (key derivation, batch
  assembly, shuffling done on the host);
* ``h2d_stage``        — host→device staging of inputs;
* ``dispatch``         — calling the jitted function until it returns
  (tracing/compile on the first call, async dispatch after);
* ``compute``          — blocking until the device result is ready
  (``block_until_ready`` / the scalar transfer);
* ``collective_wait``  — cross-device synchronization attributable to
  collectives (multi-host runs);
* ``ckpt_stage``       — checkpoint staging (device→host copy + submit
  on the async path, the full save on the sync path).

Arbitrary names are accepted — the canonical set is the shared
vocabulary, not a schema limit.  Each record is
``{"name", "step", "wall", "dur", "pid", "tid", ...attrs}`` with
``wall`` the phase *start* (``time.time()``), so records from several
processes merge on one clock.

Export is Chrome-trace-event JSON (``chrome_trace``), loadable in
Perfetto / ``chrome://tracing``: each phase name becomes its own named
track, and the converter also lifts ``events.jsonl`` span/hop records
(PR-1 spans, PR-6 distributed-trace hops) into the same view, so a
train timeline and a serve trace render side by side.  The CLI entry
point is ``python -m gene2vec_tpu.cli.obs timeline <run_dir>``.

Overhead discipline: a phase is two ``perf_counter`` calls plus one
dict append per *iteration-level* phase (never per batch inside the
jitted scan); the measured timeline-on vs timeline-off SGNS throughput
delta is recorded in ``BENCH_PERF_r10.json`` and gated ≤ 2% by the
``perf`` section of ``budgets.json`` (``analysis/passes_perf.py``).
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Dict, Iterable, Iterator, List, Optional

TIMELINE_NAME = "timeline.jsonl"

#: canonical step-phase vocabulary (free-form names are also accepted)
PHASES = (
    "host_ingest",
    "h2d_stage",
    "dispatch",
    "compute",
    "collective_wait",
    "ckpt_stage",
)


class PhaseTimeline:
    """Bounded ring of per-step phase timings.

    ``capacity`` bounds host memory: the ring keeps the newest records
    and counts what it evicted (``dropped``) so a flushed file is
    honest about truncation.  ``enabled=False`` makes every method a
    cheap no-op — the overhead-bench OFF arm and the config toggle
    (``SGNSConfig.timeline``) share this switch.
    """

    def __init__(self, capacity: int = 4096, enabled: bool = True):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = capacity
        self.enabled = enabled
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._total = 0
        self._lock = threading.Lock()

    # -- recording ---------------------------------------------------------

    def add(
        self,
        name: str,
        dur: float,
        step: Optional[int] = None,
        wall: Optional[float] = None,
        **attrs,
    ) -> None:
        """Append one completed phase (``wall`` is the phase start)."""
        if not self.enabled:
            return
        rec: Dict = {
            "name": name,
            "wall": time.time() - dur if wall is None else wall,
            "dur": float(dur),
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if step is not None:
            rec["step"] = int(step)
        if attrs:
            rec.update(attrs)
        with self._lock:
            self._ring.append(rec)
            self._total += 1

    @contextlib.contextmanager
    def phase(
        self, name: str, step: Optional[int] = None, **attrs
    ) -> Iterator[None]:
        """Timed phase context.  Disabled timelines skip the clock reads
        entirely — the body runs bare."""
        if not self.enabled:
            yield
            return
        t0w = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(
                name, time.perf_counter() - t0, step=step, wall=t0w, **attrs
            )

    # -- introspection -----------------------------------------------------

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def dropped(self) -> int:
        """Records evicted by the ring bound."""
        return max(0, self._total - self.capacity)

    def records(self) -> List[Dict]:
        """Snapshot of the ring, oldest first."""
        with self._lock:
            return list(self._ring)

    # -- persistence -------------------------------------------------------

    def flush(self, path: str) -> int:
        """Append the ring to a JSON-lines file (one record per line;
        a leading ``timeline_meta`` line records capacity/dropped so
        readers know whether the ring truncated).  Returns the number
        of phase records written.  Disabled timelines write nothing."""
        if not self.enabled:
            return 0
        records = self.records()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            meta = {
                "type": "timeline_meta",
                "capacity": self.capacity,
                "recorded": self._total,
                "dropped": self.dropped,
                "pid": os.getpid(),
                "wall": time.time(),
            }
            f.write(json.dumps(meta, separators=(",", ":")) + "\n")
            for rec in records:
                f.write(json.dumps(rec, separators=(",", ":"), default=str)
                        + "\n")
        return len(records)


def read_timeline(path: str) -> List[Dict]:
    """Parse a ``timeline.jsonl`` (phase records only; ``timeline_meta``
    header lines and torn trailing lines are skipped)."""
    out: List[Dict] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("type") == "timeline_meta":
                continue
            if "name" in rec and "dur" in rec:
                out.append(rec)
    out.sort(key=lambda r: r.get("wall", 0.0))
    return out


# -- Chrome trace-event export ----------------------------------------------

# Synthetic track ids for phase rows start high so they can never
# collide with a real OS thread id rendered from events.jsonl records.
_PHASE_TID_BASE = 1 << 48


def chrome_trace(
    timeline_records: Iterable[Dict],
    span_events: Iterable[Dict] = (),
    process_names: Optional[Dict[int, str]] = None,
) -> Dict:
    """Convert phase records (+ optional ``events.jsonl`` records) into
    one Chrome-trace-event document Perfetto can load.

    * each distinct phase name renders as its own named track
      (synthetic tid + ``thread_name`` metadata) under the recording
      process, so the step anatomy reads as parallel swimlanes;
    * ``span_end`` records from the span tracer become complete ("X")
      events on their real (pid, tid) track — PR-6 hop records
      included, categorized ``hop`` and labelled with their trace id —
      so serve request traces and train timelines merge in one viewer;
    * ``event``/``stall``/``probe`` records become instant ("i") events.

    Timestamps are microseconds relative to the earliest wall clock in
    the input (Chrome traces want small positive ts).
    """
    timeline_records = list(timeline_records)
    span_events = list(span_events)

    starts: List[float] = []
    for r in timeline_records:
        if "wall" in r:
            starts.append(float(r["wall"]))
    for e in span_events:
        if "wall" in e:
            # span_end wall stamps are END times; subtract dur for t0
            starts.append(float(e["wall"]) - float(e.get("dur", 0.0) or 0.0))
    t0 = min(starts) if starts else 0.0

    def us(wall: float) -> float:
        return round((wall - t0) * 1e6, 1)

    events: List[Dict] = []
    seen_pids: Dict[int, None] = {}
    phase_tids: Dict[str, int] = {}
    named_tracks: Dict[tuple, str] = {}

    for r in timeline_records:
        pid = int(r.get("pid", 0))
        seen_pids.setdefault(pid, None)
        name = str(r.get("name", "?"))
        tid = phase_tids.setdefault(name, _PHASE_TID_BASE + len(phase_tids))
        named_tracks[(pid, tid)] = f"phase:{name}"
        args = {
            k: v for k, v in r.items()
            if k not in ("name", "wall", "dur", "pid", "tid")
        }
        events.append({
            "name": name,
            "cat": "phase",
            "ph": "X",
            "ts": us(float(r.get("wall", t0))),
            "dur": round(max(float(r.get("dur", 0.0)), 0.0) * 1e6, 1),
            "pid": pid,
            "tid": tid,
            **({"args": args} if args else {}),
        })

    for e in span_events:
        etype = e.get("type")
        pid = int(e.get("pid", 0))
        tid = int(e.get("tid", 0))
        seen_pids.setdefault(pid, None)
        attrs = e.get("attrs") or {}
        if etype == "span_end":
            dur = float(e.get("dur", 0.0) or 0.0)
            args = dict(attrs)
            cat = "span"
            if e.get("hop"):
                cat = "hop"
            if e.get("trace"):
                args["trace"] = e["trace"]
            events.append({
                "name": str(e.get("name", "?")),
                "cat": cat,
                "ph": "X",
                "ts": us(float(e.get("wall", t0)) - dur),
                "dur": round(max(dur, 0.0) * 1e6, 1),
                "pid": pid,
                "tid": tid,
                **({"args": args} if args else {}),
            })
        elif etype in ("event", "stall", "probe"):
            events.append({
                "name": str(e.get("name", "?")),
                "cat": str(etype),
                "ph": "i",
                "s": "t",
                "ts": us(float(e.get("wall", t0))),
                "pid": pid,
                "tid": tid,
                **({"args": dict(attrs)} if attrs else {}),
            })
        # span_start records carry nothing span_end lacks — skipped

    meta: List[Dict] = []
    for pid in seen_pids:
        label = (process_names or {}).get(pid) or f"pid {pid}"
        meta.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": label},
        })
    for (pid, tid), label in named_tracks.items():
        meta.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": label},
        })

    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "gene2vec_tpu.obs.timeline",
            "t0_unix": t0,
            "phase_tracks": sorted(phase_tids),
        },
    }


def collect_run(run_dir: str) -> Dict:
    """Build the Chrome trace for one run directory tree: every
    ``timeline.jsonl`` and ``events.jsonl`` under ``run_dir`` (a fleet
    export dir covers the proxy run and every replica's) merges into
    one document."""
    from gene2vec_tpu.obs.run import EVENTS_NAME, MANIFEST_NAME
    from gene2vec_tpu.obs.trace import read_events

    timeline_records: List[Dict] = []
    span_events: List[Dict] = []
    process_names: Dict[int, str] = {}
    for dirpath, _, filenames in os.walk(run_dir):
        if TIMELINE_NAME in filenames:
            timeline_records.extend(
                read_timeline(os.path.join(dirpath, TIMELINE_NAME))
            )
        if EVENTS_NAME in filenames:
            span_events.extend(
                read_events(os.path.join(dirpath, EVENTS_NAME))
            )
        if MANIFEST_NAME in filenames:
            try:
                with open(
                    os.path.join(dirpath, MANIFEST_NAME), encoding="utf-8"
                ) as f:
                    m = json.load(f)
                if isinstance(m.get("pid"), int) and m.get("name"):
                    process_names[m["pid"]] = f"{m['name']} (pid {m['pid']})"
            except (OSError, ValueError):
                pass
    return chrome_trace(
        timeline_records, span_events, process_names=process_names
    )
