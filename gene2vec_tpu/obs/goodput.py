"""Goodput accounting: how much of a run's wall time was real training?

SRE-style goodput for the training loops: classify the run's wall time
into buckets and report achieved-vs-peak throughput, so "the run took
40 minutes" decomposes into "34 compute, 3 checkpointing, 2 input
stalls, 1 drained after preemption".  The classification consumes the
:mod:`~gene2vec_tpu.obs.timeline` phase records — each canonical phase
maps to one bucket — and the invariant is exact: the reported buckets
**sum to the wall time** (``other`` absorbs unattributed host time;
when instrumented phases overlap and exceed the wall clock, the known
buckets are scaled down proportionally rather than reporting a sum
that disagrees with the clock).

Buckets:

* ``compute``     — dispatch + device compute + collective wait (the
  time the accelerator was doing, or directly feeding, real work);
* ``input_stall`` — host-side input work the device waited on
  (``host_ingest`` / ``h2d_stage`` phases);
* ``checkpoint``  — checkpoint staging/commit time on the loop thread;
* ``preempted``   — wall time between the preemption signal landing
  and the drain completing (work the scheduler reclaimed);
* ``other``       — everything unattributed (logging, probes, python).

The summary is stamped into the run manifest (``manifest.json`` key
``goodput``) and exported as gauges into ``metrics.prom``
(:func:`stamp`), so ``cli.obs report`` and external tooling read it
without re-deriving anything.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

BUCKETS = ("compute", "input_stall", "checkpoint", "preempted", "other")

#: canonical timeline phase name → goodput bucket
PHASE_BUCKET = {
    "dispatch": "compute",
    "compute": "compute",
    "compute_wait": "compute",
    "collective_wait": "compute",
    "host_ingest": "input_stall",
    "h2d_stage": "input_stall",
    "ckpt_stage": "checkpoint",
    "checkpoint": "checkpoint",
}


def classify(
    timeline_records: Iterable[Dict],
    wall_s: float,
    preempted_s: float = 0.0,
) -> Dict[str, float]:
    """Bucket a run's wall time.  Returns ``{bucket: seconds}`` over
    exactly :data:`BUCKETS`, summing to ``wall_s`` (to float
    precision).  Unknown phase names fall into ``other`` implicitly
    (they are simply not attributed)."""
    wall_s = max(float(wall_s), 0.0)
    buckets = {b: 0.0 for b in BUCKETS}
    for rec in timeline_records:
        bucket = PHASE_BUCKET.get(str(rec.get("name", "")))
        if bucket is None:
            continue
        buckets[bucket] += max(float(rec.get("dur", 0.0)), 0.0)
    buckets["preempted"] = max(float(preempted_s), 0.0)
    known = sum(buckets.values())
    if known > wall_s and known > 0.0:
        # overlapping/duplicated instrumentation cannot make the report
        # exceed the clock: scale attributed time down to fit
        scale = wall_s / known
        for b in buckets:
            buckets[b] *= scale
        known = wall_s
    buckets["other"] = wall_s - known
    return buckets


def summarize(
    timeline_records: Iterable[Dict],
    wall_s: float,
    pairs_total: float = 0.0,
    peak_pairs_per_sec: Optional[float] = None,
    preempted_s: float = 0.0,
    kernel_seconds: Optional[Dict[str, float]] = None,
) -> Dict:
    """The full goodput summary stamped into run manifests.

    * ``buckets_s`` / ``fractions`` — the wall-time classification;
    * ``achieved_pairs_per_sec`` — pairs over the whole wall clock
      (what a user of the run actually got);
    * ``peak_pairs_per_sec`` — the best sustained rate observed (the
      caller passes the max per-iteration rate; falls back to pairs
      over compute-bucket seconds when not given);
    * ``utilization`` — achieved/peak: the fraction of the machine's
      demonstrated capability the run delivered end to end;
    * ``compute_kernels`` / ``compute_kernels_s`` (only with
      ``kernel_seconds``, the profiler's attributed wall per kernel) —
      per-kernel breakdown OF the compute bucket, same discipline as
      the buckets themselves: over-attribution scales down to fit the
      bucket, under-attribution leaves an explicit ``_unattributed``
      residual, so the kernel seconds sum to the compute bucket
      exactly (and the wall fractions to the compute fraction).
    """
    records = list(timeline_records)
    buckets = classify(records, wall_s, preempted_s=preempted_s)
    wall_s = max(float(wall_s), 0.0)
    fractions = {
        b: (buckets[b] / wall_s if wall_s > 0 else 0.0) for b in BUCKETS
    }
    achieved = pairs_total / wall_s if wall_s > 0 else 0.0
    peak = peak_pairs_per_sec
    if peak is None and buckets["compute"] > 0:
        peak = pairs_total / buckets["compute"]
    kernels_s: Optional[Dict[str, float]] = None
    if kernel_seconds is not None:
        compute_s = buckets["compute"]
        kernels_s = {
            str(k): max(float(v), 0.0)
            for k, v in kernel_seconds.items()
            if float(v) > 0.0
        }
        attributed = sum(kernels_s.values())
        if attributed > compute_s and attributed > 0.0:
            scale = compute_s / attributed
            kernels_s = {k: v * scale for k, v in kernels_s.items()}
        else:
            kernels_s["_unattributed"] = compute_s - attributed
    return {
        "wall_s": round(wall_s, 6),
        "buckets_s": {b: round(v, 6) for b, v in buckets.items()},
        "fractions": {b: round(v, 6) for b, v in fractions.items()},
        "pairs_total": float(pairs_total),
        "achieved_pairs_per_sec": round(achieved, 1),
        "peak_pairs_per_sec": (
            round(float(peak), 1) if peak is not None else None
        ),
        "utilization": (
            round(achieved / peak, 4) if peak else None
        ),
        **(
            {
                "compute_kernels_s": {
                    k: round(v, 6) for k, v in kernels_s.items()
                },
                "compute_kernels": {
                    k: (round(v / wall_s, 6) if wall_s > 0 else 0.0)
                    for k, v in kernels_s.items()
                },
            }
            if kernels_s is not None else {}
        ),
    }


def stamp(run, summary: Dict) -> None:
    """Persist a goodput summary: merge into the run's on-disk manifest
    (key ``goodput``) and set the ``goodput_*_fraction`` /
    ``achieved_pairs_per_sec`` / ``peak_pairs_per_sec`` gauges so the
    run-close ``metrics.prom`` snapshot carries them."""
    run.annotate(goodput=summary)
    for b in BUCKETS:
        run.registry.gauge(f"goodput_{b}_fraction").set(
            summary["fractions"][b]
        )
    run.registry.gauge("achieved_pairs_per_sec").set(
        summary["achieved_pairs_per_sec"]
    )
    if summary.get("peak_pairs_per_sec") is not None:
        run.registry.gauge("peak_pairs_per_sec").set(
            summary["peak_pairs_per_sec"]
        )
    if summary.get("utilization") is not None:
        run.registry.gauge("goodput_utilization").set(summary["utilization"])
    for kernel, frac in (summary.get("compute_kernels") or {}).items():
        run.registry.gauge(
            "goodput_kernel_fraction", labels={"kernel": kernel}
        ).set(frac)
