"""Incident capture: bundle the evidence the moment a rule fires.

When an :class:`~gene2vec_tpu.obs.alerts.AlertEvaluator` rule
transitions to ``firing``, the on-call wants three things in one place:
*what fired*, *what the fleet looked like*, and *what a slow/failed
request actually did*.  :class:`IncidentManager` assembles exactly that
into a bounded **incident bundle** under
``<run_dir>/incidents/<ts>_<rule>/``:

* ``rule.json``            — the triggering rule, the transition record,
  and the snapshot values it fired on;
* ``metrics_window.json``  — the aggregator's RAW per-target scrape ring
  (the un-merged series, so per-replica attribution survives the merge:
  *which* replica's counters went bad is readable after the fact);
* ``flightdump-<pid>.json`` — a SIGQUIT-equivalent flight-recorder dump
  solicited from every live replica via ``GET /debug/flight``
  (serve/server.py) plus the proxy's own ring — the requests *around*
  the incident, even the unsampled ones;
* ``trace-<id>.json``      — the slowest sampled traces in the window,
  reassembled across every process via the existing
  :func:`~gene2vec_tpu.obs.flight.collect_trace`;
* ``incident.MANIFEST.json`` — CRC32/size stamps over every bundle file
  via the resilience snapshot primitives
  (:func:`~gene2vec_tpu.resilience.snapshot.write_manifest`), written
  LAST — a bundle without a verifying manifest is torn, exactly like a
  checkpoint.

Assembly is **rate-limited** (the :class:`~gene2vec_tpu.obs.alerts.
RateLimiter` shared with the flight recorder's burst dumps) and
**disk-capped** (``max_bundles`` newest kept, hard ``max_total_bytes``
ceiling), so a flapping rule can never fill the disk.  It runs on its
own thread (``fire_async``) — the aggregator's scrape tick must never
block on N replica fetches.

``python -m gene2vec_tpu.cli.obs incident <bundle>`` verifies the
manifest and renders the bundle.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence

from gene2vec_tpu.obs import flight as flight_mod
from gene2vec_tpu.resilience import snapshot as snap

SCHEMA = "gene2vec-tpu/incident/v1"
#: bundle files whose prefix deliberately does NOT match the flight
#: recorder's ``flight-`` discovery prefix: a bundle lives inside the
#: run-dir tree that ``collect_trace`` scans, and its copies must not
#: double-count as live dumps
FLIGHTDUMP_PREFIX = "flightdump-"
MANIFEST_PREFIX = "incident"


def _dir_bytes(root: str) -> int:
    total = 0
    for dirpath, _, filenames in os.walk(root):
        for name in filenames:
            try:
                total += os.path.getsize(os.path.join(dirpath, name))
            except OSError:
                continue
    return total


def _default_fetch(url: str, timeout_s: float) -> Dict:
    with urllib.request.urlopen(
        f"{url}/debug/flight", timeout=timeout_s
    ) as r:
        return json.loads(r.read().decode("utf-8"))


def collect_trace_multi(roots: Sequence[str], trace_id: str) -> Dict:
    """:func:`~gene2vec_tpu.obs.flight.collect_trace` over several scan
    roots (export dir + an out-of-tree fleet run dir), merged into one
    document.  Nested/duplicate roots are deduped by path prefix."""
    kept: List[str] = []
    for root in sorted(
        {os.path.abspath(r) for r in roots if r}, key=len
    ):
        if not any(
            root == k or root.startswith(k + os.sep) for k in kept
        ):
            kept.append(root)
    merged: Optional[Dict] = None
    for root in kept:
        doc = flight_mod.collect_trace(root, trace_id)
        if merged is None:
            merged = doc
            continue
        merged["files_scanned"] += doc["files_scanned"]
        merged["hop_records"] += doc["hop_records"]
        merged["processes"] = sorted(
            set(merged["processes"]) | set(doc["processes"])
        )
        merged["roots"].extend(doc["roots"])
        merged["flight"].extend(doc["flight"])
    return merged if merged is not None else {
        "trace_id": trace_id, "files_scanned": 0, "hop_records": 0,
        "processes": [], "roots": [], "flight": [],
    }


class IncidentManager:
    """Assembles one bundle per allowed firing.

    ``targets`` is a zero-arg callable returning the replica base URLs
    to solicit flight dumps from (the supervisor's live set);
    ``local_flight`` is the calling process's own
    :class:`~gene2vec_tpu.obs.flight.FlightRecorder` (the proxy's ring
    is captured in-process, not over HTTP); ``aggregator`` provides the
    raw scrape window; ``scan_roots`` are the directory trees trace
    reassembly walks.  ``fetch`` and ``clock`` are injectable for
    tests.
    """

    def __init__(
        self,
        incidents_dir: str,
        scan_roots: Sequence[str] = (),
        targets: Optional[Callable[[], Sequence[str]]] = None,
        local_flight=None,
        aggregator=None,
        limiter=None,
        metrics=None,
        fetch: Callable[[str, float], Dict] = _default_fetch,
        fetch_timeout_s: float = 3.0,
        window_s: float = 120.0,
        max_traces: int = 3,
        max_bundles: int = 8,
        max_total_bytes: int = 64 << 20,
        clock=time.monotonic,
    ):
        self.incidents_dir = os.path.abspath(incidents_dir)
        self.scan_roots = list(scan_roots)
        self.targets = targets
        self.local_flight = local_flight
        self.aggregator = aggregator
        self.limiter = limiter
        self.metrics = metrics
        self._fetch = fetch
        self.fetch_timeout_s = fetch_timeout_s
        self.window_s = window_s
        self.max_traces = max_traces
        self.max_bundles = max_bundles
        self.max_total_bytes = max_total_bytes
        self._clock = clock
        self._lock = threading.Lock()
        self.last_bundle: Optional[str] = None

    # -- entry points ------------------------------------------------------

    def fire_async(self, rule, snapshot: Dict, record: Dict) -> None:
        """``AlertEvaluator.on_fire`` adapter: assemble on a background
        thread so the scrape tick never blocks on replica fetches."""
        threading.Thread(
            target=self.on_fire, args=(rule, snapshot, record),
            name=f"incident-{getattr(rule, 'name', 'rule')}", daemon=True,
        ).start()

    def on_fire(self, rule, snapshot: Dict, record: Dict) -> Optional[str]:
        """Assemble one bundle; returns its path, or None when rate- or
        disk-limited (counted, never raised — alerting must outlive its
        own forensics)."""
        try:
            return self._assemble(rule, snapshot, record)
        except Exception as e:
            self._count("incident_errors_total")
            print(f"incident: bundle assembly failed: {e!r}",
                  file=sys.stderr)
            return None

    # -- assembly ----------------------------------------------------------

    def _count(self, name: str, **labels) -> None:
        if self.metrics is not None:
            self.metrics.counter(
                name, labels=labels or None
            ).inc()

    def _assemble(self, rule, snapshot: Dict, record: Dict) -> Optional[str]:
        name = getattr(rule, "name", str(rule))
        if self.limiter is not None and not self.limiter.allow(
            f"incident:{name}"
        ):
            self._count("incident_rate_limited_total")
            return None
        with self._lock:  # one bundle at a time; overlap is re-limited
            self._prune()
            if _dir_bytes(self.incidents_dir) >= self.max_total_bytes:
                self._count("incident_disk_capped_total")
                return None
            bundle = self._bundle_dir(name)
            files: List[str] = []

            def write_json(fname: str, doc: Dict) -> None:
                path = os.path.join(bundle, fname)
                snap.atomic_write_json(path, doc)
                files.append(path)

            write_json("rule.json", {
                "schema": SCHEMA,
                "created_unix": time.time(),
                "rule": self._rule_doc(rule),
                "transition": record,
                "snapshot": {
                    k: v for k, v in snapshot.items()
                    if isinstance(v, (int, float, str))
                },
            })
            # raw per-target scrape window: the UN-merged series, so
            # "which replica went bad" survives the fleet merge
            if self.aggregator is not None:
                window = getattr(self.aggregator, "raw_recent", None)
                write_json("metrics_window.json", {
                    "schema": "gene2vec-tpu/incident-metrics/v1",
                    "window": window() if callable(window) else [],
                })
            flight_docs = self._solicit_flight(write_json)
            self._reassemble_traces(flight_docs, write_json)
            # the manifest is the bundle's commit record, written LAST
            snap.write_manifest(
                os.path.join(bundle, MANIFEST_PREFIX), files,
                meta={"incident_schema": SCHEMA, "rule": name},
            )
            self._count("incidents_total", rule=name)
            self.last_bundle = bundle
            return bundle

    def _rule_doc(self, rule) -> Dict:
        import dataclasses

        if dataclasses.is_dataclass(rule) and not isinstance(rule, type):
            return dataclasses.asdict(rule)
        return {"name": getattr(rule, "name", str(rule))}

    def _bundle_dir(self, rule_name: str) -> str:
        base = f"{int(time.time())}_{rule_name}"
        path = os.path.join(self.incidents_dir, base)
        n = 1
        while os.path.exists(path):  # same rule, same second
            path = os.path.join(self.incidents_dir, f"{base}.{n}")
            n += 1
        os.makedirs(path)
        return path

    def _prune(self) -> None:
        """Keep only the newest ``max_bundles - 1`` existing bundles
        (the one being assembled makes ``max_bundles``)."""
        try:
            entries = sorted(
                e for e in os.listdir(self.incidents_dir)
                if os.path.isdir(os.path.join(self.incidents_dir, e))
            )
        except OSError:
            return
        import shutil

        for stale in entries[: max(0, len(entries) - self.max_bundles + 1)]:
            try:
                shutil.rmtree(os.path.join(self.incidents_dir, stale))
                self._count("incident_bundles_pruned_total")
            except OSError:
                continue

    def _solicit_flight(self, write_json) -> List[Dict]:
        """The proxy's own ring + ``GET /debug/flight`` from every live
        replica.  A replica that cannot answer is counted and skipped —
        an incident bundle built DURING the incident must tolerate the
        incident."""
        docs: List[Dict] = []
        written = set()

        def emit(doc: Dict) -> None:
            docs.append(doc)
            pid = doc.get("pid", 0)
            fname = f"{FLIGHTDUMP_PREFIX}{pid}.json"
            n = 1
            while fname in written:  # pid collision guard
                fname = f"{FLIGHTDUMP_PREFIX}{pid}.{n}.json"
                n += 1
            written.add(fname)
            write_json(fname, doc)

        if self.local_flight is not None:
            emit(self.local_flight.snapshot_doc("incident"))
        for url in (self.targets() if self.targets is not None else ()):
            try:
                doc = self._fetch(url, self.fetch_timeout_s)
            except Exception:
                self._count("incident_flight_fetch_errors_total")
                continue
            if not isinstance(doc, dict) or "records" not in doc:
                self._count("incident_flight_fetch_errors_total")
                continue
            emit({**doc, "target": url})
        return docs

    def _reassemble_traces(self, flight_docs: List[Dict],
                           write_json) -> None:
        """The slowest sampled trace ids among the window's flight
        records, reassembled cross-process."""
        now = time.time()
        candidates: List[Dict] = []
        for doc in flight_docs:
            for rec in doc.get("records", ()):
                if not isinstance(rec, dict) or not rec.get("trace"):
                    continue
                if (now - float(rec.get("wall", 0.0))) > self.window_s:
                    continue
                candidates.append(rec)
        candidates.sort(
            key=lambda r: float(r.get("dur_s", 0.0)), reverse=True
        )
        seen = set()
        for rec in candidates:
            if len(seen) >= self.max_traces:
                break
            tid = rec["trace"]
            if tid in seen:
                continue
            seen.add(tid)
            doc = collect_trace_multi(self.scan_roots, tid)
            doc["picked_for"] = {
                "route": rec.get("route"), "status": rec.get("status"),
                "dur_s": rec.get("dur_s"),
            }
            write_json(f"trace-{tid}.json", doc)


# -- verification + rendering (cli.obs incident) ------------------------------


def verify_bundle(bundle_dir: str):
    """CRC-verify one bundle via the resilience manifest primitives.
    Returns the :class:`~gene2vec_tpu.resilience.snapshot.VerifyResult`
    (falsy with a machine-parseable reason on a torn bundle)."""
    return snap.verify_manifest(
        os.path.join(bundle_dir, MANIFEST_PREFIX), use_cache=False
    )


def _read_json(path: str) -> Optional[Dict]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


def format_bundle(bundle_dir: str, verify) -> str:
    """Human-readable bundle report (the cli.obs incident runbook view:
    docs/OBSERVABILITY.md#reading-a-bundle)."""
    lines = [f"incident bundle {bundle_dir}"]
    lines.append(
        f"  manifest: {'VERIFIED' if verify else f'FAILED ({verify.reason})'}"
    )
    rule = _read_json(os.path.join(bundle_dir, "rule.json")) or {}
    r = rule.get("rule") or {}
    tr = rule.get("transition") or {}
    lines.append(
        f"  rule: {r.get('name')} [{r.get('severity')}] kind={r.get('kind')}"
    )
    value = tr.get("value")
    lines.append(
        f"  fired: {tr.get('from')} -> {tr.get('to')}"
        + (f" at value {value:g}" if isinstance(value, (int, float)) else "")
    )
    snapshot = rule.get("snapshot") or {}
    for key in sorted(snapshot):
        if key.startswith("_"):
            continue
        v = snapshot[key]
        if isinstance(v, (int, float)):
            lines.append(f"    {key} = {v:g}")
    metrics = _read_json(os.path.join(bundle_dir, "metrics_window.json"))
    if metrics is not None:
        window = metrics.get("window") or []
        targets = sorted({w.get("target") for w in window
                          if isinstance(w, dict)})
        lines.append(
            f"  metrics window: {len(window)} raw scrape(s) across "
            f"{len(targets)} target(s)"
        )
    try:
        names = sorted(os.listdir(bundle_dir))
    except OSError:
        names = []
    dumps = [n for n in names if n.startswith(FLIGHTDUMP_PREFIX)]
    traces = [n for n in names if n.startswith("trace-")]
    lines.append(f"  flight dumps: {len(dumps)} ({', '.join(dumps)})"
                 if dumps else "  flight dumps: none")
    for name in traces:
        doc = _read_json(os.path.join(bundle_dir, name)) or {}
        picked = doc.get("picked_for") or {}
        lines.append(
            f"  trace {doc.get('trace_id', name)}: "
            f"{doc.get('hop_records', 0)} record(s) across "
            f"{len(doc.get('processes', []))} process(es)"
            + (
                f"  [{picked.get('route')} status={picked.get('status')} "
                f"dur={picked.get('dur_s')}s]" if picked else ""
            )
        )
    if not traces:
        lines.append("  traces: none reassembled")
    return "\n".join(lines)
