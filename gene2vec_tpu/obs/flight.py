"""Flight recorder + cross-process trace reassembly.

**Flight recorder**: every replica (and the fleet proxy) keeps a
bounded in-memory ring of recent request records — trace id, route,
status, duration, per-hop timings — costing one deque append per
request.  The ring is dumped to the run dir as ``flight-<pid>-<n>.json``
when (a) the operator sends SIGQUIT (``cli.serve`` installs the
handler), or (b) a 5xx burst is detected (``burst_threshold`` server
errors within ``burst_window_s``, rate-limited to one dump per window)
— so the moments *around* an incident are on disk even when sampling
missed the individual requests.

**Hop sink**: a thread-local dict installed around one request's
handling (:func:`collect_hops`); downstream stages on the same thread
(the batcher ticket recording queue-wait/compute time) deposit their
timings into it via :func:`add_hop` without any plumbing through the
route layer.

**Trace reassembly**: :func:`collect_trace` walks a directory tree for
``events.jsonl`` files and flight dumps (a fleet export dir holds the
proxy's ``fleet_runs/<ts>`` and every replica's ``serve_runs/<ts>``),
gathers the records stamped with one trace id, and rebuilds the
cross-process tree — proxy hop → client attempts (retries/hedges as
siblings) → replica request → batcher item → the process-local
``serve_batch``/``serve_compute``/``engine_topk`` subtree.  ``python -m
gene2vec_tpu.cli.obs trace <run_dir> <trace_id>`` renders it.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import json
import math
import os
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

FLIGHT_PREFIX = "flight-"

# -- per-request hop sink (thread-local) -------------------------------------

_hops_local = threading.local()


@contextlib.contextmanager
def collect_hops() -> Iterator[Dict[str, float]]:
    """Install a fresh hop-timing sink for this thread; stages that run
    on the request thread (``Ticket.get``) deposit into it."""
    prev = getattr(_hops_local, "sink", None)
    sink: Dict[str, float] = {}
    _hops_local.sink = sink
    try:
        yield sink
    finally:
        _hops_local.sink = prev


def add_hop(key: str, value: float) -> None:
    """Record one per-hop timing into the current request's sink (no-op
    without one — library code never needs to know whether a recorder
    is active)."""
    sink = getattr(_hops_local, "sink", None)
    if sink is not None:
        sink[key] = round(float(value), 6)


# -- the recorder ------------------------------------------------------------


class FlightRecorder:
    """Bounded ring of recent request records with 5xx-burst detection.

    ``record`` returns True when its 5xx pushed the burst window over
    ``burst_threshold`` and a dump is due — the caller dumps, the
    recorder never touches disk on the hot path.  The trigger is
    configurable (``cli.serve --burst-threshold/--burst-window``), and
    when a shared :class:`~gene2vec_tpu.obs.alerts.RateLimiter` is
    provided it arbitrates dump cadence INSTEAD of the internal
    once-per-window rule — in the fleet proxy, burst dumps and
    rule-triggered incident bundles then draw from one budget, so an
    error storm plus a flapping alert cannot multiply disk writes.
    """

    def __init__(
        self,
        capacity: int = 512,
        burst_threshold: int = 10,
        burst_window_s: float = 5.0,
        clock=time.monotonic,
        limiter=None,
    ):
        self.capacity = capacity
        self.burst_threshold = burst_threshold
        self.burst_window_s = burst_window_s
        self._clock = clock
        self.limiter = limiter
        self._ring: "collections.deque[Dict]" = collections.deque(
            maxlen=capacity
        )
        self._5xx: "collections.deque[float]" = collections.deque()
        self._last_burst_dump = -math.inf
        self._seq = itertools.count(1)
        self._lock = threading.Lock()

    def record(
        self,
        route: str,
        status: int,
        dur_s: float,
        trace_id: Optional[str] = None,
        hops: Optional[Dict[str, float]] = None,
    ) -> bool:
        rec = {
            "wall": time.time(),
            "pid": os.getpid(),
            "route": route,
            "status": int(status),
            "dur_s": round(float(dur_s), 6),
        }
        if trace_id:
            rec["trace"] = trace_id
        if hops:
            rec["hops"] = dict(hops)
        now = self._clock()
        with self._lock:
            self._ring.append(rec)
            if status < 500:
                return False
            self._5xx.append(now)
            horizon = now - self.burst_window_s
            while self._5xx and self._5xx[0] < horizon:
                self._5xx.popleft()
            if len(self._5xx) < self.burst_threshold:
                return False
            if self.limiter is not None:
                # the shared alert/incident limiter owns dump cadence
                return self.limiter.allow("5xx-burst")
            if now - self._last_burst_dump >= self.burst_window_s:
                self._last_burst_dump = now
                return True
        return False

    def snapshot(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def snapshot_doc(self, reason: str) -> Dict:
        """The dump document WITHOUT touching disk — what ``GET
        /debug/flight`` returns and the incident manager files into a
        bundle (one schema for on-disk and over-the-wire dumps)."""
        return {
            "schema": "gene2vec-tpu/flight/v1",
            "reason": reason,
            "written_unix": time.time(),
            "pid": os.getpid(),
            "records": self.snapshot(),
        }

    def dump(self, dirpath: str, reason: str) -> str:
        """Write the current ring to ``<dirpath>/flight-<pid>-<n>.json``
        (tmp + rename, so reassembly never reads a torn dump)."""
        os.makedirs(dirpath, exist_ok=True)
        path = os.path.join(
            dirpath, f"{FLIGHT_PREFIX}{os.getpid()}-{next(self._seq)}.json"
        )
        doc = self.snapshot_doc(reason)
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)
        return path


# -- reassembly --------------------------------------------------------------


def _iter_artifact_files(root: str) -> Iterator[Tuple[str, str]]:
    """(kind, path) for every events.jsonl / flight dump under root."""
    for dirpath, _, filenames in os.walk(root):
        for fname in sorted(filenames):
            if fname == "events.jsonl":
                yield "events", os.path.join(dirpath, fname)
            elif fname.startswith(FLIGHT_PREFIX) and fname.endswith(".json"):
                yield "flight", os.path.join(dirpath, fname)


def _read_jsonl(path: str) -> List[Dict]:
    out = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn trailing line (a SIGKILLed writer)
    except OSError:
        pass
    return out


def _expand_process_subtree(
    span_id: str, pid: int, by_parent: Dict[Tuple[int, str], List[Dict]],
    by_span: Dict[Tuple[int, str], Dict], depth: int = 0,
) -> List[Dict]:
    """The process-local span subtree rooted at (pid, span_id) — how a
    ``batch_item`` hop picks up the ``serve_batch``/``serve_compute``/
    ``engine_topk`` spans that served it."""
    root = by_span.get((pid, span_id))
    if root is None or depth > 8:
        return []
    node = {
        "name": root.get("name"),
        "pid": pid,
        "wall": root.get("wall"),
        "dur": root.get("dur"),
        "attrs": root.get("attrs") or {},
        "children": [],
    }
    for child in sorted(
        by_parent.get((pid, span_id), []), key=lambda r: r.get("wall", 0.0)
    ):
        node["children"].extend(_expand_process_subtree(
            child.get("span"), pid, by_parent, by_span, depth + 1
        ))
    return [node]


def collect_trace(root_dir: str, trace_id: str) -> Dict:
    """Reassemble one trace from every ``events.jsonl`` and flight dump
    under ``root_dir`` (pass a fleet export dir to cover the proxy's
    run AND every replica's)."""
    hop_records: List[Dict] = []
    by_span: Dict[Tuple[int, str], Dict] = {}
    by_parent: Dict[Tuple[int, str], List[Dict]] = {}
    flight: List[Dict] = []
    n_files = 0
    for kind, path in _iter_artifact_files(root_dir):
        n_files += 1
        if kind == "flight":
            try:
                with open(path, "r", encoding="utf-8") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            for rec in doc.get("records", []):
                if rec.get("trace") == trace_id:
                    flight.append({**rec, "source": path})
            continue
        # one file in memory at a time; its span index is kept ONLY
        # when this file contributed a hop that references a
        # process-local subtree — a fleet export dir also holds long
        # training histories whose spans a single-trace lookup must
        # not retain
        records = _read_jsonl(path)
        matched = []
        needs_index = False
        for rec in records:
            if rec.get("trace") == trace_id:
                matched.append({**rec, "source": path})
                if rec.get("hop") and rec.get("span"):
                    needs_index = True
        hop_records.extend(matched)
        if not needs_index:
            continue
        for rec in records:
            if rec.get("type") != "span_end" or rec.get("hop"):
                # hop records carry the ENCLOSING span's id in `span`;
                # indexing them under it would mislabel the subtree
                # root whenever the real span_end never landed (a
                # SIGKILL mid-batch — the forensics case)
                continue
            pid = rec.get("pid")
            if rec.get("span"):
                by_span[(pid, rec["span"])] = rec
            if rec.get("parent"):
                by_parent.setdefault(
                    (pid, rec["parent"]), []
                ).append(rec)

    # one node per hop (tsid); the primary record is the outermost
    # span_end in the hop (max dur) — every record written under one
    # installed context shares the tsid
    groups: Dict[str, List[Dict]] = {}
    for rec in hop_records:
        tsid = rec.get("tsid")
        if tsid:
            groups.setdefault(tsid, []).append(rec)

    nodes: Dict[str, Dict] = {}
    for tsid, recs in groups.items():
        span_ends = [r for r in recs if r.get("type") == "span_end"]
        pool = span_ends or recs
        primary = max(pool, key=lambda r: float(r.get("dur") or 0.0))
        node = {
            "tsid": tsid,
            "tpid": primary.get("tpid"),
            "name": primary.get("name"),
            "pid": primary.get("pid"),
            "wall": primary.get("wall"),
            "dur": primary.get("dur"),
            "attrs": primary.get("attrs") or {},
            "records": len(recs),
            "children": [],
            "process_spans": [],
        }
        # a batch_item hop carries the worker's enclosing serve_batch
        # span id in its process-local `span` field — expand that
        # subtree so "batcher → engine" is visible per trace
        if primary.get("name") == "batch_item" and primary.get("span"):
            node["process_spans"] = _expand_process_subtree(
                primary["span"], primary.get("pid"), by_parent, by_span
            )
        nodes[tsid] = node

    roots: List[Dict] = []
    for node in nodes.values():
        parent = nodes.get(node["tpid"]) if node["tpid"] else None
        if parent is not None:
            parent["children"].append(node)
        else:
            roots.append(node)
    for node in nodes.values():
        node["children"].sort(key=lambda n: n.get("wall") or 0.0)
    roots.sort(key=lambda n: n.get("wall") or 0.0)

    return {
        "trace_id": trace_id,
        "files_scanned": n_files,
        "hop_records": len(hop_records),
        "processes": sorted(
            {n["pid"] for n in nodes.values() if n.get("pid")}
        ),
        "roots": roots,
        "flight": sorted(flight, key=lambda r: r.get("wall", 0.0)),
    }


def _fmt_dur(dur) -> str:
    if dur is None:
        return "?"
    dur = float(dur)
    return f"{dur * 1e3:.1f}ms" if dur < 1 else f"{dur:.2f}s"


def _format_node(node: Dict, indent: int, t0: float, lines: List[str],
                 process_level: bool = False) -> None:
    attrs = node.get("attrs") or {}
    shown = " ".join(
        f"{k}={attrs[k]}" for k in sorted(attrs)
        if isinstance(attrs[k], (str, int, float, bool))
    )
    wall = node.get("wall")
    offset = f"+{(wall - t0) * 1e3:.1f}ms" if wall is not None else "?"
    marker = "· " if process_level else ""
    lines.append(
        f"{'  ' * indent}{marker}{node.get('name')} "
        f"[pid {node.get('pid')}] {offset} dur={_fmt_dur(node.get('dur'))}"
        + (f"  {shown}" if shown else "")
    )
    for sub in node.get("process_spans", []):
        _format_node(sub, indent + 1, t0, lines, process_level=True)
    for child in node.get("children", []):
        _format_node(child, indent + 1, t0, lines, process_level)


def format_trace(doc: Dict) -> str:
    """Human-readable tree for ``cli.obs trace``."""
    lines = [
        f"trace {doc['trace_id']}: {doc['hop_records']} record(s) across "
        f"{len(doc['processes'])} process(es) "
        f"({doc['files_scanned']} artifact file(s) scanned)"
    ]
    if not doc["roots"] and not doc["flight"]:
        lines.append("  (no matching records — wrong run dir, an "
                     "unsampled trace, or events not yet flushed)")
        return "\n".join(lines)
    walls = [
        n["wall"] for n in doc["roots"] if n.get("wall") is not None
    ] + [r["wall"] for r in doc["flight"] if r.get("wall") is not None]
    t0 = min(walls) if walls else 0.0
    for root in doc["roots"]:
        _format_node(root, 1, t0, lines)
    if doc["flight"]:
        lines.append("flight-recorder records:")
        for rec in doc["flight"]:
            hops = rec.get("hops") or {}
            hop_txt = " ".join(
                f"{k}={v}" for k, v in sorted(hops.items())
            )
            lines.append(
                f"  pid {rec.get('pid')} {rec.get('route')} "
                f"status={rec.get('status')} "
                f"dur={_fmt_dur(rec.get('dur_s'))}"
                + (f"  {hop_txt}" if hop_txt else "")
            )
    return "\n".join(lines)
