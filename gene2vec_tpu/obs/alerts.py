"""SLO alerting: declarative burn-rate/threshold rules over the fleet view.

The fleet telemetry plane (PR 6) computes the SLO signals — merged
availability counters, per-route latency quantiles, queue depth,
rejection rate — but nothing *watches* them; an operator has to notice a
p99 blowout by hand.  This module closes that loop:

* :class:`AlertRule` — one declarative rule, loadable from a
  ``budgets.json``-style ``alerts.json`` (``{"rules": [...]}``).  Two
  kinds:

  - ``threshold`` — a gauge selector (``fleet_queue_depth``, or a
    labeled series as ``fleet_route_p99_seconds{route=/v1/similar}``)
    compared against ``value`` with ``op``; hysteresis via
    ``clear_value`` (the condition must drop past it, and STAY there
    for ``clear_for_s``, before the alert clears);
  - ``burn_rate`` — an error fraction derived from a cumulative
    good/total counter pair (``fleet_ok`` / ``fleet_responses``),
    evaluated over a SHORT and a LONG window simultaneously: both
    windows' bad fraction must exceed ``max_bad_frac``, so a brief blip
    cannot fire (long window) and a real incident is seen quickly
    (short window).  Counter resets (a restarted replica zeroing its
    counters) are rebased exactly like the aggregator rebases its fleet
    sums, so a reset can never fake a burn-rate spike.

* ``for_s`` debounces firing: the condition must hold continuously for
  at least ``for_s`` (boundary inclusive) before the rule transitions
  to ``firing``.
* :class:`AlertEvaluator` — streaming evaluation, fed one snapshot per
  :class:`~gene2vec_tpu.obs.aggregate.FleetAggregator` scrape tick (the
  evaluator never touches the serve path; alerting costs zero per
  request).  State is exported as ``fleet_alert_active{rule=}`` /
  ``fleet_alert_transitions_total{rule=,to=}`` on the fleet view and
  every transition is appended to ``alerts.jsonl`` in the fleet run
  dir; a transition to ``firing`` invokes ``on_fire`` (the incident
  manager, :mod:`gene2vec_tpu.obs.incident`).
* :class:`RateLimiter` — the ONE limiter shared by the flight
  recorder's 5xx-burst dumps and rule-triggered incident bundles, so a
  flapping rule plus an error storm cannot multiply disk writes past
  one budget.

Staleness guard: the aggregator stamps ``_fresh_targets`` (replicas
that answered THIS scrape) into every snapshot; a rule whose
``min_fresh_targets`` is not met is **held** — no state transition, no
timer progress — so rules never evaluate (or clear on) frozen data
(docs/OBSERVABILITY.md#alerting).

``python -m gene2vec_tpu.cli.obs alerts <run_dir>`` renders the
transition timeline.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import sys
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

ALERTS_LOG_NAME = "alerts.jsonl"

RULE_KINDS = ("threshold", "burn_rate")
_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}

#: alert states (the full machine: inactive -> pending -> firing -> inactive)
INACTIVE, PENDING, FIRING = "inactive", "pending", "firing"


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative rule.  ``metric`` selectors address the
    aggregator's snapshot keys: a bare name (``fleet_queue_depth``) or
    ``name{label=value}`` for a labeled series
    (``fleet_route_p99_seconds{route=/v1/similar}``)."""

    name: str
    kind: str = "threshold"          # threshold | burn_rate
    severity: str = "warn"           # free-form; "page"/"warn" by convention
    # -- threshold rules --------------------------------------------------
    metric: str = ""
    op: str = ">"
    value: float = 0.0
    # hysteresis: while firing, the value must cross BACK past
    # clear_value (default: value) and stay there for clear_for_s
    clear_value: Optional[float] = None
    # -- burn-rate rules --------------------------------------------------
    good: str = ""                   # cumulative "success" counter
    total: str = ""                  # cumulative "all events" counter
    max_bad_frac: float = 0.02       # (Δtotal-Δgood)/Δtotal ceiling
    short_window_s: float = 30.0
    long_window_s: float = 300.0
    min_count: float = 20.0          # Δtotal below this = no evidence
    # -- shared -----------------------------------------------------------
    for_s: float = 0.0               # debounce before firing (inclusive)
    clear_for_s: float = 30.0        # hysteresis hold before clearing
    # hold the rule when fewer replicas answered the current scrape;
    # set 0 for rules whose inputs are proxy-local counters (the
    # availability pair) — those stay fresh when every scrape fails
    min_fresh_targets: int = 1

    def validate(self) -> None:
        if not self.name:
            raise ValueError("alert rule needs a non-empty name")
        if self.kind not in RULE_KINDS:
            raise ValueError(
                f"rule {self.name!r}: kind must be one of {RULE_KINDS}, "
                f"got {self.kind!r}"
            )
        if self.kind == "threshold":
            if not self.metric:
                raise ValueError(
                    f"threshold rule {self.name!r} needs a 'metric'"
                )
            if self.op not in _OPS:
                raise ValueError(
                    f"rule {self.name!r}: op must be one of "
                    f"{sorted(_OPS)}, got {self.op!r}"
                )
        else:
            if not self.good or not self.total:
                raise ValueError(
                    f"burn_rate rule {self.name!r} needs 'good' and "
                    "'total' counter names"
                )
            if self.short_window_s <= 0 or (
                self.long_window_s < self.short_window_s
            ):
                raise ValueError(
                    f"rule {self.name!r}: need 0 < short_window_s <= "
                    "long_window_s"
                )
        if self.for_s < 0 or self.clear_for_s < 0:
            raise ValueError(
                f"rule {self.name!r}: for_s/clear_for_s must be >= 0"
            )


def parse_rules(doc: Dict) -> List[AlertRule]:
    """``{"rules": [...]}`` (an ``alerts.json`` document) → validated
    rules.  Unknown fields and duplicate names are errors — a typo'd
    threshold key must not silently produce a rule that never fires."""
    raw = doc.get("rules")
    if not isinstance(raw, list) or not raw:
        raise ValueError("alert rules document needs a non-empty 'rules' list")
    known = {f.name for f in dataclasses.fields(AlertRule)}
    rules: List[AlertRule] = []
    seen = set()
    for i, entry in enumerate(raw):
        if not isinstance(entry, dict):
            raise ValueError(f"rules[{i}] must be an object")
        unknown = set(entry) - known
        if unknown:
            raise ValueError(
                f"rules[{i}] ({entry.get('name', '?')!r}): unknown "
                f"field(s) {sorted(unknown)}"
            )
        rule = AlertRule(**entry)
        rule.validate()
        if rule.name in seen:
            raise ValueError(f"duplicate rule name {rule.name!r}")
        seen.add(rule.name)
        rules.append(rule)
    return rules


def load_rules(path: str) -> List[AlertRule]:
    with open(path, "r", encoding="utf-8") as f:
        return parse_rules(json.load(f))


def default_rules() -> List[AlertRule]:
    """The rules ``cli.fleet`` ships by default — one per SLO signal the
    aggregator computes (availability, route p99, rejection rate, queue
    depth).  Thresholds are the docs/SERVING.md capacity-planning
    values; override with ``--alert-rules <file>``."""
    return [
        AlertRule(
            name="availability-burn", kind="burn_rate", severity="page",
            good="fleet_ok", total="fleet_responses",
            max_bad_frac=0.02, short_window_s=30.0, long_window_s=300.0,
            min_count=20.0, for_s=0.0, clear_for_s=60.0,
            # the burn pair is PROXY-local (forwarded-response
            # counters), not replica-scraped: it stays perfectly fresh
            # during exactly the every-replica-wedged outage that
            # zeroes _fresh_targets, so the staleness hold must not
            # silence the page
            min_fresh_targets=0,
        ),
        AlertRule(
            name="route-p99", kind="threshold", severity="warn",
            metric="fleet_route_p99_seconds{route=/v1/similar}",
            # 0.5s sits an order of magnitude above the measured serve
            # p99 (BENCH_SERVE_r11: 0.8 ms single replica) yet clear of
            # the one-off jit-compile observations a cold replica's
            # cumulative histogram carries
            op=">", value=0.5, clear_value=0.25,
            for_s=15.0, clear_for_s=60.0,
        ),
        AlertRule(
            name="degraded-burn", kind="burn_rate", severity="warn",
            # sharded-fleet recall degradation (serve/shardgroup.py):
            # a response assembled from a PARTIAL shard gather is a
            # 200, so the availability burn never sees it — this rule
            # pages on the complete-answer fraction instead.  The
            # counter pair is proxy-local like availability-burn, so
            # the staleness hold must not silence it; on an unsharded
            # fleet fleet_degraded stays 0 and the rule never fires.
            good="fleet_undegraded", total="fleet_responses",
            max_bad_frac=0.05, short_window_s=30.0, long_window_s=300.0,
            min_count=20.0, for_s=0.0, clear_for_s=60.0,
            min_fresh_targets=0,
        ),
        AlertRule(
            name="shard-redundancy-lost", kind="threshold",
            severity="page",
            # replicated-shard fleets (--replicas-per-shard >= 2): some
            # shard's live replica count dropped to 1 (or 0) — ONE more
            # failure costs that shard's row fraction of recall.  This
            # is the page that precedes the degraded-burn page: fire
            # immediately (redundancy is already gone), clear only
            # after the supervisor re-admits a sibling and holds it.
            # The gauge counts shards below their configured redundancy
            # and exists only on sharded fleets — elsewhere the
            # selector is absent and the rule holds forever.
            metric="fleet_shards_redundancy_lost",
            op=">", value=0.0, for_s=0.0, clear_for_s=10.0,
            # supervisor-truth via the proxy process, not a replica
            # scrape: stays fresh during exactly the all-replicas-down
            # window it pages on
            min_fresh_targets=0,
        ),
        AlertRule(
            name="rejection-rate", kind="threshold", severity="warn",
            metric="fleet_rejection_rate",
            op=">", value=0.05, clear_value=0.01,
            for_s=5.0, clear_for_s=60.0,
        ),
        AlertRule(
            name="model-staleness", kind="threshold", severity="warn",
            # continuous-learning freshness (docs/CONTINUOUS.md): the
            # oldest served artifact across the fleet.  Two days is
            # deliberately generous — the loop retrains on study-batch
            # cadence, and a fleet quietly pinned to an old iteration
            # (every candidate quarantined, promotion wedged) must
            # FIRE, not linger; override per deployment cadence.
            metric="fleet_model_age_seconds_max",
            op=">", value=2 * 86400.0, clear_value=86400.0,
            for_s=60.0, clear_for_s=60.0,
        ),
        AlertRule(
            name="model-iteration-skew", kind="threshold",
            severity="warn",
            # replicas serving DIFFERENT iterations: normal for the
            # seconds a swap wave takes, never for minutes — a wedged
            # promotion (one replica quarantined its candidate, the
            # rest flipped) is exactly this signal held high
            metric="fleet_model_iteration_skew",
            op=">", value=0.0, for_s=120.0, clear_for_s=30.0,
        ),
        AlertRule(
            name="catalog-model-staleness", kind="threshold",
            severity="warn",
            # multi-model catalog fleets (serve/catalog.py): COUNT of
            # models whose freshest replica serves an artifact older
            # than the aggregator's model_stale_after_s.  Distinct from
            # model-staleness above, which watches the single oldest
            # artifact fleet-wide: in a catalog, one cold rarely-
            # retrained model would hold that rule firing forever while
            # a genuinely wedged sibling hides behind it — this rule
            # fires per-model, on the count.  The gauge exists only on
            # catalog fleets (mirrors shard-redundancy-lost) —
            # elsewhere the selector is absent and the rule holds.
            metric="fleet_models_stale",
            op=">", value=0.0, for_s=60.0, clear_for_s=60.0,
        ),
        AlertRule(
            name="queue-depth", kind="threshold", severity="warn",
            metric="fleet_queue_depth",
            op=">", value=192.0, clear_value=64.0,
            for_s=5.0, clear_for_s=60.0,
        ),
        AlertRule(
            name="jit-recompile-storm", kind="threshold",
            severity="warn",
            # compiles observed fleet-wide during the last scrape tick
            # (aggregate.py sums the replicas' jit_compile_events_total
            # counters and deltas them per tick).  A warm bucketed
            # engine compiles NOTHING in steady state — every padded
            # shape is in the jit cache — so sustained nonzero deltas
            # mean a shape leak or cache churn eating serve ticks
            # (the hazard class graftcheck's hlo-cache-stability pass
            # gates statically; this is the live-fleet view).  for_s
            # spans the legitimate compile burst of a cold replica or
            # an index-mode rollout warming its buckets.
            metric="fleet_jit_compile_delta",
            op=">", value=0.0, for_s=30.0, clear_for_s=60.0,
        ),
    ]


class RateLimiter:
    """Shared dump/bundle budget: at most one event per ``key`` per
    ``min_interval_s`` AND at most ``max_per_window`` events across ALL
    keys per ``window_s``.  The flight recorder's 5xx-burst dumps and
    the incident manager's bundles consult the SAME instance in the
    proxy process, so an error storm plus a flapping rule share one
    disk-write budget instead of multiplying each other."""

    def __init__(
        self,
        min_interval_s: float = 30.0,
        max_per_window: int = 8,
        window_s: float = 3600.0,
        clock=time.monotonic,
    ):
        self.min_interval_s = float(min_interval_s)
        self.max_per_window = int(max_per_window)
        self.window_s = float(window_s)
        self._clock = clock
        self._events: Deque[float] = collections.deque()
        self._last: Dict[str, float] = {}
        self.denied = 0
        self._lock = threading.Lock()

    def allow(self, key: str) -> bool:
        now = self._clock()
        with self._lock:
            while self._events and self._events[0] <= now - self.window_s:
                self._events.popleft()
            if len(self._events) >= self.max_per_window:
                self.denied += 1
                return False
            last = self._last.get(key)
            if last is not None and now - last < self.min_interval_s:
                self.denied += 1
                return False
            self._last[key] = now
            self._events.append(now)
            return True


class _RuleState:
    """Mutable evaluation state for one rule."""

    __slots__ = (
        "state", "pending_since", "clear_since", "value",
        "samples", "last_good", "last_total", "acc_good", "acc_total",
        "held",
    )

    def __init__(self):
        self.state = INACTIVE
        self.pending_since: Optional[float] = None
        self.clear_since: Optional[float] = None
        self.value: Optional[float] = None
        # burn-rate: reset-rebased cumulative (t, good, total) samples
        self.samples: Deque[Tuple[float, float, float]] = collections.deque()
        self.last_good: Optional[float] = None
        self.last_total: Optional[float] = None
        self.acc_good = 0.0
        self.acc_total = 0.0
        self.held = 0


class AlertEvaluator:
    """Streaming rule evaluation over aggregator snapshots.

    ``observe`` is called once per scrape tick with the flat snapshot
    the aggregator builds (headline gauges + labeled route quantiles +
    ``_fresh_targets``).  Transitions are appended to ``log_path``
    (``alerts.jsonl``), exported on ``registry``
    (``fleet_alert_active{rule=}``,
    ``fleet_alert_transitions_total{rule=,to=}``), and a transition to
    ``firing`` invokes ``on_fire(rule, snapshot, record)`` — which must
    not block (the fleet proxy hands it to the incident manager's
    background thread).
    """

    def __init__(
        self,
        rules: Sequence[AlertRule],
        registry=None,
        log_path: Optional[str] = None,
        on_fire: Optional[Callable[[AlertRule, Dict, Dict], None]] = None,
        clock=time.monotonic,
    ):
        names = [r.name for r in rules]
        if len(names) != len(set(names)):
            raise ValueError(f"duplicate rule names in {names}")
        self.rules = list(rules)
        self.registry = registry
        self.log_path = log_path
        self.on_fire = on_fire
        self._clock = clock
        self._states: Dict[str, _RuleState] = {
            r.name: _RuleState() for r in self.rules
        }
        self._lock = threading.Lock()
        if self.registry is not None:
            for r in self.rules:  # every rule visible from tick zero
                self.registry.gauge(
                    "fleet_alert_active", labels={"rule": r.name}
                ).set(0)

    # -- introspection -----------------------------------------------------

    def states(self) -> Dict[str, str]:
        with self._lock:
            return {name: st.state for name, st in self._states.items()}

    def firing(self) -> List[str]:
        with self._lock:
            return [
                name for name, st in self._states.items()
                if st.state == FIRING
            ]

    # -- evaluation --------------------------------------------------------

    def observe(
        self,
        snapshot: Dict[str, float],
        wall: Optional[float] = None,
        now: Optional[float] = None,
    ) -> List[Dict]:
        """Evaluate every rule against one snapshot; returns the
        transition records emitted this tick (tests assert on them)."""
        now = self._clock() if now is None else now
        wall = time.time() if wall is None else wall
        fresh = snapshot.get("_fresh_targets")
        transitions: List[Dict] = []
        fired: List[Tuple[AlertRule, Dict]] = []
        with self._lock:
            for rule in self.rules:
                st = self._states[rule.name]
                if fresh is not None and fresh < rule.min_fresh_targets:
                    # frozen data: neither fire nor clear on it — hold
                    st.held += 1
                    continue
                breach, hot, value = self._condition(rule, st, snapshot, now)
                if breach is None:
                    st.held += 1  # selector absent from this snapshot
                    continue
                st.value = value
                for rec in self._advance(rule, st, breach, hot, now, wall):
                    transitions.append(rec)
                    if rec["to"] == FIRING:
                        fired.append((rule, rec))
            if self.registry is not None:
                for rule in self.rules:
                    st = self._states[rule.name]
                    self.registry.gauge(
                        "fleet_alert_active", labels={"rule": rule.name}
                    ).set(1 if st.state == FIRING else 0)
        for rec in transitions:
            self._log(rec)
        if self.on_fire is not None:
            for rule, rec in fired:
                try:
                    self.on_fire(rule, dict(snapshot), rec)
                except Exception as e:  # alerting must outlive its sink
                    print(
                        f"alerts: on_fire({rule.name}) failed: {e!r}",
                        file=sys.stderr,
                    )
        return transitions

    def _condition(
        self, rule: AlertRule, st: _RuleState, snapshot: Dict[str, float],
        now: float,
    ):
        """(breach, still_hot, value) for one rule this tick; breach is
        None when the snapshot lacks the rule's inputs (→ hold).
        ``still_hot`` is the hysteresis condition: while firing, the
        alert only starts its clear timer once still_hot is False."""
        if rule.kind == "threshold":
            raw = snapshot.get(rule.metric)
            if raw is None:
                return None, None, None
            value = float(raw)
            cmp = _OPS[rule.op]
            clear_value = (
                rule.value if rule.clear_value is None else rule.clear_value
            )
            return cmp(value, rule.value), cmp(value, clear_value), value
        # burn_rate: rebase the cumulative pair (a restarted replica's
        # zeroed counters must never read as a negative — or a giant —
        # delta), then delta over the two windows
        g = snapshot.get(rule.good)
        t = snapshot.get(rule.total)
        if g is None or t is None:
            return None, None, None
        g, t = float(g), float(t)
        # first sample is the baseline; afterwards a value that went
        # BACKWARD is a counter reset — the raw value is the new
        # increment (the aggregator's own rebase rule)
        if st.last_good is not None:
            st.acc_good += (g - st.last_good) if g >= st.last_good else g
        if st.last_total is not None:
            st.acc_total += (t - st.last_total) if t >= st.last_total else t
        st.last_good, st.last_total = g, t
        st.samples.append((now, st.acc_good, st.acc_total))
        horizon = now - rule.long_window_s - 1.0
        while st.samples and st.samples[0][0] < horizon:
            st.samples.popleft()

        def frac_over(window_s: float) -> Optional[float]:
            # the newest sample at least window_s old (else the oldest:
            # a young series evaluates over the data it has)
            base = st.samples[0]
            for s in st.samples:
                if s[0] <= now - window_s:
                    base = s
                else:
                    break
            d_total = st.acc_total - base[2]
            if d_total < rule.min_count:
                return None  # not enough evidence either way
            d_bad = d_total - (st.acc_good - base[1])
            return max(0.0, d_bad) / d_total

        short = frac_over(rule.short_window_s)
        long_ = frac_over(rule.long_window_s)
        if short is None or long_ is None:
            return False, False, short
        breach = short > rule.max_bad_frac and long_ > rule.max_bad_frac
        # hysteresis for burn rates is the time hold (clear_for_s); the
        # hot condition is the short-window frac still over budget
        return breach, short > rule.max_bad_frac, short

    def _advance(
        self, rule: AlertRule, st: _RuleState, breach: bool, hot: bool,
        now: float, wall: float,
    ) -> List[Dict]:
        out: List[Dict] = []

        def move(to: str, **extra) -> None:
            rec = {
                "wall": wall,
                "rule": rule.name,
                "severity": rule.severity,
                "from": st.state,
                "to": to,
                "value": st.value,
                **extra,
            }
            st.state = to
            out.append(rec)
            if self.registry is not None:
                self.registry.counter(
                    "fleet_alert_transitions_total",
                    labels={"rule": rule.name, "to": to},
                ).inc()

        if st.state == INACTIVE and breach:
            st.pending_since = now
            move(PENDING)
        if st.state == PENDING:
            if not breach:
                st.pending_since = None
                move(INACTIVE)
            elif now - st.pending_since >= rule.for_s:  # boundary fires
                st.clear_since = None
                move(FIRING, for_s=rule.for_s)
        if st.state == FIRING:
            if hot:
                # hysteresis: any re-breach resets the clear timer; the
                # rule stays firing with NO flapping transitions
                st.clear_since = None
            else:
                if st.clear_since is None:
                    st.clear_since = now
                if now - st.clear_since >= rule.clear_for_s:
                    st.pending_since = None
                    st.clear_since = None
                    move(INACTIVE, cleared_after_s=rule.clear_for_s)
        return out

    def _log(self, rec: Dict) -> None:
        if not self.log_path:
            return
        try:
            with open(self.log_path, "a", encoding="utf-8") as f:
                f.write(json.dumps(rec, separators=(",", ":"),
                                   default=str) + "\n")
        except OSError as e:  # a full disk must not take alerting down
            print(f"alerts: cannot append {self.log_path}: {e!r}",
                  file=sys.stderr)


# -- timeline rendering (cli.obs alerts) --------------------------------------


def collect_transitions(root_dir: str) -> List[Dict]:
    """Every ``alerts.jsonl`` record under ``root_dir`` (a fleet run dir,
    or an export dir covering several), wall-ordered."""
    records: List[Dict] = []
    for dirpath, _, filenames in os.walk(root_dir):
        if ALERTS_LOG_NAME not in filenames:
            continue
        path = os.path.join(dirpath, ALERTS_LOG_NAME)
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn trailing line
                    rec["source"] = path
                    records.append(rec)
        except OSError:
            continue
    records.sort(key=lambda r: r.get("wall", 0.0))
    return records


def format_timeline(records: List[Dict]) -> str:
    """Human-readable alert timeline for ``cli.obs alerts``."""
    if not records:
        return "no alert transitions recorded"
    t0 = records[0].get("wall", 0.0)
    lines = [f"{len(records)} alert transition(s):"]
    active: Dict[str, str] = {}
    for rec in records:
        offset = (rec.get("wall", t0) or t0) - t0
        value = rec.get("value")
        shown = f" value={value:g}" if isinstance(value, (int, float)) else ""
        lines.append(
            f"  +{offset:8.1f}s {rec.get('to', '?').upper():8} "
            f"{rec.get('rule')} [{rec.get('severity')}]"
            f" (was {rec.get('from')}){shown}"
        )
        active[rec.get("rule", "?")] = rec.get("to", "?")
    firing = sorted(r for r, s in active.items() if s == FIRING)
    lines.append(
        "currently firing: " + (", ".join(firing) if firing else "none")
    )
    return "\n".join(lines)
