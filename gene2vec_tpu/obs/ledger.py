"""Unified bench ledger: every root bench artifact, one record schema.

The repo root accumulates one JSON artifact per benchmark family per
round — ``BENCH_r*`` (SGNS headline), ``MULTICHIP_r*``,
``BENCH_SERVE/FLEET/OBS/RESILIENCE/VIZ_CORPUS/BATCH_*``,
``MESH_SANITY_*``,
``INTRINSIC_*``, ``REAL_AUC``, ``BENCH_PERF_*`` — each with its own
shape and no index.  The ledger ingests all of them through per-family
*adapters* into one versioned record schema, renders the longitudinal
trajectory (``ledger.jsonl`` + CSV), and runs trailing-window
regression detection over the metric series:

* a **record** is ``{schema, family, source, round, created_unix,
  schema_version, legacy_unstamped, producer, headline_metric,
  metrics}`` — ``metrics`` a flat name→number map, ``round`` parsed
  from the ``_rNN`` filename suffix;
* artifacts written before this PR carry no ``schema_version`` /
  ``command`` stamp; adapters tolerate them and mark the record
  ``legacy_unstamped`` so provenance gaps are visible, not silent;
* **regression detection** compares the newest point of a configured
  metric series against the **median of the trailing window** of
  prior points (median-of-band: one outlier round cannot fake or mask
  a regression); the per-metric threshold and direction live in the
  ``perf.regression`` section of ``analysis/budgets.json`` and are
  enforced by :mod:`gene2vec_tpu.analysis.passes_perf` in the DEFAULT
  ``cli.analyze`` tier.

Every family's producer/schema/headline metric is documented in
``docs/BENCHMARKS.md``.  CLI: ``python -m gene2vec_tpu.cli.obs
ledger`` (``--check`` exits 1 on a detected regression).
"""

from __future__ import annotations

import csv
import json
import os
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

SCHEMA = "gene2vec-tpu/ledger-record/v1"

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def _num(v) -> Optional[float]:
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, (int, float)):
        return float(v)
    return None


def _put(metrics: Dict[str, float], name: str, value) -> None:
    n = _num(value)
    if n is not None:
        metrics[name] = n


def _parse_tail_json(tail: str, key: str = "metric") -> Optional[Dict]:
    """The driver-wrapped ``BENCH_r*`` files hold the bench's one stdout
    JSON line inside a captured ``tail``; find it (newest last)."""
    found = None
    for line in tail.splitlines():
        line = line.strip()
        if not (line.startswith("{") and f'"{key}"' in line):
            continue
        try:
            found = json.loads(line)
        except json.JSONDecodeError:
            continue
    return found


# -- per-family adapters -----------------------------------------------------
# Each takes the parsed source document and returns (metrics, headline).
# Adapters are defensive by contract: every field access is guarded, so
# a shape drift in one family degrades to a sparser record, never an
# ingest crash.


def _adapt_bench_sgns(doc: Dict) -> Tuple[Dict[str, float], str]:
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict):
        parsed = _parse_tail_json(doc.get("tail", "")) or {}
    m: Dict[str, float] = {}
    _put(m, "sgns_pairs_per_sec", parsed.get("value"))
    _put(m, "vs_baseline", parsed.get("vs_baseline"))
    _put(m, "vs_32thread_equiv", parsed.get("vs_32thread_equiv"))
    _put(m, "baseline_1core", parsed.get("baseline_1core"))
    quality = parsed.get("quality")
    if isinstance(quality, dict):
        _put(m, "quality_passed", quality.get("passed"))
        _put(m, "holdout_cos_auc", quality.get("holdout_cos_auc"))
    secondary = parsed.get("secondary")
    if isinstance(secondary, dict):
        for k in (
            "cbow_hs_pairs_per_sec",
            "dim512_sharded_pairs_per_sec",
            "ggipnn_pairs_per_sec",
            "shared_mode_pairs_per_sec",
            "table_bf16_pairs_per_sec",
        ):
            _put(m, k, secondary.get(k))
    _put(m, "rc", doc.get("rc"))
    return m, "sgns_pairs_per_sec"


def _adapt_multichip(doc: Dict) -> Tuple[Dict[str, float], str]:
    m: Dict[str, float] = {}
    _put(m, "multichip_ok", doc.get("ok"))
    _put(m, "multichip_skipped", doc.get("skipped"))
    _put(m, "n_devices", doc.get("n_devices"))
    _put(m, "rc", doc.get("rc"))
    return m, "multichip_ok"


def _adapt_serve(doc: Dict) -> Tuple[Dict[str, float], str]:
    m: Dict[str, float] = {}
    levels = doc.get("levels")
    if isinstance(levels, list) and levels:
        by_rate = sorted(
            (lv for lv in levels if isinstance(lv, dict)),
            key=lambda lv: _num(lv.get("offered_rps")) or 0.0,
        )
        if by_rate:
            low = by_rate[0]
            _put(m, "serve_p50_ms_min_load", low.get("p50_ms"))
            _put(m, "serve_p99_ms_min_load", low.get("p99_ms"))
            _put(m, "serve_min_load_rps", low.get("offered_rps"))
            # highest offered load that shed nothing: the measured knee
            clean = [
                lv for lv in by_rate
                if (_num(lv.get("rejection_rate")) or 0.0) == 0.0
                and (_num(lv.get("errors")) or 0.0) == 0.0
            ]
            if clean:
                _put(m, "serve_clean_capacity_rps",
                     clean[-1].get("offered_rps"))
    # capacity sections (schema_version >= 2; serve_loadgen --method/
    # --fleet era).  Older records (BENCH_SERVE_r06) predate the
    # capacity verdict entirely — they simply contribute no point to
    # the serve_capacity_rps series ("pre-capacity legacy"), which the
    # trajectory rules treat as a shorter series, never an error.
    capacity = doc.get("capacity")
    if isinstance(capacity, dict):
        _put(m, "serve_capacity_rps", capacity.get("sustained_rps"))
        _put(m, "serve_capacity_p99_ms", capacity.get("p99_ms"))
    else:
        _put(m, "serve_pre_capacity_legacy", True)
    fleet_capacity = doc.get("fleet_capacity")
    if isinstance(fleet_capacity, dict):
        _put(m, "serve_fleet_capacity_rps",
             fleet_capacity.get("sustained_rps"))
        _put(m, "serve_fleet_capacity_p99_ms",
             fleet_capacity.get("p99_ms"))
    return m, "serve_p50_ms_min_load"


def _adapt_fleet(doc: Dict) -> Tuple[Dict[str, float], str]:
    m: Dict[str, float] = {}
    fleet = doc.get("fleet")
    if isinstance(fleet, dict):
        _put(m, "fleet_availability", fleet.get("availability"))
        _put(m, "fleet_retry_amplification", fleet.get("retry_amplification"))
        _put(m, "fleet_wrong_answers", fleet.get("wrong_answers"))
        _put(m, "fleet_mixed_iteration_answers",
             fleet.get("mixed_iteration_answers"))
        _put(m, "fleet_requests", fleet.get("requests"))
    _put(m, "passed", doc.get("passed"))
    return m, "fleet_availability"


def _adapt_obs_trace(doc: Dict) -> Tuple[Dict[str, float], str]:
    m: Dict[str, float] = {}
    ov = doc.get("trace_overhead")
    if isinstance(ov, dict):
        _put(m, "trace_p50_regression_frac", ov.get("regression_frac"))
        _put(m, "trace_p50_untraced_ms", ov.get("p50_untraced_ms"))
        _put(m, "trace_p50_traced_ms", ov.get("p50_traced_ms"))
    return m, "trace_p50_regression_frac"


def _adapt_resilience(doc: Dict) -> Tuple[Dict[str, float], str]:
    m: Dict[str, float] = {}
    _put(m, "chaos_passed", doc.get("passed"))
    _put(m, "chaos_wall_seconds", doc.get("wall_seconds"))
    phases = doc.get("phases")
    if isinstance(phases, dict):
        async_ov = phases.get("async_overhead")
        if isinstance(async_ov, dict):
            _put(m, "async_ckpt_overhead_fraction",
                 async_ov.get("async_overhead_fraction"))
            _put(m, "sync_ckpt_overhead_fraction",
                 async_ov.get("sync_overhead_fraction"))
    return m, "chaos_passed"


def _adapt_mesh_sanity(doc: Dict) -> Tuple[Dict[str, float], str]:
    m: Dict[str, float] = {}
    rows = doc.get("rows")
    if isinstance(rows, list) and rows:
        rows = [r for r in rows if isinstance(r, dict)]
        parity = [r.get("loss_parity") for r in rows if "loss_parity" in r]
        if parity:
            _put(m, "mesh_loss_parity", all(bool(p) for p in parity))
        top = max(rows, key=lambda r: _num(r.get("devices")) or 0.0)
        _put(m, "mesh_max_devices", top.get("devices"))
        _put(m, "mesh_pairs_per_sec_max_devices", top.get("pairs_per_sec"))
        _put(m, "mesh_overhead_factor_max_devices",
             top.get("overhead_factor"))
    return m, "mesh_loss_parity"


def _adapt_intrinsic(doc: Dict) -> Tuple[Dict[str, float], str]:
    m: Dict[str, float] = {}
    _put(m, "intrinsic_target_func_ratio",
         doc.get("trained_target_func_ratio"))
    trained = doc.get("trained")
    if isinstance(trained, dict):
        _put(m, "intrinsic_intra_set_cos",
             trained.get("intra_set_cos_real_sets"))
    return m, "intrinsic_target_func_ratio"


def _adapt_real_auc(doc: Dict) -> Tuple[Dict[str, float], str]:
    m: Dict[str, float] = {}
    holdout = doc.get("holdout")
    if isinstance(holdout, dict):
        cos = holdout.get("cosine_auc")
        if isinstance(cos, dict):
            _put(m, "holdout_cos_auc_in_vocab", cos.get("in_vocab_pairs"))
            _put(m, "holdout_cos_auc_all_pairs", cos.get("all_pairs"))
        _put(m, "ggipnn_auc", holdout.get("ggipnn_auc"))
        _put(m, "ggipnn_accuracy", holdout.get("ggipnn_accuracy"))
    return m, "holdout_cos_auc_in_vocab"


def _adapt_viz_corpus(doc: Dict) -> Tuple[Dict[str, float], str]:
    m: Dict[str, float] = {}
    tsne = doc.get("tsne_24k")
    if isinstance(tsne, dict):
        _put(m, "tsne_tpu_iters_per_sec", tsne.get("tpu_iters_per_sec"))
    umap = doc.get("umap_24k")
    if isinstance(umap, dict):
        _put(m, "umap_iters_per_sec", umap.get("iters_per_sec"))
    corr = doc.get("corpus_corr")
    if isinstance(corr, dict):
        _put(m, "corpus_corr_tpu_vs_pandas", corr.get("tpu_vs_pandas"))
    return m, "tsne_tpu_iters_per_sec"


def _adapt_perf(doc: Dict) -> Tuple[Dict[str, float], str]:
    m: Dict[str, float] = {}
    _put(m, "timeline_regression_frac", doc.get("regression_frac"))
    _put(m, "rate_timeline_on", doc.get("rate_timeline_on"))
    _put(m, "rate_timeline_off", doc.get("rate_timeline_off"))
    return m, "timeline_regression_frac"


def _adapt_alerts(doc: Dict) -> Tuple[Dict[str, float], str]:
    """BENCH_ALERTS_* (chaos_drill.py --only alerts --alerts-out): the
    detection loop's headline is how fast the right rule fired after
    the injected fault; the ``perf.regression`` rules watch it so
    detection latency cannot silently erode."""
    m: Dict[str, float] = {}
    section = doc.get("alerts")
    section = section if isinstance(section, dict) else {}
    _put(m, "alert_detection_latency_s",
         section.get("detection_latency_s"))
    _put(m, "alert_warmup_false_positives",
         section.get("warmup_false_positives"))
    _put(m, "alert_bundle_verified", section.get("bundle_verified"))
    _put(m, "alert_bundle_trace_through_faulty_replica",
         section.get("bundle_trace_through_faulty_replica"))
    _put(m, "alert_bundle_traces", section.get("bundle_traces"))
    _put(m, "passed", doc.get("passed"))
    return m, "alert_detection_latency_s"


def _adapt_autoscale(doc: Dict) -> Tuple[Dict[str, float], str]:
    """BENCH_AUTOSCALE_* (chaos_drill.py --only autoscale
    --autoscale-out): the elastic fleet's headline is how fast the
    scaler noticed a load ramp (in scrape ticks) plus whether tenant
    isolation held; the ``perf.regression`` rules watch both so
    elasticity wins cannot silently erode."""
    m: Dict[str, float] = {}
    section = doc.get("autoscale")
    section = section if isinstance(section, dict) else {}
    for key in (
        "scale_up_detection_ticks",
        "victim_tenant_availability",
        "dropped_answers",
        "wrong_answers",
        "mixed_iteration_answers",
        "steady_state_scale_actions",
        "scale_up_completed_s",
        "scale_down_s",
        "drain_timeouts",
    ):
        _put(m, key, section.get(key))
    _put(m, "passed", doc.get("passed"))
    return m, "scale_up_detection_ticks"


def _adapt_ann(doc: Dict) -> Tuple[Dict[str, float], str]:
    """BENCH_ANN_* (bench.py --ann): per-index-mode recall@10 vs the
    exact numpy oracle, p50/p99 at the 1M-row synthetic geometry, and
    the IVF-vs-exact gain factors the ``ann.recall`` budget gates."""
    m: Dict[str, float] = {}
    modes = doc.get("modes")
    modes = modes if isinstance(modes, dict) else {}
    for mode in ("exact", "quant", "ivf"):
        section = modes.get(mode)
        if not isinstance(section, dict):
            continue
        _put(m, f"ann_{mode}_recall_at_10", section.get("recall_at_10"))
        _put(m, f"ann_{mode}_p50_ms", section.get("p50_ms"))
        _put(m, f"ann_{mode}_p99_ms", section.get("p99_ms"))
        _put(m, f"ann_{mode}_bytes_per_query",
             section.get("bytes_per_query"))
    ivf = modes.get("ivf")
    if isinstance(ivf, dict):
        # the two headline series the perf.regression rules watch
        _put(m, "ann_recall_at_10", ivf.get("recall_at_10"))
        _put(m, "ann_p99_ms_1m", ivf.get("p99_ms"))
        _put(m, "ann_p99_speedup_vs_exact",
             ivf.get("p99_speedup_vs_exact"))
        _put(m, "ann_bytes_reduction_vs_exact",
             ivf.get("bytes_reduction_vs_exact"))
    real = doc.get("real_table")
    if isinstance(real, dict):
        _put(m, "ann_real_recall_at_10_ivf", real.get("recall_at_10_ivf"))
        _put(m, "ann_real_recall_at_10_quant",
             real.get("recall_at_10_quant"))
    return m, "ann_recall_at_10"


def _adapt_shard(doc: Dict) -> Tuple[Dict[str, float], str]:
    """BENCH_SHARD_* (chaos_drill.py --only shard --shard-out): the
    fleet-sharded index story in two halves — the 10M-row scatter-merge
    bench (recall@10 vs the exact oracle with all shards up, degraded
    recall with one shard removed, merge p99) and the HTTP chaos drill
    (availability + answer integrity under a SIGKILLed shard and a
    swap-under-load).  The ``perf.regression`` rules watch the recall
    and p99 headline series."""
    m: Dict[str, float] = {}
    section = doc.get("shard")
    section = section if isinstance(section, dict) else {}
    bench = section.get("bench")
    if isinstance(bench, dict):
        _put(m, "shard_recall_at_10", bench.get("recall_at_10"))
        _put(m, "shard_degraded_recall_at_10",
             bench.get("degraded_recall_at_10"))
        _put(m, "shard_dead_row_fraction",
             bench.get("dead_shard_row_fraction"))
        _put(m, "shard_p50_ms", bench.get("p50_ms"))
        _put(m, "shard_p99_ms_10m", bench.get("p99_ms"))
        _put(m, "shard_rows", bench.get("rows"))
        _put(m, "shard_count", bench.get("shards"))
    drill = section.get("drill")
    if isinstance(drill, dict):
        _put(m, "shard_availability", drill.get("availability"))
        _put(m, "shard_wrong_answers", drill.get("wrong_answers"))
        _put(m, "shard_mixed_iteration_answers",
             drill.get("mixed_iteration_answers"))
        _put(m, "shard_server_5xx", drill.get("server_5xx"))
        _put(m, "shard_retry_amplification",
             drill.get("retry_amplification"))
        # replicated-shard failover scenario (PR 15): a dead sibling
        # must cost zero degraded answers and bounded latency
        fo = drill.get("failover")
        if isinstance(fo, dict):
            _put(m, "failover_degraded_responses",
                 fo.get("degraded_responses"))
            _put(m, "failover_p99_ms", fo.get("p99_ms"))
            _put(m, "failover_availability", fo.get("availability"))
    _put(m, "passed", doc.get("passed"))
    return m, "shard_recall_at_10"


def _adapt_loop(doc: Dict) -> Tuple[Dict[str, float], str]:
    """BENCH_LOOP_* (chaos_drill.py --only loop --loop-out): the
    continuous-learning cycle end to end — ingest→promoted wall time,
    shadow answer churn / p99 delta between the live and candidate
    arms, promotion decision latency, and the zero-wrong/zero-mixed
    answer integrity held through a SIGKILL in every loop state.  The
    ``perf.regression`` rules watch churn and cycle wall time."""
    m: Dict[str, float] = {}
    section = doc.get("loop")
    section = section if isinstance(section, dict) else {}
    for key in (
        "answer_churn",
        "shadow_p99_delta_ms",
        "ingest_to_promoted_s",
        "promotion_decision_s",
        "wrong_answers",
        "mixed_iteration_answers",
        "resume_bit_exact",
        "promoted",
        "states_killed",
        "shadow_scored",
        "quality_auc",
        "new_genes",
    ):
        _put(m, f"loop_{key}", section.get(key))
    _put(m, "passed", doc.get("passed"))
    return m, "loop_answer_churn"


def _adapt_kernels(doc: Dict) -> Tuple[Dict[str, float], str]:
    """BENCH_KERNELS_* (bench.py --kernel-profile): per-kernel roofline
    records — static XLA flops/bytes plus best observed wall and
    achieved-vs-peak utilization at the pinned recipe — flattened to
    ``kernel_<name>_*`` series, plus the profiling-overhead headline
    the ``perf.regression`` rules watch (``kernels.profile`` budget)."""
    m: Dict[str, float] = {}
    kernels = doc.get("kernels")
    kernels = kernels if isinstance(kernels, dict) else {}
    for name, rec in kernels.items():
        if not isinstance(rec, dict):
            continue
        for key in ("flops", "bytes_accessed", "wall_s", "utilization",
                    "compile_s"):
            _put(m, f"kernel_{name}_{key}", rec.get(key))
    sgns = kernels.get("sgns_train_step")
    if isinstance(sgns, dict):
        _put(m, "kernel_sgns_utilization", sgns.get("utilization"))
    overhead = doc.get("overhead")
    overhead = overhead if isinstance(overhead, dict) else {}
    _put(m, "kernel_profile_overhead_frac", overhead.get("regression_frac"))
    return m, "kernel_profile_overhead_frac"


def _adapt_batch(doc: Dict) -> Tuple[Dict[str, float], str]:
    """BENCH_BATCH_* (chaos_drill.py --only batch --batch-out): the
    offline analytics plane end to end — full-vocab kNN graph build
    through the live fleet's background lane (throughput at the paper's
    24k vocab, recall@10 vs the brute-force oracle, SIGKILL-resume
    bit-identity), sampled-query throughput against a 1M-row index,
    and the mixed-workload interactive p99 delta.  The
    ``perf.regression`` rules watch graph throughput (higher) and the
    p99-under-batch delta (lower)."""
    m: Dict[str, float] = {}
    section = doc.get("batch")
    section = section if isinstance(section, dict) else {}
    g = section.get("graph_24k")
    if isinstance(g, dict):
        _put(m, "batch_graph_rows_per_sec", g.get("rows_per_sec"))
        _put(m, "batch_graph_recall_at_10", g.get("recall_at_10"))
        _put(m, "batch_graph_rows", g.get("rows"))
        _put(m, "batch_graph_wall_s", g.get("wall_s"))
        _put(m, "batch_resume_bit_exact", g.get("resume_bit_exact"))
        _put(m, "batch_resumed_records", g.get("resumed_records"))
    g1m = section.get("graph_1m")
    if isinstance(g1m, dict):
        _put(m, "batch_graph_1m_rows_per_sec", g1m.get("rows_per_sec"))
        _put(m, "batch_graph_1m_recall_at_10", g1m.get("recall_at_10"))
        _put(m, "batch_graph_1m_rows", g1m.get("rows"))
    mixed = section.get("mixed")
    if isinstance(mixed, dict):
        _put(m, "batch_interactive_p99_baseline_ms",
             mixed.get("interactive_p99_baseline_ms"))
        _put(m, "batch_interactive_p99_under_batch_ms",
             mixed.get("interactive_p99_under_batch_ms"))
        _put(m, "batch_p99_delta_ms", mixed.get("p99_delta_ms"))
        _put(m, "batch_p99_delta_frac", mixed.get("p99_delta_frac"))
        _put(m, "batch_goodput_rows_per_sec",
             mixed.get("batch_goodput_rows_per_sec"))
    _put(m, "passed", doc.get("passed"))
    return m, "batch_graph_rows_per_sec"


def _adapt_catalog(doc: Dict) -> Tuple[Dict[str, float], str]:
    """BENCH_CATALOG_* (chaos_drill.py --only catalog --catalog-out):
    the multi-model serving plane's isolation drill — a two-model
    catalog fleet hot-swaps its default model under verified load on
    both models, then ramps the second model and proves only that
    model's pool scales.  The ``perf.regression`` rules watch verified
    availability (higher) and the per-model scale-up detection latency
    in scrape ticks (lower)."""
    m: Dict[str, float] = {}
    section = doc.get("catalog")
    section = section if isinstance(section, dict) else {}
    verified = section.get("verified")
    if isinstance(verified, dict):
        _put(m, "catalog_availability", verified.get("availability"))
        _put(m, "catalog_verified_requests", verified.get("requests"))
        _put(m, "catalog_wrong_answers", verified.get("wrong"))
        _put(m, "catalog_mixed_answers", verified.get("mixed"))
        _put(m, "catalog_cross_model_answers", verified.get("cross_model"))
    swap = section.get("swap")
    if isinstance(swap, dict):
        _put(m, "catalog_swap_visible_s", swap.get("visible_s"))
    scale = section.get("scale_up")
    if isinstance(scale, dict):
        _put(m, "catalog_scale_up_detection_ticks",
             scale.get("detection_ticks"))
        _put(m, "catalog_scale_up_completed_s", scale.get("completed_s"))
        _put(m, "catalog_cold_pool_final", scale.get("cold_pool_final"))
    _put(m, "passed", doc.get("passed"))
    return m, "catalog_availability"


#: ingest order: (compiled filename pattern, family, adapter).
#: First match wins — BENCH_PERF/SERVE/FLEET/... must precede the bare
#: BENCH_r catch-all.
ADAPTERS: Sequence[Tuple[re.Pattern, str, Callable]] = (
    (re.compile(r"^BENCH_CATALOG_\w*\.json$"), "catalog", _adapt_catalog),
    (re.compile(r"^BENCH_BATCH_\w*\.json$"), "batch", _adapt_batch),
    (re.compile(r"^BENCH_LOOP_\w*\.json$"), "loop", _adapt_loop),
    (re.compile(r"^BENCH_SHARD_\w*\.json$"), "shard", _adapt_shard),
    (re.compile(r"^BENCH_PERF_r?\d*\.json$"), "perf_timeline", _adapt_perf),
    (re.compile(r"^BENCH_ALERTS_\w*\.json$"), "alerts", _adapt_alerts),
    (re.compile(r"^BENCH_AUTOSCALE_\w*\.json$"), "autoscale",
     _adapt_autoscale),
    (re.compile(r"^BENCH_KERNELS_\w*\.json$"), "kernels", _adapt_kernels),
    (re.compile(r"^BENCH_ANN_\w*\.json$"), "ann", _adapt_ann),
    (re.compile(r"^BENCH_SERVE_\w*\.json$"), "serve_loadgen", _adapt_serve),
    (re.compile(r"^BENCH_FLEET_\w*\.json$"), "fleet_chaos", _adapt_fleet),
    (re.compile(r"^BENCH_OBS_\w*\.json$"), "obs_trace", _adapt_obs_trace),
    (re.compile(r"^BENCH_RESILIENCE_\w*\.json$"), "chaos_drill",
     _adapt_resilience),
    (re.compile(r"^BENCH_VIZ_CORPUS_\w*\.json$"), "viz_corpus",
     _adapt_viz_corpus),
    (re.compile(r"^BENCH_r\d+\.json$"), "bench_sgns", _adapt_bench_sgns),
    (re.compile(r"^MULTICHIP_r\d+\.json$"), "multichip", _adapt_multichip),
    (re.compile(r"^MESH_SANITY_\w*\.json$"), "mesh_sanity",
     _adapt_mesh_sanity),
    (re.compile(r"^INTRINSIC_\w*\.json$"), "intrinsic", _adapt_intrinsic),
    (re.compile(r"^REAL_AUC\.json$"), "real_auc", _adapt_real_auc),
)


def provenance_stamp(doc: Dict) -> Dict:
    """Stamp ``schema_version`` / ``command`` / ``created_unix`` into a
    bench or quality-eval JSON product so :func:`adapt_file` ingests it
    with provenance instead of marking it ``legacy_unstamped``.  The
    canonical implementation behind ``bench.py``'s ``bench_stamp()`` —
    one stamping convention, wherever the artifact is produced
    (bench.py, scripts/run_intrinsic.py, scripts/run_real_auc.py,
    ``cli.evaluate --json``)."""
    import sys
    import time

    doc.setdefault("schema_version", 1)
    doc.setdefault("command", " ".join([sys.executable, *sys.argv]))
    doc.setdefault("created_unix", time.time())
    return doc


def match_family(filename: str) -> Optional[Tuple[str, Callable]]:
    for pattern, family, adapter in ADAPTERS:
        if pattern.match(filename):
            return family, adapter
    return None


def parse_round(filename: str) -> Optional[int]:
    m = _ROUND_RE.search(filename)
    return int(m.group(1)) if m else None


def adapt_file(path: str) -> Optional[Dict]:
    """One artifact → one ledger record, or None when the filename
    matches no family.  Unreadable/unparseable files yield a record
    with an ``error`` field (the trajectory shows the hole) instead of
    crashing the ingest."""
    name = os.path.basename(path)
    matched = match_family(name)
    if matched is None:
        return None
    family, adapter = matched
    record: Dict = {
        "schema": SCHEMA,
        "family": family,
        "source": name,
        "round": parse_round(name),
    }
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError(f"top-level JSON is {type(doc).__name__}")
    except (OSError, ValueError) as e:
        record.update({
            "error": str(e), "metrics": {}, "headline_metric": None,
            "legacy_unstamped": True,
        })
        return record
    try:
        metrics, headline = adapter(doc)
    except Exception as e:  # adapter bug ≠ ingest crash
        record.update({
            "error": f"adapter failed: {e}", "metrics": {},
            "headline_metric": None,
        })
        metrics, headline = {}, None
    # provenance stamps live at the top level of directly-written
    # artifacts; the BENCH_r* driver wrapper stores the bench's own
    # stdout document under "parsed", so fall back one level — the
    # stamp must survive the wrapping or every future headline round
    # would still read as legacy
    stamp_docs = [doc]
    if isinstance(doc.get("parsed"), dict):
        stamp_docs.append(doc["parsed"])

    def stamped(key, want):
        for d in stamp_docs:
            v = d.get(key)
            if isinstance(v, want):
                return v
        return None

    sv = stamped("schema_version", int)
    created = next(
        (v for d in stamp_docs
         if (v := _num(d.get("created_unix"))) is not None),
        None,
    )
    if created is None:
        try:
            created = os.path.getmtime(path)
        except OSError:
            created = None
    record.update({
        "created_unix": created,
        "schema_version": sv,
        "source_schema": stamped("schema", str),
        # artifacts produced before the provenance stamps: visible, not
        # silent (the stamping satellite's contract)
        "legacy_unstamped": sv is None,
        "producer": stamped("command", str),
        "headline_metric": headline,
        "metrics": metrics,
    })
    return record


def ingest_root(root: str) -> List[Dict]:
    """Adapt every matching artifact directly under ``root`` (the repo
    root by convention), ordered by (round, created) so the series read
    oldest → newest."""
    records: List[Dict] = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return records
    for name in names:
        path = os.path.join(root, name)
        if not os.path.isfile(path):
            continue
        rec = adapt_file(path)
        if rec is not None:
            records.append(rec)
    records.sort(key=lambda r: (
        r["family"],
        r["round"] if r["round"] is not None else -1,
        r.get("created_unix") or 0.0,
        r["source"],
    ))
    return records


# -- persistence -------------------------------------------------------------


def write_jsonl(records: List[Dict], path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        for rec in records:
            f.write(json.dumps(rec, separators=(",", ":"), default=str)
                    + "\n")


def write_csv(records: List[Dict], path: str) -> None:
    """Flat CSV: fixed identity columns + the union of metric names."""
    metric_names = sorted({
        name for rec in records for name in rec.get("metrics", {})
    })
    head = [
        "family", "source", "round", "created_unix", "schema_version",
        "legacy_unstamped", "headline_metric", "headline_value", "error",
    ]
    with open(path, "w", encoding="utf-8", newline="") as f:
        w = csv.writer(f)
        w.writerow(head + metric_names)
        for rec in records:
            metrics = rec.get("metrics", {})
            headline = rec.get("headline_metric")
            row = [
                rec.get("family"), rec.get("source"), rec.get("round"),
                rec.get("created_unix"), rec.get("schema_version"),
                rec.get("legacy_unstamped"), headline,
                metrics.get(headline) if headline else None,
                rec.get("error", ""),
            ]
            w.writerow(row + [metrics.get(n, "") for n in metric_names])


# -- regression detection ----------------------------------------------------


def series(records: List[Dict], metric: str) -> List[Tuple[str, float]]:
    """(source, value) points for one metric, in ledger (oldest→newest)
    order."""
    out = []
    for rec in records:
        v = rec.get("metrics", {}).get(metric)
        if v is not None:
            out.append((rec["source"], float(v)))
    return out


def _median(values: Sequence[float]) -> float:
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return ordered[mid]
    return 0.5 * (ordered[mid - 1] + ordered[mid])


def detect_regressions(records: List[Dict], rules: Dict) -> List[Dict]:
    """Trailing-window regression check per configured metric.

    ``rules`` is the ``perf.regression`` budgets section::

        {"window": 4, "min_points": 3,
         "metrics": {"sgns_pairs_per_sec":
                     {"direction": "higher", "max_regression_frac": 0.3}}}

    For each metric: the newest point is compared against the MEDIAN of
    the up-to-``window`` points before it (median-of-band: one outlier
    round cannot fake or mask a regression).  ``direction`` names which
    way is good ("higher" for throughput, "lower" for latency); a
    newest point worse than the band median by more than
    ``max_regression_frac`` regresses.  Series shorter than
    ``min_points`` (newest included) are reported ``skipped`` — gating
    them would make every new benchmark family fail until it has
    history.

    Returns one evaluation dict per configured metric with a
    ``regressed`` bool; callers (``cli.obs ledger --check``,
    ``analysis/passes_perf.py``) decide severity.
    """
    window = int(rules.get("window", 4))
    min_points = int(rules.get("min_points", 3))
    out: List[Dict] = []
    for metric, rule in (rules.get("metrics") or {}).items():
        if metric.startswith("_") or not isinstance(rule, dict):
            continue
        pts = series(records, metric)
        threshold = float(rule.get("max_regression_frac", 0.2))
        direction = str(rule.get("direction", "higher"))
        ev: Dict = {
            "metric": metric,
            "direction": direction,
            "max_regression_frac": threshold,
            "n_points": len(pts),
            "regressed": False,
        }
        if len(pts) < min_points:
            ev["skipped"] = f"needs >= {min_points} points, has {len(pts)}"
            out.append(ev)
            continue
        newest_src, newest = pts[-1]
        band = [v for _, v in pts[-1 - window:-1]]
        med = _median(band)
        ev.update({
            "newest_source": newest_src,
            "newest_value": newest,
            "band_median": med,
            "band_values": band,
        })
        if med != 0:
            delta = (
                (med - newest) / abs(med)
                if direction == "higher"
                else (newest - med) / abs(med)
            )
            ev["regression_frac"] = round(delta, 4)
            ev["regressed"] = delta > threshold
        out.append(ev)
    return out
