"""Fleet telemetry aggregation: scrape every replica, serve one view.

A fleet of N replicas exports N separate ``/metrics`` expositions;
nothing autoscaling (ROADMAP item 4) can act on lives in any single one
of them.  :class:`FleetAggregator` runs inside the front-door proxy
process, periodically scrapes every live replica's ``/metrics`` plus
the proxy's own registry, and maintains a merged **fleet-level view**
served at ``/metrics/fleet``:

* ``fleet_availability`` — ok / total over the proxy's forwarded
  responses (the client-observed number, not a replica's self-report);
* ``fleet_route_p50_seconds{route=...}`` / ``fleet_route_p99_seconds``
  — per-route latency quantiles estimated from the replicas' merged
  ``serve_route_seconds`` histogram buckets (bucket upper bounds, so
  estimates are conservative);
* ``fleet_queue_depth`` — Σ replica ``serve_queue_depth``;
* ``fleet_rejection_rate`` — Σ ``serve_rejected_total`` / Σ
  ``serve_requests_total``;
* raw sums (``fleet_requests``, ``fleet_rejected``,
  ``fleet_ok``, ``fleet_responses``) so dashboards and the
  chaos drill can do exact delta math across a load window — monotone
  series (counters, histogram buckets) are accumulated per replica
  with reset detection, so a replica dying or restarting with zeroed
  counters never makes a fleet sum go backward;
* scrape health: ``fleet_replicas_scraped``,
  ``fleet_scrape_errors_total``, and per-target
  ``fleet_scrape_staleness{target=}`` (consecutive missed scrapes; a
  target missing ``stale_after`` scrapes in a row is **stale** — its
  frozen histogram history is excluded from the per-route quantile
  estimates, and the snapshot handed to the alert evaluator carries
  ``_fresh_targets`` so rules hold instead of evaluating frozen data).

Every scrape also appends one CSV row (``fleet_telemetry.csv`` in the
fleet run dir) through the registry's CSV sink, so the load-signal
history survives the process.

The Prometheus text parser here is the escape-aware inverse of
``obs/registry.py``'s exposition (label values may contain ``\\``,
``"``, and newlines); ``tests/test_tracing.py`` round-trips them.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

from gene2vec_tpu.obs.registry import MetricsRegistry, unescape_label_value

#: canonical label-set key: sorted (k, v) tuples
LabelKey = Tuple[Tuple[str, str], ...]


@dataclasses.dataclass(frozen=True)
class Sample:
    """One exposition line: ``name{labels} value``."""

    name: str
    labels: LabelKey
    value: float

    def label(self, key: str) -> Optional[str]:
        for k, v in self.labels:
            if k == key:
                return v
        return None


def _parse_labels(body: str) -> Optional[LabelKey]:
    """Parse the inside of ``{...}`` respecting escaped quotes; None on
    malformed input (a scrape must never crash the aggregator)."""
    labels: List[Tuple[str, str]] = []
    i = 0
    n = len(body)
    while i < n:
        eq = body.find("=", i)
        if eq < 0:
            return None
        key = body[i:eq].strip().strip(",").strip()
        if not key:
            return None
        j = eq + 1
        if j >= n or body[j] != '"':
            return None
        j += 1
        raw: List[str] = []
        while j < n:
            c = body[j]
            if c == "\\" and j + 1 < n:
                raw.append(body[j:j + 2])
                j += 2
                continue
            if c == '"':
                break
            raw.append(c)
            j += 1
        if j >= n:
            return None  # unterminated value
        labels.append((key, unescape_label_value("".join(raw))))
        i = j + 1
    return tuple(sorted(labels))


def parse_prometheus(text: str) -> List[Sample]:
    """Parse a text exposition into samples, skipping comments and any
    malformed line (tolerant by design: one bad line must not discard a
    replica's whole scrape)."""
    out: List[Sample] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if "{" in line:
            brace = line.index("{")
            name = line[:brace]
            end = line.rfind("}")
            if end < brace:
                continue
            labels = _parse_labels(line[brace + 1:end])
            if labels is None:
                continue
            rest = line[end + 1:].strip()
        else:
            name, _, rest = line.partition(" ")
            labels = ()
            rest = rest.strip()
        if not name or not rest:
            continue
        value_str = rest.split()[0]
        try:
            value = float(value_str.replace("+Inf", "inf"))
        except ValueError:
            continue
        out.append(Sample(name, labels, value))
    return out


def merge_samples(
    scrapes: Sequence[Sequence[Sample]],
) -> Dict[Tuple[str, LabelKey], float]:
    """Sum samples across replicas by (name, label set) — the right
    merge for counters, cumulative histogram buckets, and additive
    gauges like queue depth."""
    merged: Dict[Tuple[str, LabelKey], float] = {}
    for samples in scrapes:
        for s in samples:
            key = (s.name, s.labels)
            merged[key] = merged.get(key, 0.0) + s.value
    return merged


def histogram_quantile(
    merged: Dict[Tuple[str, LabelKey], float],
    name: str,
    labels: LabelKey,
    q: float,
) -> Optional[float]:
    """Quantile estimate from merged cumulative ``<name>_bucket``
    samples matching ``labels`` (+ their ``le``): the smallest bucket
    upper bound whose cumulative count covers ``q`` of observations.
    A quantile landing in the ``+Inf`` bucket SATURATES to the largest
    finite bucket bound — a truthful "at least this" that keeps the
    fleet gauges moving during exactly the overload they exist to
    expose (skipping the update would freeze them at the pre-overload
    value).  None when the histogram is empty or absent."""
    buckets: List[Tuple[float, float]] = []
    for (n, lk), value in merged.items():
        if n != f"{name}_bucket":
            continue
        le = None
        rest = []
        for k, v in lk:
            if k == "le":
                le = v
            else:
                rest.append((k, v))
        if le is None or tuple(sorted(rest)) != labels:
            continue
        try:
            buckets.append((float(le.replace("+Inf", "inf")), value))
        except ValueError:
            continue
    if not buckets:
        return None
    buckets.sort()
    total = buckets[-1][1]
    if total <= 0:
        return None
    finite = [le for le, _ in buckets if math.isfinite(le)]
    target = q * total
    for le, cum in buckets:
        if cum >= target:
            if math.isfinite(le):
                return le
            break
    return max(finite) if finite else None


def histogram_routes(
    merged: Dict[Tuple[str, LabelKey], float], name: str
) -> List[LabelKey]:
    """Distinct non-``le`` label sets present for ``<name>_bucket``."""
    seen = set()
    for (n, lk), _ in merged.items():
        if n != f"{name}_bucket":
            continue
        rest = tuple(sorted((k, v) for k, v in lk if k != "le"))
        seen.add(rest)
    return sorted(seen)


def _default_fetch(url: str, timeout_s: float) -> str:
    with urllib.request.urlopen(f"{url}/metrics", timeout=timeout_s) as r:
        return r.read().decode("utf-8")


class FleetAggregator:
    """Periodic scraper + merged fleet-level metrics view.

    ``targets`` is a list of replica base URLs or a zero-arg callable
    returning the current list (the supervisor's live set).
    ``proxy_registry`` is the front door's own registry — the source of
    the client-observed availability counters.  ``fetch`` and ``clock``
    are injectable for tests.
    """

    #: replica histogram whose buckets back the per-route quantiles
    ROUTE_HISTOGRAM = "serve_route_seconds"

    def __init__(
        self,
        targets: Union[Sequence[str], Callable[[], Sequence[str]]],
        proxy_registry: Optional[MetricsRegistry] = None,
        interval_s: float = 2.0,
        csv_path: Optional[str] = None,
        fetch: Callable[[str, float], str] = _default_fetch,
        timeout_s: float = 2.0,
        evaluator=None,
        stale_after: int = 3,
        raw_window_records: int = 512,
    ):
        self._targets = targets
        self.proxy_registry = proxy_registry
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self._fetch = fetch
        #: optional obs.alerts.AlertEvaluator fed one snapshot per tick
        self.evaluator = evaluator
        #: sharded-fleet hooks (cli.fleet wires them for
        #: --shard-by-rows): ``shard_of(url) -> shard index`` maps a
        #: scrape target onto its row shard so per-shard queue depth
        #: and scatter p99 can be projected out of the merge, and
        #: ``shard_facts() -> {shard: {"up": n, "desired": r}}`` is the
        #: supervisor's redundancy view behind
        #: ``fleet_shard_replicas_up{shard=}`` and the
        #: ``shard-redundancy-lost`` alert.  Both None on an unsharded
        #: fleet — no per-shard series exist and the alert rule holds.
        self.shard_of: Optional[Callable[[str], Optional[int]]] = None
        self.shard_facts: Optional[Callable[[], Dict]] = None
        #: shards whose queue/p99 series were published last round —
        #: a shard that stops reporting (every replica down or stale)
        #: has its labeled gauges RETIRED, not frozen: a dead shard
        #: showing its last queue depth on /metrics is the stale-skew
        #: trap the model-fact gauges already guard against
        self._shard_queue_series: set = set()
        self._shard_p99_series: set = set()
        #: catalog-fleet hooks (cli.fleet wires them for --catalog):
        #: ``model_of(url) -> model name`` maps a scrape target onto
        #: its catalog model so per-model queue depth can be projected
        #: out of the merge and the iteration-skew headline can group
        #: by model (skew ACROSS models is expected — each trains on
        #: its own cadence), and ``model_pool_facts() -> {name: up}``
        #: is the supervisor's per-model redundancy view behind
        #: ``fleet_model_replicas_up{model=}``.  Both None on a
        #: single-model fleet — no per-model series exist.
        self.model_of: Optional[Callable[[str], Optional[str]]] = None
        self.model_pool_facts: Optional[Callable[[], Dict]] = None
        #: a model whose FRESHEST replica serves an artifact older than
        #: this counts into the ``fleet_models_stale`` gauge (the
        #: per-model staleness alert's input)
        self.model_stale_after_s: float = 2 * 86400.0
        self._model_queue_series: set = set()
        self._model_age_series: set = set()
        #: additional per-tick snapshot consumers, called AFTER the
        #: evaluator with the same (snapshot, wall) — the autoscaler
        #: (serve/autoscale.py ElasticController.observe) registers
        #: here.  Each observer is exception-isolated: a scaling bug
        #: must not cost a telemetry tick.
        self.observers: List[Callable[..., None]] = []
        #: consecutive missed scrapes before a target's series go stale
        self.stale_after = int(stale_after)
        #: the merged fleet-level registry served at /metrics/fleet
        self.view = MetricsRegistry()
        if csv_path:
            self.view.attach_csv(csv_path)
        self._scrapes = 0
        # per-target consecutive-miss counts (exported as
        # fleet_scrape_staleness{target=}); >= stale_after -> stale
        self._missed: Dict[str, int] = {}
        # targets whose per-replica model facts are currently exported
        # (fleet_model_iteration{target=}); departures retire them
        self._model_targets: set = set()
        # last-known model facts per target — carried through missed
        # scrapes until the target goes stale, so the skew/age headline
        # doesn't flicker on a single flaky scrape
        self._model_facts: Dict[str, Dict[str, float]] = {}
        # last fleet-summed jit_compile_events_total — None until the
        # first scrape, so the first observation seeds the baseline and
        # fleet_jit_compile_delta starts at 0 rather than the fleet's
        # whole compile history
        self._last_compile_sum: Optional[float] = None
        # bounded ring of RAW per-target scrapes — the UN-merged series
        # an incident bundle files so per-replica attribution survives
        self._raw_ring: "collections.deque" = collections.deque(
            maxlen=int(raw_window_records)
        )
        # per-(target, series) monotone-counter state: (last_raw,
        # accumulated).  A replica that dies keeps its accumulated
        # contribution, and one that restarts (counters back at 0) is
        # detected by raw < last and resumes accumulating — so the
        # fleet sums never go backward and window delta math stays
        # honest across exactly the SIGKILL the fleet exists to absorb.
        # Targets that leave the target LIST (a dead replica respawns
        # on a fresh ephemeral port; its old URL never returns) are
        # retired: their accumulation folds into _retired, bounding
        # per-target state in a long-lived proxy.  A scrape FAILURE is
        # not retirement — a blackholed replica stays listed and keeps
        # its live state.
        self._counter_state: Dict[
            Tuple[str, str, LabelKey], Tuple[float, float]
        ] = {}
        self._retired: Dict[Tuple[str, LabelKey], float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def targets(self) -> List[str]:
        t = self._targets() if callable(self._targets) else self._targets
        return [u.rstrip("/") for u in t]

    @staticmethod
    def _monotone(name: str) -> bool:
        """Series that only ever grow on a live replica: counters and
        cumulative histogram components.  These are retained across
        replica death/restart; gauges (queue depth) are live-only."""
        return name.endswith(("_total", "_bucket", "_count", "_sum"))

    def _accumulate(self, target: str, samples: List[Sample]) -> None:
        for s in samples:
            if not self._monotone(s.name):
                continue
            key = (target, s.name, s.labels)
            last, acc = self._counter_state.get(key, (0.0, 0.0))
            inc = s.value - last if s.value >= last else s.value
            self._counter_state[key] = (s.value, acc + inc)

    # -- one scrape --------------------------------------------------------

    def scrape_once(self) -> Dict[str, float]:
        """Scrape every target, merge, refresh the view, append the CSV
        row.  Returns the headline values (tests assert on them).

        Targets are fetched CONCURRENTLY: one wedged/blackholed replica
        costs its own timeout, not everyone's scrape cadence (the same
        lesson the fleet supervisor's health probes learned)."""
        target_list = self.targets()
        results: Dict[str, List[Sample]] = {}

        def one(url: str) -> None:
            try:
                results[url] = parse_prometheus(
                    self._fetch(url, self.timeout_s)
                )
            except Exception:
                pass  # absent from results -> counted as a scrape error

        fetchers = [
            threading.Thread(
                target=lambda u=u: one(u), daemon=True
            )
            for u in target_list
        ]
        for t in fetchers:
            t.start()
        deadline = time.monotonic() + self.timeout_s + 1.0
        for t in fetchers:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        ok_targets = 0
        scrape_wall = time.time()
        scrapes: List[List[Sample]] = []
        with self._lock:
            for url in target_list:
                samples = results.get(url)
                if samples is None:
                    # fetch raised or is still stuck past the deadline
                    self.view.counter(
                        "fleet_scrape_errors_total",
                        "replica /metrics scrapes that failed",
                    ).inc()
                    self._missed[url] = self._missed.get(url, 0) + 1
                    continue
                self._missed[url] = 0
                ok_targets += 1
                scrapes.append(samples)
                self._accumulate(url, samples)
                # raw (un-merged) window ring for incident bundles:
                # which REPLICA's series went bad must survive the merge
                self._raw_ring.append({
                    "wall": scrape_wall,
                    "target": url,
                    "samples": {
                        s.name + (
                            "{" + ",".join(
                                f"{k}={v}" for k, v in s.labels
                            ) + "}" if s.labels else ""
                        ): s.value
                        for s in samples
                    },
                })
            # staleness bookkeeping: consecutive misses per LISTED
            # target; a departed URL's series is REMOVED, not zeroed —
            # ephemeral-port targets never recur, and a crash-looping
            # replica must not grow /metrics/fleet one dead
            # target= label set per restart
            current_targets = set(target_list)
            for url in [u for u in self._missed if u not in current_targets]:
                del self._missed[url]
                self.view.remove(
                    "fleet_scrape_staleness", labels={"target": url}
                )
            stale = {
                url for url, n in self._missed.items()
                if n >= self.stale_after
            }
            for url in target_list:
                self.view.gauge(
                    "fleet_scrape_staleness", labels={"target": url}
                ).set(self._missed.get(url, 0))
            # per-replica served-model facts (docs/CONTINUOUS.md): which
            # iteration each target serves and how old its artifact is.
            # A fleet silently wedged on an old iteration (quarantined
            # candidate, half-finished promotion) shows up here as
            # iteration skew / growing age — the default staleness and
            # skew alert rules watch the fleet-level reductions below.
            for url in target_list:
                samples = results.get(url)
                if samples is None:
                    # missed scrape: keep the last-known facts until the
                    # target goes STALE (same tolerance as the quantile
                    # machinery) — one flaky scrape must not zero the
                    # skew headline and reset a skew alert's debounce
                    # exactly when a wedged replica matters most
                    if url in stale:
                        self._model_facts.pop(url, None)
                    continue
                facts: Dict[str, float] = {}
                for s in samples:
                    if s.name in (
                        "model_iteration", "model_age_seconds"
                    ) and not s.labels:
                        facts[s.name] = s.value
                if facts:
                    self._model_facts[url] = facts
                else:
                    # scraped fine but reports no model facts (replica
                    # restarted unloaded): genuinely gone, retire both
                    # the cached facts and the per-target series
                    self._model_facts.pop(url, None)
                    for gauge in (
                        "fleet_model_iteration",
                        "fleet_model_age_seconds",
                    ):
                        self.view.remove(gauge, labels={"target": url})
                for name, gauge in (
                    ("model_iteration", "fleet_model_iteration"),
                    ("model_age_seconds", "fleet_model_age_seconds"),
                ):
                    if name in facts:
                        self.view.gauge(
                            gauge, labels={"target": url}
                        ).set(facts[name])
            model_facts = {
                u: f for u, f in self._model_facts.items()
                if u in set(target_list)
            }
            for url in [
                u for u in self._model_targets
                if u not in set(target_list)
            ]:
                # departed targets retire their labeled series like the
                # staleness gauges do — ephemeral ports never recur
                for gauge in (
                    "fleet_model_iteration", "fleet_model_age_seconds"
                ):
                    self.view.remove(gauge, labels={"target": url})
                self._model_facts.pop(url, None)
            self._model_targets = set(target_list)
            # group per-target facts by served catalog model: iteration
            # skew ACROSS models is expected (each model trains on its
            # own cadence), so in a catalog fleet the skew headline is
            # the max WITHIN-model skew — a heterogeneous two-model
            # fleet must not hold the skew alert firing forever.  On a
            # single-model fleet every target lands in one group and
            # the math is unchanged.
            groups: Dict[Optional[str], List[Dict[str, float]]] = {}
            for u, f in model_facts.items():
                m = (
                    self.model_of(u)
                    if self.model_of is not None else None
                )
                groups.setdefault(m, []).append(f)
            iters = [
                f["model_iteration"] for f in model_facts.values()
                if "model_iteration" in f
            ]
            ages = [
                f["model_age_seconds"] for f in model_facts.values()
                if "model_age_seconds" in f
            ]
            model_headline: Dict[str, float] = {}
            if iters:
                model_headline["fleet_model_iteration_min"] = min(iters)
                model_headline["fleet_model_iteration_max"] = max(iters)
                skews = []
                for fs in groups.values():
                    gi = [
                        f["model_iteration"] for f in fs
                        if "model_iteration" in f
                    ]
                    if gi:
                        skews.append(max(gi) - min(gi))
                model_headline["fleet_model_iteration_skew"] = (
                    max(skews) if skews else 0.0
                )
            if ages:
                model_headline["fleet_model_age_seconds_max"] = max(ages)
            # per-model labeled age + the stale-models count: a model
            # counts as stale only when even its FRESHEST replica's
            # artifact is old — one lagging replica is iteration skew's
            # problem, a whole model nobody retrains is this one's
            pub_age_models: set = set()
            stale_models = 0
            for m in sorted(k for k in groups if k is not None):
                ga = [
                    f["model_age_seconds"] for f in groups[m]
                    if "model_age_seconds" in f
                ]
                if not ga:
                    continue
                self.view.gauge(
                    "fleet_model_age_seconds_max", labels={"model": m}
                ).set(max(ga))
                model_headline[
                    f"fleet_model_age_seconds_max{{model={m}}}"
                ] = max(ga)
                pub_age_models.add(m)
                if min(ga) > self.model_stale_after_s:
                    stale_models += 1
            for m in self._model_age_series - pub_age_models:
                self.view.remove(
                    "fleet_model_age_seconds_max", labels={"model": m}
                )
            self._model_age_series = pub_age_models
            if pub_age_models:
                self.view.gauge("fleet_models_stale").set(stale_models)
                model_headline["fleet_models_stale"] = float(stale_models)
            else:
                # no named models reporting: retire the count like the
                # per-target series — a frozen stale-count would hold
                # the per-model staleness alert firing forever
                self.view.remove("fleet_models_stale")
            for key in (
                "fleet_model_iteration_min",
                "fleet_model_iteration_max",
                "fleet_model_iteration_skew",
                "fleet_model_age_seconds_max",
            ):
                if key in model_headline:
                    self.view.gauge(key).set(model_headline[key])
                else:
                    # model facts gone (every scrape missed, or the
                    # replicas restarted unloaded): retire the headline
                    # like the per-target series — a stale skew gauge
                    # would hold a skew alert firing forever
                    self.view.remove(key)
            # fold state for targets no longer LISTED into the retired
            # baseline (caveat: a target re-listed later under the SAME
            # url restarts from its current raw value — supervisor
            # fleets never reuse urls, and static target lists never
            # unlist, so neither path double-counts in practice)
            for key in [
                k for k in self._counter_state
                if k[0] not in current_targets
            ]:
                _target, name, labels = key
                _last, acc = self._counter_state.pop(key)
                rkey = (name, labels)
                self._retired[rkey] = self._retired.get(rkey, 0.0) + acc
            # monotone series come from the RETAINED accumulation (dead
            # replicas keep their history); live-only series merge from
            # this round's successful scrapes
            merged = {
                key: value
                for key, value in merge_samples(scrapes).items()
                if not self._monotone(key[0])
            }
            # quantile estimates use FRESH histogram history only: a
            # stale (or retired) target's buckets are frozen — letting
            # them keep weighing the percentile would freeze exactly
            # the gauge an alert rule is watching (the staleness
            # satellite's contract); fleet SUMS still include every
            # accumulation so counters never go backward
            fresh_hist: Dict[Tuple[str, LabelKey], float] = {}
            # per-shard projections (replicated-shard fleets): the same
            # fresh-histogram rule, bucketed by the target's shard, so
            # the per-shard autoscaler sees ITS pool's scatter latency
            shard_hist: Dict[int, Dict[Tuple[str, LabelKey], float]] = {}
            for (
                (target, name, labels), (_last, acc)
            ) in self._counter_state.items():
                key = (name, labels)
                merged[key] = merged.get(key, 0.0) + acc
                if target not in stale and name.startswith(
                    self.ROUTE_HISTOGRAM
                ):
                    fresh_hist[key] = fresh_hist.get(key, 0.0) + acc
                    if self.shard_of is not None:
                        s = self.shard_of(target)
                        if s is not None:
                            h = shard_hist.setdefault(s, {})
                            h[key] = h.get(key, 0.0) + acc
            for rkey, acc in self._retired.items():
                merged[rkey] = merged.get(rkey, 0.0) + acc
            shard_queue: Dict[int, float] = {}
            if self.shard_of is not None:
                # live-only like the fleet queue gauge: this round's
                # successful scrapes, summed per shard
                for url in target_list:
                    samples = results.get(url)
                    if samples is None:
                        continue
                    s = self.shard_of(url)
                    if s is None:
                        continue
                    for smp in samples:
                        if smp.name == "serve_queue_depth":
                            shard_queue[s] = (
                                shard_queue.get(s, 0.0) + smp.value
                            )
            model_queue: Dict[str, float] = {}
            if self.model_of is not None:
                # per-model pool pressure, live-only like the shard
                # twin: each target's whole queue depth (labeled or
                # not) belongs to exactly one model in a catalog fleet
                for url in target_list:
                    samples = results.get(url)
                    if samples is None:
                        continue
                    m = self.model_of(url)
                    if m is None:
                        continue
                    for smp in samples:
                        if smp.name == "serve_queue_depth":
                            model_queue[m] = (
                                model_queue.get(m, 0.0) + smp.value
                            )

        def msum(name: str) -> float:
            return sum(
                v for (n, _), v in merged.items() if n == name
            )

        requests = msum("serve_requests_total")
        rejected = msum("serve_rejected_total")
        # the tenant-labeled slice of the rejections: per-tenant quota
        # shedding (serve/tenancy.py).  Kept distinct so the autoscaler
        # can scale on CAPACITY rejections (queue-full, unlabeled) and
        # not on traffic a quota is deliberately rejecting.
        quota_rejected = sum(
            v for (n, lk), v in merged.items()
            if n == "serve_rejected_total"
            and any(k == "tenant" for k, _ in lk)
        )
        queue_depth = msum("serve_queue_depth")
        rejection_rate = (rejected / requests) if requests > 0 else 0.0
        # fleet-wide jit compile events: replicas mirror their
        # CompileWatcher into the monotone jit_compile_events_total
        # counter (reset-rebased across restarts by the merge above);
        # the per-tick delta is what the default jit-recompile-storm
        # rule watches — compiles during steady-state serving are a
        # recompile storm.  The first scrape only seeds the baseline,
        # so an aggregator joining a warm fleet never false-fires on
        # the backlog.
        jit_compiles = msum("jit_compile_events_total")
        if self._last_compile_sum is None:
            compile_delta = 0.0
        else:
            compile_delta = max(
                0.0, jit_compiles - self._last_compile_sum
            )
        self._last_compile_sum = jit_compiles

        ok_total = total = throttled = degraded = 0.0
        if self.proxy_registry is not None:
            ok_total = self.proxy_registry.counter(
                "fleet_proxy_ok_total"
            ).value
            total = self.proxy_registry.counter(
                "fleet_proxy_responses_total"
            ).value
            throttled = self.proxy_registry.counter(
                "fleet_proxy_429_total"
            ).value
            # sharded-fleet degradation: 200s built from a PARTIAL
            # shard gather (serve/shardgroup.py).  Exported alongside
            # the plain availability pair — and as the good-counter
            # complement fleet_undegraded, so the degraded-burn alert
            # rule can treat "complete answer" as the good event.
            degraded = self.proxy_registry.counter(
                "fleet_degraded_responses_total"
            ).value
        availability = (ok_total / total) if total > 0 else 1.0
        undegraded = max(0.0, total - degraded)

        # the flat snapshot handed to the alert evaluator: headline
        # values, the raw availability counter pair (burn-rate rules
        # delta them), labeled route quantiles, and the freshness facts
        # that let rules HOLD instead of evaluating frozen data
        snapshot: Dict[str, float] = {}
        with self._lock:
            self._scrapes += 1
            v = self.view
            v.gauge("fleet_replicas_scraped").set(ok_targets)
            v.gauge("fleet_queue_depth").set(queue_depth)
            v.gauge("fleet_requests").set(requests)
            v.gauge("fleet_rejected").set(rejected)
            v.gauge("fleet_quota_rejected").set(quota_rejected)
            v.gauge("fleet_rejection_rate").set(rejection_rate)
            v.gauge("fleet_ok").set(ok_total)
            v.gauge("fleet_responses").set(total)
            v.gauge("fleet_throttled").set(throttled)
            v.gauge("fleet_degraded").set(degraded)
            v.gauge("fleet_undegraded").set(undegraded)
            v.gauge("fleet_availability").set(availability)
            v.gauge("fleet_stale_targets").set(len(stale))
            v.gauge("fleet_last_scrape_unix").set(scrape_wall)
            v.gauge("fleet_jit_compiles").set(jit_compiles)
            v.gauge("fleet_jit_compile_delta").set(compile_delta)
            for labels in histogram_routes(fresh_hist, self.ROUTE_HISTOGRAM):
                label_dict = dict(labels)
                for gauge_name, q in (
                    ("fleet_route_p50_seconds", 0.50),
                    ("fleet_route_p99_seconds", 0.99),
                ):
                    quant = histogram_quantile(
                        fresh_hist, self.ROUTE_HISTOGRAM, labels, q
                    )
                    if quant is not None and math.isfinite(quant):
                        v.gauge(gauge_name, labels=label_dict).set(quant)
                        suffix = ",".join(
                            f"{k}={val}" for k, val in sorted(
                                label_dict.items()
                            )
                        )
                        snapshot[f"{gauge_name}{{{suffix}}}"] = quant
            # per-shard pool signals + the redundancy view
            # (docs/SERVING.md#replicated-shards): queue depth and
            # scatter p99 per shard feed the per-shard autoscaler;
            # fleet_shard_replicas_up{shard=} + the
            # fleet_shards_redundancy_lost headline feed the
            # shard-redundancy-lost alert — the page that precedes the
            # recall-degradation page
            if self.shard_of is not None:
                pub_queue: set = set()
                pub_p99: set = set()
                for s, q in sorted(shard_queue.items()):
                    v.gauge(
                        "fleet_shard_queue_depth",
                        labels={"shard": str(s)},
                    ).set(q)
                    snapshot[f"fleet_shard_queue_depth{{shard={s}}}"] = q
                    pub_queue.add(s)
                topk_labels = (("route", "/v1/shard/topk"),)
                for s, hist in sorted(shard_hist.items()):
                    quant = histogram_quantile(
                        hist, self.ROUTE_HISTOGRAM, topk_labels, 0.99
                    )
                    if quant is not None and math.isfinite(quant):
                        v.gauge(
                            "fleet_shard_p99_seconds",
                            labels={"shard": str(s)},
                        ).set(quant)
                        snapshot[
                            f"fleet_shard_p99_seconds{{shard={s}}}"
                        ] = quant
                        pub_p99.add(s)
                # a shard with no fresh evidence this round retires its
                # series (the snapshot above is already rebuilt fresh,
                # so this only stops /metrics/fleet from freezing a
                # dead shard's last queue/p99 forever)
                for name, pub, prev in (
                    ("fleet_shard_queue_depth", pub_queue,
                     self._shard_queue_series),
                    ("fleet_shard_p99_seconds", pub_p99,
                     self._shard_p99_series),
                ):
                    for s in prev - pub:
                        v.remove(name, labels={"shard": str(s)})
                self._shard_queue_series = pub_queue
                self._shard_p99_series = pub_p99
            if self.shard_facts is not None:
                try:
                    facts = self.shard_facts() or {}
                except Exception:
                    facts = {}
                lost = 0
                for s, f in sorted(facts.items()):
                    up = float(f.get("up", 0))
                    v.gauge(
                        "fleet_shard_replicas_up",
                        labels={"shard": str(s)},
                    ).set(up)
                    snapshot[f"fleet_shard_replicas_up{{shard={s}}}"] = up
                    if float(f.get("desired", 1)) >= 2 and up < 2:
                        lost += 1
                if facts:
                    v.gauge("fleet_shards_redundancy_lost").set(lost)
                    snapshot["fleet_shards_redundancy_lost"] = float(lost)
            # per-model pool signals (docs/SERVING.md#multi-model-
            # catalog): queue depth per model feeds the (model, shard)
            # pool autoscaler; fleet_model_replicas_up{model=} is the
            # per-model redundancy view.  Retirement mirrors the shard
            # series — a model whose every replica went dark must not
            # freeze its last queue depth on /metrics/fleet.
            if self.model_of is not None:
                pub_mq: set = set()
                for m, q in sorted(model_queue.items()):
                    v.gauge(
                        "fleet_model_queue_depth", labels={"model": m}
                    ).set(q)
                    snapshot[f"fleet_model_queue_depth{{model={m}}}"] = q
                    pub_mq.add(m)
                for m in self._model_queue_series - pub_mq:
                    v.remove(
                        "fleet_model_queue_depth", labels={"model": m}
                    )
                self._model_queue_series = pub_mq
            if self.model_pool_facts is not None:
                try:
                    mfacts = self.model_pool_facts() or {}
                except Exception:
                    mfacts = {}
                for m, up in sorted(mfacts.items()):
                    v.gauge(
                        "fleet_model_replicas_up",
                        labels={"model": str(m)},
                    ).set(float(up))
                    snapshot[
                        f"fleet_model_replicas_up{{model={m}}}"
                    ] = float(up)
            headline = {
                "fleet_availability": availability,
                "fleet_queue_depth": queue_depth,
                "fleet_rejection_rate": rejection_rate,
                "fleet_replicas_scraped": float(ok_targets),
                "fleet_requests": requests,
                "fleet_rejected": rejected,
            }
            snapshot.update(headline)
            snapshot.update(model_headline)
            snapshot.update({
                "fleet_ok": ok_total,
                "fleet_responses": total,
                "fleet_throttled": throttled,
                "fleet_degraded": degraded,
                "fleet_undegraded": undegraded,
                "fleet_quota_rejected": quota_rejected,
                "fleet_stale_targets": float(len(stale)),
                "fleet_jit_compiles": jit_compiles,
                "fleet_jit_compile_delta": compile_delta,
                "_fresh_targets": float(ok_targets),
            })
            # CSV history: one row per scrape through the standard sink
            v.log_row(self._scrapes, headline)
        if self.evaluator is not None:
            # outside the view lock: the evaluator takes its own lock
            # and writes alert gauges back through the registry's
            self.evaluator.observe(snapshot, wall=scrape_wall)
        for observer in list(self.observers):
            try:
                observer(snapshot, wall=scrape_wall)
            except Exception:
                self.view.counter(
                    "fleet_observer_errors_total",
                    "snapshot observers (autoscaler) that raised",
                ).inc()
        return headline

    def raw_recent(self) -> List[Dict]:
        """The raw per-target scrape ring (newest last) — what an
        incident bundle files as ``metrics_window.json``."""
        with self._lock:
            return list(self._raw_ring)

    def fleet_text(self) -> str:
        """The ``/metrics/fleet`` exposition."""
        with self._lock:
            return self.view.prometheus_text()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FleetAggregator":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="fleet-aggregator", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception:
                # aggregation must outlive surprises; the error counter
                # above records per-target trouble, this guards the rest
                self.view.counter("fleet_scrape_errors_total").inc()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self.view.close()
