"""Run manifest + stall watchdog: the per-run observability orchestrator.

A :class:`Run` owns one run directory and writes, at construction, a
``manifest.json`` recording *what configuration produced this run*:
config dict + deterministic config hash, git sha, argv, backend/mesh,
library versions.  It then exposes the tracer (``events.jsonl``), the
metrics registry (``metrics.prom`` snapshots + the trainer's CSV sink),
and a step clock whose :class:`StallWatchdog` flags any step exceeding
3× the rolling-window p99 as a ``stall`` event.

Construction never raises for missing optional context (no git, no jax
backend, read-only env probes): a run that cannot record its git sha
still records everything else.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import hashlib
import json
import os
import socket
import subprocess
import sys
import time
from typing import Dict, Iterator, Optional

from gene2vec_tpu.obs import probes, trace
from gene2vec_tpu.obs.registry import MetricsRegistry
from gene2vec_tpu.obs.trace import Tracer

MANIFEST_NAME = "manifest.json"
EVENTS_NAME = "events.jsonl"
METRICS_NAME = "metrics.prom"


def _config_dict(config) -> Dict:
    if config is None:
        return {}
    if dataclasses.is_dataclass(config) and not isinstance(config, type):
        return dataclasses.asdict(config)
    if isinstance(config, dict):
        return dict(config)
    return {"repr": repr(config)}


def config_hash(config) -> str:
    """Deterministic hash of a config (dataclass or dict): same config →
    same hash, across processes and sessions."""
    blob = json.dumps(
        _config_dict(config), sort_keys=True, separators=(",", ":"),
        default=str,
    )
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def _git_sha() -> Optional[str]:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            capture_output=True, timeout=10, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        return out.stdout.strip() or None if out.returncode == 0 else None
    except Exception:
        return None


def _versions() -> Dict[str, str]:
    out = {"python": sys.version.split()[0]}
    for mod in ("jax", "jaxlib", "numpy", "flax", "optax"):
        try:
            from importlib import metadata

            out[mod] = metadata.version(mod)
        except Exception:
            continue
    return out


def _backend_info(probe_devices: bool) -> Dict:
    """Backend/mesh facts.  Only queried when jax is already imported AND
    the caller opted in — ``jax.devices()`` initializes the backend, a
    cost (and a device claim) the native CPU trainer must not pay."""
    if not probe_devices or "jax" not in sys.modules:
        return {}
    try:
        import jax

        devs = jax.devices()
        return {
            "platform": devs[0].platform if devs else None,
            "device_count": len(devs),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
        }
    except Exception:
        return {}


class StallWatchdog:
    """Rolling-p99 step budget: a step slower than ``factor`` × the p99
    of the trailing window is a stall.

    The window holds the *previous* steps only — the candidate step is
    judged against history, then admitted, so one huge step cannot
    instantly inflate its own budget.
    """

    def __init__(
        self, window: int = 64, factor: float = 3.0, min_samples: int = 5
    ):
        self.window: collections.deque = collections.deque(maxlen=window)
        self.factor = factor
        self.min_samples = min_samples

    def p99(self) -> Optional[float]:
        if not self.window:
            return None
        ordered = sorted(self.window)
        return ordered[min(len(ordered) - 1, int(0.99 * len(ordered)))]

    def budget(self) -> Optional[float]:
        """Current stall threshold in seconds (None while warming up)."""
        if len(self.window) < self.min_samples:
            return None
        return self.factor * self.p99()

    def record(self, duration_s: float) -> bool:
        """Admit one step duration; True when it breached the budget."""
        budget = self.budget()
        stalled = budget is not None and duration_s > budget
        self.window.append(float(duration_s))
        return stalled


class Run:
    """One observed run: run dir + manifest + tracer + registry + watchdog.

    Also installs itself as the *ambient* tracer
    (:func:`gene2vec_tpu.obs.trace.set_tracer`), so library spans emitted
    without a handle — including spans buffered before the run existed,
    like the native ABI check — land in this run's ``events.jsonl``.
    """

    def __init__(
        self,
        run_dir: str,
        name: str = "run",
        config=None,
        manifest_extra: Optional[Dict] = None,
        probe_devices: bool = True,
        watchdog: Optional[StallWatchdog] = None,
        snapshot_interval_s: float = 15.0,
    ):
        self.run_dir = os.path.abspath(run_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        self.name = name
        self.config = config
        self.registry = MetricsRegistry()
        self.tracer = Tracer(os.path.join(self.run_dir, EVENTS_NAME))
        self.watchdog = watchdog or StallWatchdog()
        self._snapshot_interval = snapshot_interval_s
        self._closed = False
        if probe_devices:
            probes.CompileWatcher.install()
        self.manifest = {
            "name": name,
            "run_dir": self.run_dir,
            "created_unix": time.time(),
            "argv": list(sys.argv),
            "cwd": os.getcwd(),
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
            "git_sha": _git_sha(),
            "config": _config_dict(config),
            "config_hash": config_hash(config),
            "versions": _versions(),
            "backend": _backend_info(probe_devices),
            "env": {
                k: os.environ[k]
                for k in ("JAX_PLATFORMS", "XLA_FLAGS")
                if k in os.environ
            },
            **(manifest_extra or {}),
        }
        self._write_manifest()
        trace.set_tracer(self.tracer)
        self.tracer.event("run_start", run=name)

    def _write_manifest(self) -> None:
        path = os.path.join(self.run_dir, MANIFEST_NAME)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.manifest, f, indent=1, default=str)
            f.write("\n")
        os.replace(tmp, path)

    def annotate(self, **fields) -> None:
        """Merge late-arriving facts (e.g. the compiled collective budget)
        into the on-disk manifest."""
        self.manifest.update(fields)
        self._write_manifest()

    def mark_interrupted(self, reason: str = "preempted", **fields) -> None:
        """Stamp the on-disk manifest ``interrupted=true`` — the
        preemption-drain contract (docs/RESILIENCE.md): a resumed run
        can tell a drained predecessor from one that finished, and
        dashboards can count preemptions per run dir."""
        self.annotate(interrupted=True, interrupted_reason=reason, **fields)
        self.tracer.event("interrupted", reason=reason, **fields)

    def annotate_backend(self) -> None:
        """Merge live backend facts into the manifest — for callers that
        construct with ``probe_devices=False`` (to keep jax uninitialized
        across a fork, say) and initialize jax later themselves."""
        info = _backend_info(True)
        if info:
            self.annotate(backend=info)

    # -- tracing -----------------------------------------------------------

    def span(self, name: str, **attrs):
        return self.tracer.span(name, **attrs)

    def event(self, name: str, **attrs) -> None:
        self.tracer.event(name, **attrs)

    def record_step(self, name: str, duration_s: float, **attrs) -> bool:
        """Feed one step duration to the ``step_seconds`` histogram and
        the rolling-p99 stall detector; a breach emits a ``stall`` event
        carrying the budget it broke.  Span-free — the high-cadence path
        (per-batch host loops) calls this without writing per-step
        records.  Returns whether the step stalled."""
        budget = self.watchdog.budget()
        stalled = self.watchdog.record(duration_s)
        self.registry.histogram("step_seconds").observe(duration_s)
        if stalled:
            self.registry.counter("stalls_total").inc()
            # Canonical stall fields win; caller attrs that collide (e.g.
            # a per-batch ``step`` counter) survive under a ``ctx_`` prefix
            # rather than crashing the training loop mid-run.
            canonical = {
                "step": name, "dur": duration_s,
                "budget": budget, "p99": self.watchdog.p99(),
            }
            extra = {
                (f"ctx_{k}" if k in canonical or k == "type" else k): v
                for k, v in attrs.items()
            }
            self.tracer.event("stall", type="stall", **canonical, **extra)
        return stalled

    @contextlib.contextmanager
    def step(self, name: str = "step", **attrs) -> Iterator[Dict]:
        """A watchdog-clocked span: :meth:`record_step` plus the
        span_start/span_end records in the timeline."""
        t0 = time.perf_counter()
        with self.tracer.span(name, **attrs) as out:
            yield out
        self.record_step(name, time.perf_counter() - t0, **attrs)

    # -- metrics -----------------------------------------------------------

    def log_row(self, step: int, metrics: Dict[str, float]) -> None:
        """Per-iteration row → CSV sink + gauges + a bounded-cadence
        ``metrics.prom`` snapshot."""
        self.registry.log_row(step, metrics)
        self.registry.maybe_snapshot(
            os.path.join(self.run_dir, METRICS_NAME),
            self._snapshot_interval,
        )

    def probe(self) -> Dict:
        """Sample runtime probes into gauges + one ``probe`` event."""
        values = probes.sample(self.registry)
        self.tracer.event(
            "probe", **{k: v for k, v in values.items() if v is not None}
        )
        return values

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.tracer.event("run_end", run=self.name)
        self.registry.snapshot_to(os.path.join(self.run_dir, METRICS_NAME))
        self.registry.close()
        if trace.get_tracer() is self.tracer:
            trace.set_tracer(None)
        self.tracer.close()

    def __enter__(self) -> "Run":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
