"""Structured span tracer: append-only JSON-lines events.

Every record is one JSON object on one line of ``events.jsonl``:

* ``type``  — ``span_start`` | ``span_end`` | ``event`` | ``stall``;
* ``name``  — span/event name;
* ``wall``  — ``time.time()`` (the cross-process merge key);
* ``mono``  — ``time.monotonic()`` (the within-process duration clock);
* ``pid`` / ``tid`` — process id / thread id, so native Hogwild worker
  activity, subprocess probes, and the jitted step loop land in one
  merged timeline;
* ``span`` / ``parent`` — span id and enclosing span id (nesting);
* ``dur``   — seconds, on ``span_end`` records only;
* free-form ``attrs``.

Writes go through one ``os.write`` on an ``O_APPEND`` fd, so concurrent
writers (multiple processes appending to the same file) never interleave
within a line.  The fd is reopened after ``fork`` (pid change) so child
processes do not share a file position.

A module-level *ambient* tracer lets library code emit spans without
threading a tracer handle through every call: :func:`ambient_span` uses
the installed tracer when a :class:`~gene2vec_tpu.obs.run.Run` is active
and otherwise buffers a bounded number of records in memory, which the
next installed tracer flushes to disk — e.g. the native-backend ABI
check runs at import/construction time, before any run dir exists, and
still shows up in that run's timeline.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from typing import Dict, Iterator, List, Optional

from gene2vec_tpu.obs import tracecontext

_PENDING_MAX = 256


def _stamp_trace(record: Dict) -> None:
    """Stamp the thread's sampled trace context onto a record —
    ``trace`` (trace_id), ``tsid`` (this hop's span id), ``tpid``
    (parent hop) — so every span/event written while a request context
    is installed joins the cross-process tree ``cli.obs trace``
    reassembles.  Explicit fields win; an unsampled or absent context
    stamps nothing (that IS the overhead contract)."""
    if "trace" in record:
        return
    ctx = tracecontext.current()
    if ctx is None or not ctx.sampled:
        return
    record["trace"] = ctx.trace_id
    record["tsid"] = ctx.span_id
    if ctx.parent_id is not None:
        record["tpid"] = ctx.parent_id


class Tracer:
    """JSON-lines span/event writer bound to one ``events.jsonl`` path."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self._fd: Optional[int] = None
        self._fd_pid: Optional[int] = None
        self._local = threading.local()
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    # -- low-level ---------------------------------------------------------

    def _ensure_fd(self) -> int:
        pid = os.getpid()
        if self._fd is None or self._fd_pid != pid:
            self._fd = os.open(
                self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
            )
            self._fd_pid = pid
        return self._fd

    def _stack(self) -> List[str]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    def write(self, record: Dict) -> None:
        """Append one raw record (timestamps/pid/tid added if absent)."""
        record.setdefault("wall", time.time())
        record.setdefault("mono", time.monotonic())
        record.setdefault("pid", os.getpid())
        record.setdefault("tid", threading.get_ident())
        _stamp_trace(record)
        line = json.dumps(record, separators=(",", ":"), default=str) + "\n"
        with self._lock:
            os.write(self._ensure_fd(), line.encode("utf-8"))

    # -- spans / events ----------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[Dict]:
        """Nested timed span.  Yields a dict; keys set on it during the
        body are recorded as ``span_end`` attrs (e.g. a loss computed
        inside the span)."""
        stack = self._stack()
        span_id = f"{os.getpid()}-{next(self._ids)}"
        parent = stack[-1] if stack else None
        t0 = time.monotonic()
        self.write(
            {
                "type": "span_start", "name": name, "span": span_id,
                "parent": parent, "mono": t0,
                **({"attrs": attrs} if attrs else {}),
            }
        )
        stack.append(span_id)
        out_attrs: Dict = {}
        try:
            yield out_attrs
        finally:
            stack.pop()
            t1 = time.monotonic()
            merged = {**attrs, **out_attrs}
            self.write(
                {
                    "type": "span_end", "name": name, "span": span_id,
                    "parent": parent, "mono": t1, "dur": t1 - t0,
                    **({"attrs": merged} if merged else {}),
                }
            )

    def event(self, name: str, type: str = "event", **attrs) -> None:
        stack = self._stack()
        self.write(
            {
                "type": type, "name": name,
                "span": stack[-1] if stack else None,
                **({"attrs": attrs} if attrs else {}),
            }
        )

    def close(self) -> None:
        with self._lock:
            if self._fd is not None and self._fd_pid == os.getpid():
                os.close(self._fd)
            self._fd = None
            self._fd_pid = None


# -- ambient tracer ---------------------------------------------------------

_current: Optional[Tracer] = None
_pending: List[Dict] = []
_pending_lock = threading.Lock()


def get_tracer() -> Optional[Tracer]:
    """The installed tracer, or None when no run is active."""
    return _current


def set_tracer(tracer: Optional[Tracer]) -> None:
    """Install (or clear) the ambient tracer.  Buffered pre-run records
    are flushed to the newly installed tracer."""
    global _current
    _current = tracer
    if tracer is not None:
        with _pending_lock:
            buffered, _pending[:] = _pending[:], []
        for rec in buffered:
            tracer.write(rec)


@contextlib.contextmanager
def ambient_span(name: str, **attrs) -> Iterator[Dict]:
    """A span on the ambient tracer; with no tracer installed the record
    is buffered (bounded) and flushed into the next run's timeline."""
    tracer = _current
    if tracer is not None:
        with tracer.span(name, **attrs) as out:
            yield out
        return
    t0m, t0w = time.monotonic(), time.time()
    out: Dict = {}
    try:
        yield out
    finally:
        t1 = time.monotonic()
        merged = {**attrs, **out}
        rec = {
            "type": "span_end", "name": name, "span": None, "parent": None,
            "wall": t0w, "mono": t1, "dur": t1 - t0m, "pid": os.getpid(),
            "tid": threading.get_ident(), "buffered": True,
            **({"attrs": merged} if merged else {}),
        }
        # capture the context NOW — the buffered record is flushed later
        # from whichever thread installs the next tracer
        _stamp_trace(rec)
        with _pending_lock:
            if len(_pending) < _PENDING_MAX:
                _pending.append(rec)


def hop_span(
    name: str,
    ctx,
    dur: Optional[float] = None,
    wall: Optional[float] = None,
    **attrs,
) -> None:
    """Emit one ``span_end`` hop record with an EXPLICIT trace context —
    for code that finishes a hop on a thread where installing the
    thread-local context is wrong (the batcher worker serves many traces
    per batch; a hedged client attempt concludes on its own thread).

    ``ctx`` is the hop's own :class:`~gene2vec_tpu.obs.tracecontext.
    TraceContext` (its ``parent_id`` links it into the tree).  The
    record's process-local ``span`` field is the current thread's
    enclosing span, which is what lets ``cli.obs trace`` attach the
    surrounding ``serve_batch``/``serve_compute`` subtree to a
    ``batch_item`` hop.  No tracer installed, or an unsampled context →
    no record, no cost."""
    tracer = _current
    if tracer is None or ctx is None or not ctx.sampled:
        return
    stack = tracer._stack()
    record: Dict = {
        "type": "span_end",
        "name": name,
        # the ENCLOSING span's id, not an id of this record's own —
        # the "hop" marker below tells reassembly readers apart
        "span": stack[-1] if stack else None,
        "hop": True,
        "parent": None,
        "trace": ctx.trace_id,
        "tsid": ctx.span_id,
        **({"tpid": ctx.parent_id} if ctx.parent_id is not None else {}),
        **({"dur": float(dur)} if dur is not None else {}),
        **({"wall": float(wall)} if wall is not None else {}),
        **({"attrs": attrs} if attrs else {}),
    }
    tracer.write(record)


def read_events(path: str) -> List[Dict]:
    """Parse an ``events.jsonl`` (skipping torn/partial trailing lines),
    ordered by wall clock — the merged multi-process timeline."""
    events = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    events.sort(key=lambda e: e.get("wall", 0.0))
    return events
