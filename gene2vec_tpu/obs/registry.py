"""Metrics registry: named counters/gauges/histograms, with labels.

Trainers register instruments once and update them per iteration; the
registry renders a Prometheus-style text exposition
(:meth:`MetricsRegistry.prometheus_text`) and snapshots it to disk at a
bounded cadence (:meth:`MetricsRegistry.maybe_snapshot` — called from
the per-iteration log path, so no background thread is needed).

Instruments may carry **labels** (``registry.counter("serve_requests",
labels={"route": "/v1/similar"})``): each distinct label set is its own
series under one metric name (one ``# TYPE`` line per name).  Label
values are escaped per the Prometheus exposition format (``\\`` →
``\\\\``, ``"`` → ``\\"``, newline → ``\\n``) so a route or error string
containing any of them still produces a parseable scrape.  Distinct
label sets per metric are capped (:attr:`MetricsRegistry.
max_label_sets`, warn-then-drop): a per-gene or per-trace label can
never grow the registry without bound — overflow series collapse into
one detached instrument and ``metrics_dropped_labels_total`` counts the
capped get-or-create lookups (equal to dropped updates on the repo's
look-up-per-update hot paths; a caller that caches the returned
overflow instrument counts once).

The per-row CSV convention every trainer already used
(``training_log.csv`` via :class:`~gene2vec_tpu.utils.metrics.
MetricsLogger`) is absorbed as the registry's CSV sink:
:meth:`MetricsRegistry.log_row` writes the row through the attached
logger AND mirrors numeric values into same-named gauges, so the
Prometheus export always carries the latest row.
"""

from __future__ import annotations

import math
import os
import sys
import threading
from typing import Dict, List, Optional, Tuple, Union

from gene2vec_tpu.utils.metrics import MetricsLogger

# powers-of-4 seconds-scale buckets: 61 µs .. 4,096 s covers everything
# from a jitted step to a full corpus build
_DEFAULT_BUCKETS = tuple(4.0 ** e for e in range(-7, 7))


def _fmt(v: float) -> str:
    """Prometheus float formatting (+Inf / integer-exact values)."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def escape_label_value(value: str) -> str:
    """Prometheus exposition escaping for a label VALUE: backslash,
    double-quote, and newline must be escaped or the scrape line is
    unparseable (the text format's only three escapes)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def unescape_label_value(value: str) -> str:
    """Inverse of :func:`escape_label_value` (scrape parsers use it)."""
    out: List[str] = []
    i = 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt == "n":
                out.append("\n")
            elif nxt in ("\\", '"'):
                out.append(nxt)
            else:  # unknown escape: keep both chars, like Prometheus
                out.append(c)
                out.append(nxt)
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def _label_suffix(labels: Optional[Dict[str, str]]) -> str:
    """``{k="v",...}`` with escaped values, sorted keys; '' when bare."""
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class Counter:
    """Monotonically increasing value."""

    TYPE = "counter"

    def __init__(self, name: str, help: str = "", labels=None):
        self.name, self.help = name, help
        self.labels = dict(labels) if labels else None
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: Union[int, float] = 1) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative increment")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def expose(self) -> List[str]:
        return [
            f"{self.name}{_label_suffix(self.labels)} {_fmt(self._value)}",
        ]


class Gauge:
    """Last-written value."""

    TYPE = "gauge"

    def __init__(self, name: str, help: str = "", labels=None):
        self.name, self.help = name, help
        self.labels = dict(labels) if labels else None
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def expose(self) -> List[str]:
        return [
            f"{self.name}{_label_suffix(self.labels)} {_fmt(self._value)}",
        ]


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics) + min/max."""

    TYPE = "histogram"

    def __init__(self, name: str, help: str = "", buckets=_DEFAULT_BUCKETS,
                 labels=None):
        self.name, self.help = name, help
        self.labels = dict(labels) if labels else None
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            i = 0
            while i < len(self.buckets) and value > self.buckets[i]:
                i += 1
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            self._min = min(self._min, value)
            self._max = max(self._max, value)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    @property
    def max(self) -> float:
        return self._max if self._count else 0.0

    def expose(self) -> List[str]:
        suffix = _label_suffix(self.labels)
        lines: List[str] = []
        cum = 0
        for le, c in zip(self.buckets, self._counts):
            cum += c
            lines.append(
                f"{self.name}_bucket"
                f"{_label_suffix({**(self.labels or {}), 'le': _fmt(le)})}"
                f" {cum}"
            )
        lines.append(
            f"{self.name}_bucket"
            f"{_label_suffix({**(self.labels or {}), 'le': '+Inf'})}"
            f" {self._count}"
        )
        lines.append(f"{self.name}_sum{suffix} {_fmt(self._sum)}")
        lines.append(f"{self.name}_count{suffix} {self._count}")
        return lines


class MetricsRegistry:
    """(name, label set) → instrument registry with get-or-create
    accessors.  One metric NAME has one type (conflicts raise) and at
    most :attr:`max_label_sets` distinct label sets — beyond that,
    updates collapse into a shared detached instrument (invisible to
    the exposition) and ``metrics_dropped_labels_total`` counts the
    capped lookups, so a per-gene/per-trace label can never grow the
    scrape without bound."""

    #: distinct label sets allowed per metric name (warn-then-drop)
    max_label_sets = 64

    def __init__(self, max_label_sets: Optional[int] = None):
        if max_label_sets is not None:
            self.max_label_sets = int(max_label_sets)
        self._instruments: Dict[Tuple[str, Tuple], object] = {}
        self._label_sets: Dict[str, int] = {}   # name → distinct series
        self._warned_names: set = set()
        self._overflow: Dict[Tuple[str, str], object] = {}
        self._lock = threading.RLock()
        self._csv: Optional[MetricsLogger] = None
        self._last_snapshot = 0.0

    def _get(self, cls, name: str, help: str, labels=None, **kw):
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is not None:
                if not isinstance(inst, cls):
                    raise TypeError(
                        f"metric {name!r} already registered as "
                        f"{type(inst).__name__}, not {cls.__name__}"
                    )
                return inst
            # a NAME's type is fixed by its first series, labeled or not
            existing = self._label_sets.get(name)
            if existing is not None:
                for (n, _), other in self._instruments.items():
                    if n == name:
                        if not isinstance(other, cls):
                            raise TypeError(
                                f"metric {name!r} already registered as "
                                f"{type(other).__name__}, not {cls.__name__}"
                            )
                        break
            if labels and (existing or 0) >= self.max_label_sets:
                return self._drop_overflow(cls, name, help, **kw)
            inst = self._instruments[key] = cls(
                name, help, labels=labels, **kw
            )
            self._label_sets[name] = (existing or 0) + 1
            return inst

    def _drop_overflow(self, cls, name: str, help: str, **kw):
        """Cardinality cap hit: warn once per metric, count the capped
        lookup, and hand back one shared instrument that is NOT in the
        exposition — callers keep working, the scrape stays bounded."""
        if name not in self._warned_names:
            self._warned_names.add(name)
            print(
                f"metrics: label cardinality cap ({self.max_label_sets}) "
                f"hit for {name!r}; further label sets are dropped "
                "(metrics_dropped_labels_total counts them)",
                file=sys.stderr,
            )
        drop_key = ("metrics_dropped_labels_total", ())
        drop = self._instruments.get(drop_key)
        if drop is None:
            drop = self._instruments[drop_key] = Counter(
                "metrics_dropped_labels_total",
                "updates dropped by the per-metric label-cardinality cap",
            )
            self._label_sets["metrics_dropped_labels_total"] = 1
        drop.inc()
        okey = (name, cls.__name__)
        inst = self._overflow.get(okey)
        if inst is None:
            inst = self._overflow[okey] = cls(name, help, **kw)
        return inst

    def counter(self, name: str, help: str = "", labels=None) -> Counter:
        return self._get(Counter, name, help, labels=labels)

    def gauge(self, name: str, help: str = "", labels=None) -> Gauge:
        return self._get(Gauge, name, help, labels=labels)

    def histogram(
        self, name: str, help: str = "", buckets=_DEFAULT_BUCKETS,
        labels=None,
    ) -> Histogram:
        return self._get(Histogram, name, help, labels=labels,
                         buckets=buckets)

    def remove(self, name: str, labels=None) -> bool:
        """Drop one series from the exposition (and free its label-set
        slot).  For series keyed by inherently ephemeral label values —
        the fleet aggregator's ``fleet_scrape_staleness{target=}``
        gauges, whose ephemeral-port targets never recur — where
        leaving a dead series behind would grow the scrape without
        bound.  Returns whether the series existed."""
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            if self._instruments.pop(key, None) is None:
                return False
            remaining = self._label_sets.get(name, 1) - 1
            if remaining > 0:
                self._label_sets[name] = remaining
            else:
                self._label_sets.pop(name, None)
            return True

    # -- exposition --------------------------------------------------------

    def prometheus_text(self) -> str:
        lines: List[str] = []
        with self._lock:
            instruments = sorted(
                self._instruments.items(), key=lambda kv: kv[0]
            )
        last_name = None
        for (name, _), inst in instruments:
            if name != last_name:
                lines.append(f"# TYPE {name} {inst.TYPE}")
                last_name = name
            lines.extend(inst.expose())
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot_to(self, path: str) -> None:
        """Atomic (tmp + rename) write of the Prometheus exposition."""
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(self.prometheus_text())
        os.replace(tmp, path)

    def maybe_snapshot(
        self, path: str, interval_s: float = 15.0, now: float = None
    ) -> bool:
        """Time-gated :meth:`snapshot_to` — call from any periodic code
        path (the per-iteration log row); writes at most once per
        ``interval_s``."""
        import time

        now = time.monotonic() if now is None else now
        if now - self._last_snapshot < interval_s:
            return False
        self._last_snapshot = now
        self.snapshot_to(path)
        return True

    # -- CSV sink ----------------------------------------------------------

    def attach_csv(
        self, csv_path: str, tensorboard_dir: Optional[str] = None
    ) -> MetricsLogger:
        """Attach the per-row CSV sink (the repo's ``training_log.csv``
        convention); rows then flow through :meth:`log_row`."""
        self._csv = MetricsLogger(csv_path, tensorboard_dir=tensorboard_dir)
        return self._csv

    def log_row(self, step: int, metrics: Dict[str, float]) -> None:
        """One iteration row: CSV append + same-named gauges updated."""
        if self._csv is not None:
            self._csv.log(step, metrics)
        for k, v in metrics.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self.gauge(k).set(v)

    def close(self) -> None:
        if self._csv is not None:
            self._csv.close()
            self._csv = None
