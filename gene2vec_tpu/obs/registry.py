"""Metrics registry: named counters/gauges/histograms.

Trainers register instruments once and update them per iteration; the
registry renders a Prometheus-style text exposition
(:meth:`MetricsRegistry.prometheus_text`) and snapshots it to disk at a
bounded cadence (:meth:`MetricsRegistry.maybe_snapshot` — called from
the per-iteration log path, so no background thread is needed).

The per-row CSV convention every trainer already used
(``training_log.csv`` via :class:`~gene2vec_tpu.utils.metrics.
MetricsLogger`) is absorbed as the registry's CSV sink:
:meth:`MetricsRegistry.log_row` writes the row through the attached
logger AND mirrors numeric values into same-named gauges, so the
Prometheus export always carries the latest row.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Dict, List, Optional, Union

from gene2vec_tpu.utils.metrics import MetricsLogger

# powers-of-4 seconds-scale buckets: 61 µs .. 4,096 s covers everything
# from a jitted step to a full corpus build
_DEFAULT_BUCKETS = tuple(4.0 ** e for e in range(-7, 7))


def _fmt(v: float) -> str:
    """Prometheus float formatting (+Inf / integer-exact values)."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Counter:
    """Monotonically increasing value."""

    def __init__(self, name: str, help: str = ""):
        self.name, self.help = name, help
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: Union[int, float] = 1) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative increment")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def expose(self) -> List[str]:
        return [
            f"# TYPE {self.name} counter",
            f"{self.name} {_fmt(self._value)}",
        ]


class Gauge:
    """Last-written value."""

    def __init__(self, name: str, help: str = ""):
        self.name, self.help = name, help
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def expose(self) -> List[str]:
        return [
            f"# TYPE {self.name} gauge",
            f"{self.name} {_fmt(self._value)}",
        ]


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics) + min/max."""

    def __init__(self, name: str, help: str = "", buckets=_DEFAULT_BUCKETS):
        self.name, self.help = name, help
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            i = 0
            while i < len(self.buckets) and value > self.buckets[i]:
                i += 1
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            self._min = min(self._min, value)
            self._max = max(self._max, value)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    @property
    def max(self) -> float:
        return self._max if self._count else 0.0

    def expose(self) -> List[str]:
        lines = [f"# TYPE {self.name} histogram"]
        cum = 0
        for le, c in zip(self.buckets, self._counts):
            cum += c
            lines.append(f'{self.name}_bucket{{le="{_fmt(le)}"}} {cum}')
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {self._count}')
        lines.append(f"{self.name}_sum {_fmt(self._sum)}")
        lines.append(f"{self.name}_count {self._count}")
        return lines


class MetricsRegistry:
    """Name → instrument registry with get-or-create accessors."""

    def __init__(self):
        self._instruments: Dict[str, object] = {}
        self._lock = threading.Lock()
        self._csv: Optional[MetricsLogger] = None
        self._last_snapshot = 0.0

    def _get(self, cls, name: str, help: str, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(name, help, **kw)
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not {cls.__name__}"
                )
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(
        self, name: str, help: str = "", buckets=_DEFAULT_BUCKETS
    ) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    # -- exposition --------------------------------------------------------

    def prometheus_text(self) -> str:
        lines: List[str] = []
        with self._lock:
            instruments = sorted(self._instruments.items())
        for _, inst in instruments:
            lines.extend(inst.expose())
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot_to(self, path: str) -> None:
        """Atomic (tmp + rename) write of the Prometheus exposition."""
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(self.prometheus_text())
        os.replace(tmp, path)

    def maybe_snapshot(
        self, path: str, interval_s: float = 15.0, now: float = None
    ) -> bool:
        """Time-gated :meth:`snapshot_to` — call from any periodic code
        path (the per-iteration log row); writes at most once per
        ``interval_s``."""
        import time

        now = time.monotonic() if now is None else now
        if now - self._last_snapshot < interval_s:
            return False
        self._last_snapshot = now
        self.snapshot_to(path)
        return True

    # -- CSV sink ----------------------------------------------------------

    def attach_csv(
        self, csv_path: str, tensorboard_dir: Optional[str] = None
    ) -> MetricsLogger:
        """Attach the per-row CSV sink (the repo's ``training_log.csv``
        convention); rows then flow through :meth:`log_row`."""
        self._csv = MetricsLogger(csv_path, tensorboard_dir=tensorboard_dir)
        return self._csv

    def log_row(self, step: int, metrics: Dict[str, float]) -> None:
        """One iteration row: CSV append + same-named gauges updated."""
        if self._csv is not None:
            self._csv.log(step, metrics)
        for k, v in metrics.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self.gauge(k).set(v)

    def close(self) -> None:
        if self._csv is not None:
            self._csv.close()
            self._csv = None
