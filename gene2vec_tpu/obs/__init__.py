"""Unified observability layer: spans, metrics, probes, run manifests.

One subsystem replaces the per-script CSV/JSON dumps that grew alongside
the four trainers:

* :mod:`gene2vec_tpu.obs.trace` — append-only JSON-lines span/event
  tracer (``events.jsonl``); nested spans, wall + monotonic timestamps,
  process/thread ids, so concurrent writers land in one merged timeline;
* :mod:`gene2vec_tpu.obs.registry` — named counters/gauges/histograms
  with a Prometheus-style text export and a CSV sink
  (:class:`~gene2vec_tpu.utils.metrics.MetricsLogger`);
* :mod:`gene2vec_tpu.obs.probes` — runtime samplers: live-array HBM
  bytes, host RSS, jit compile counts, per-step collective bytes from
  optimized HLO (the ``scripts/hlo_comm_audit.py`` logic as a library);
* :mod:`gene2vec_tpu.obs.run` — the per-run orchestrator: writes
  ``manifest.json`` (config hash, git sha, backend, versions, argv) at
  run start and flags steps exceeding a rolling p99×3 budget as
  ``stall`` events;
* :mod:`gene2vec_tpu.obs.tracecontext` — W3C-traceparent-style
  distributed trace context (trace/span ids + sampled bit) propagated
  as an HTTP header across the serving fleet;
* :mod:`gene2vec_tpu.obs.aggregate` — fleet telemetry aggregator: the
  proxy scrapes every replica's ``/metrics`` and serves the merged
  SLO view at ``/metrics/fleet``;
* :mod:`gene2vec_tpu.obs.flight` — bounded per-process flight recorder
  (dumped on SIGQUIT / 5xx bursts) and the cross-process trace
  reassembly behind ``cli.obs trace``;
* :mod:`gene2vec_tpu.obs.timeline` — per-step phase timeline
  (host_ingest / dispatch / compute / ckpt_stage) into a bounded ring,
  flushed to ``timeline.jsonl`` and exported as Perfetto-loadable
  Chrome trace JSON via ``cli.obs timeline``;
* :mod:`gene2vec_tpu.obs.goodput` — goodput accounting: run wall time
  classified into compute / input-stall / checkpoint / preempted
  buckets (summing exactly to wall), achieved-vs-peak pairs/s, stamped
  into the run manifest and ``metrics.prom``;
* :mod:`gene2vec_tpu.obs.ledger` — the unified bench ledger: every
  root bench artifact adapted into one record schema, trailing-window
  regression detection (``cli.obs ledger``, gated by
  ``analysis/passes_perf.py``; docs/BENCHMARKS.md);
* :mod:`gene2vec_tpu.obs.alerts` — SLO alerting: declarative
  burn-rate/threshold rules with debounce + hysteresis, evaluated on
  every fleet-aggregator scrape tick, exported as
  ``fleet_alert_active{rule=}`` and logged to ``alerts.jsonl``
  (``cli.obs alerts``);
* :mod:`gene2vec_tpu.obs.incident` — incident capture: a rule firing
  assembles a rate-limited, disk-capped, manifest-CRC-verified bundle
  (rule + raw metric window + solicited flight dumps + slowest
  reassembled traces) under ``<run_dir>/incidents/``
  (``cli.obs incident``).

Every trainer's ``run(export_dir)`` writes ``manifest.json`` +
``events.jsonl`` into its export/run directory;
``python -m gene2vec_tpu.cli.obs report <run_dir>`` summarizes any of
them.  Schema and layout: docs/OBSERVABILITY.md.
"""

from gene2vec_tpu.obs.registry import MetricsRegistry  # noqa: F401
from gene2vec_tpu.obs.run import Run, StallWatchdog, config_hash  # noqa: F401
from gene2vec_tpu.obs.trace import Tracer, ambient_span, get_tracer  # noqa: F401
