"""Runtime probes: HBM, host RSS, jit compiles, collective bytes.

Everything here degrades gracefully — a probe that cannot run on this
backend/platform returns ``None`` rather than raising, so trainers can
sample unconditionally.  jax is imported lazily: the native Hogwild
trainer records manifests and RSS without paying a jax backend init.

The HLO collective audit (:func:`collective_stats_from_hlo` /
:func:`collective_stats`) is the ``scripts/hlo_comm_audit.py`` scanner
as a library call, so trainers can record their per-step comm budget in
the run manifest and the script stays a thin CLI over the same logic.
"""

from __future__ import annotations

import collections
import re
import sys
from typing import Dict, Optional

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8": 1,
    "s64": 8, "s32": 4, "s16": 2, "s8": 1,
    "u64": 8, "u32": 4, "u16": 2, "u8": 1, "pred": 1,
}

# one HLO shape like "f32[24447,513]" or a tuple "(f32[8,2], u32[...])"
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")

_COLLECTIVE_RE = re.compile(
    r"=\s*(\([^)]*\)|\S+)\s+"
    r"(all-gather|all-reduce|reduce-scatter|collective-permute|"
    r"all-to-all)\w*\("
)


def shape_bytes(text: str) -> int:
    """Total bytes of every ``dtype[dims]`` shape appearing in ``text``."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dtype, 4)
    return total


def collective_stats_from_hlo(hlo_text: str) -> Dict:
    """Count and size every collective in an optimized-HLO module text.

    Returns ``{"collectives": {op: {"count", "output_bytes"}},
    "total_bytes": N}`` — in a scanned epoch the loop body appears once,
    so these are per-step numbers.
    """
    ops = collections.defaultdict(lambda: [0, 0])
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if m:
            out_shape, op = m.group(1), m.group(2)
            ops[op][0] += 1
            ops[op][1] += shape_bytes(out_shape)
    return {
        "collectives": {
            op: {"count": c, "output_bytes": b} for op, (c, b) in ops.items()
        },
        "total_bytes": sum(b for _, b in ops.values()),
    }


def collective_stats(compiled_or_lowered) -> Optional[Dict]:
    """:func:`collective_stats_from_hlo` over a jitted function's
    ``.lower(...)`` result (compiled here) or an already-compiled object."""
    try:
        obj = compiled_or_lowered
        if hasattr(obj, "compile"):
            obj = obj.compile()
        return collective_stats_from_hlo(obj.as_text())
    except Exception:
        return None


def live_array_bytes() -> Optional[int]:
    """Total bytes of live device arrays (``jax.live_arrays``) — the HBM
    footprint attributable to this client on accelerator backends."""
    if "jax" not in sys.modules:
        return None
    try:
        import jax

        return int(sum(a.nbytes for a in jax.live_arrays()))
    except Exception:
        return None


def host_rss_bytes() -> Optional[int]:
    """Resident set size of this process."""
    try:
        with open("/proc/self/status", "r", encoding="ascii") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource

        # ru_maxrss is KiB on Linux, bytes on macOS — close enough as a
        # peak fallback when /proc is unavailable
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return rss * 1024 if sys.platform != "darwin" else rss
    except Exception:
        return None


class CompileWatcher:
    """Counts jax compilation events (jit cache misses) via the public
    ``jax.monitoring`` listener hook.  ``count`` stays 0 when the hook is
    unavailable; ``supported`` says whether the numbers mean anything."""

    _installed: Optional["CompileWatcher"] = None

    def __init__(self):
        self.count = 0
        self.supported = False
        self.events: Dict[str, int] = {}

    def _on_event(self, key: str, **kw) -> None:
        if "compil" in key:  # /jax/core/compile events, version-tolerant
            self.count += 1
            self.events[key] = self.events.get(key, 0) + 1

    @classmethod
    def install(cls) -> "CompileWatcher":
        """Idempotent process-wide installation (listeners cannot be
        unregistered, so one watcher serves every Run in the process)."""
        if cls._installed is not None:
            return cls._installed
        watcher = cls()
        try:
            import jax.monitoring

            jax.monitoring.register_event_listener(
                lambda key, **kw: watcher._on_event(key, **kw)
            )
            watcher.supported = True
        except Exception:
            watcher.supported = False
        cls._installed = watcher
        return watcher


def sample(registry=None) -> Dict[str, Optional[int]]:
    """One probe sample: HBM bytes, host RSS, cumulative compile count.
    With ``registry`` (a :class:`~gene2vec_tpu.obs.registry.
    MetricsRegistry`) the values also land in gauges."""
    watcher = CompileWatcher._installed
    out = {
        "hbm_bytes": live_array_bytes(),
        "host_rss_bytes": host_rss_bytes(),
        "jit_compiles": watcher.count if watcher is not None else None,
    }
    if registry is not None:
        for k, v in out.items():
            if v is not None:
                registry.gauge(k).set(v)
    return out
