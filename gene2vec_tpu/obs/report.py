"""Human-readable summary of any observed run directory.

``python -m gene2vec_tpu.cli.obs report <run_dir>`` renders, from the
standard artifacts (``manifest.json`` + ``events.jsonl`` + optional
``metrics.prom`` / ``training_log.csv``):

* the identity block — run name, config hash, git sha, backend, argv;
* per-phase wall time, aggregated over ``span_end`` events by name;
* throughput, from ``pairs``/``seconds`` span attrs when present;
* peak HBM / host RSS across ``probe`` events;
* every ``stall`` event with the budget it broke;
* when the run attributed kernels (``kernels.jsonl``,
  :mod:`gene2vec_tpu.obs.profiler`): the compact per-kernel block —
  top kernels by wall share with utilization and compile seconds
  (``cli.obs kernels`` renders the full roofline table).
"""

from __future__ import annotations

import collections
import json
import os
from typing import Dict, List, Optional

from gene2vec_tpu.obs.run import EVENTS_NAME, MANIFEST_NAME
from gene2vec_tpu.obs.trace import read_events


def _fmt_s(s: float) -> str:
    if s >= 60:
        return f"{s / 60:.1f} min"
    if s >= 1:
        return f"{s:.2f} s"
    return f"{s * 1e3:.1f} ms"


def _fmt_bytes(b: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(b) < 1024 or unit == "TiB":
            return f"{b:.1f} {unit}" if unit != "B" else f"{int(b)} B"
        b /= 1024
    return f"{b:.1f} TiB"


def load_run(run_dir: str) -> Dict:
    """Parsed artifacts: ``{"manifest": ..., "events": [...]}`` (either
    may be empty when the file is absent)."""
    manifest: Dict = {}
    mpath = os.path.join(run_dir, MANIFEST_NAME)
    if os.path.exists(mpath):
        with open(mpath, "r", encoding="utf-8") as f:
            manifest = json.load(f)
    events: List[Dict] = []
    epath = os.path.join(run_dir, EVENTS_NAME)
    if os.path.exists(epath):
        events = read_events(epath)
    return {"manifest": manifest, "events": events}


def summarize(run_dir: str) -> Dict:
    """Structured summary (the CLI renders this; tests assert on it)."""
    data = load_run(run_dir)
    manifest, events = data["manifest"], data["events"]

    phases: Dict[str, Dict] = collections.OrderedDict()
    pairs_total = 0.0
    train_seconds = 0.0
    for e in events:
        if e.get("type") != "span_end":
            continue
        name = e.get("name", "?")
        p = phases.setdefault(name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
        dur = float(e.get("dur", 0.0))
        p["count"] += 1
        p["total_s"] += dur
        p["max_s"] = max(p["max_s"], dur)
        attrs = e.get("attrs") or {}
        if "pairs" in attrs:
            pairs_total += float(attrs["pairs"])
            train_seconds += dur

    peak: Dict[str, float] = {}
    for e in events:
        if e.get("type") == "event" and e.get("name") == "probe":
            for k, v in (e.get("attrs") or {}).items():
                if isinstance(v, (int, float)):
                    peak[k] = max(peak.get(k, 0.0), float(v))

    stalls = [
        {
            "step": (e.get("attrs") or {}).get("step"),
            "dur": (e.get("attrs") or {}).get("dur"),
            "budget": (e.get("attrs") or {}).get("budget"),
            "wall": e.get("wall"),
        }
        for e in events
        if e.get("type") == "stall"
    ]

    walls = [e["wall"] for e in events if "wall" in e]
    processes = sorted({e.get("pid") for e in events if e.get("pid")})
    from gene2vec_tpu.obs import profiler

    kernel_records = profiler.read_kernels(run_dir)
    return {
        "goodput": manifest.get("goodput"),
        "kernels": (
            profiler.kernel_summary(kernel_records)
            if kernel_records else None
        ),
        "run_dir": os.path.abspath(run_dir),
        "name": manifest.get("name"),
        "config_hash": manifest.get("config_hash"),
        "git_sha": manifest.get("git_sha"),
        "backend": manifest.get("backend") or {},
        "argv": manifest.get("argv"),
        "n_events": len(events),
        "n_processes": len(processes),
        "wall_span_s": (max(walls) - min(walls)) if walls else 0.0,
        "phases": phases,
        "pairs_total": pairs_total,
        "pairs_per_sec": (
            pairs_total / train_seconds if train_seconds > 0 else None
        ),
        "peak": peak,
        "stalls": stalls,
    }


def format_report(run_dir: str) -> str:
    """The ``obs report`` text."""
    s = summarize(run_dir)
    lines = [f"run: {s['name'] or '(no manifest)'}  [{s['run_dir']}]"]
    if s["config_hash"]:
        lines.append(f"config hash: {s['config_hash']}")
    if s["git_sha"]:
        lines.append(f"git sha: {s['git_sha'][:12]}")
    backend = s["backend"]
    if backend:
        line = f"backend: {backend.get('platform')}"
        if backend.get("device_count") is not None:
            line += f" x{backend['device_count']}"
        if backend.get("process_count") is not None:
            line += (
                f" (process {backend.get('process_index')}/"
                f"{backend['process_count']})"
            )
        lines.append(line)
    lines.append(
        f"events: {s['n_events']} from {s['n_processes']} process(es) over "
        f"{_fmt_s(s['wall_span_s'])}"
    )
    if s["phases"]:
        lines.append("")
        lines.append(f"{'phase':<28}{'count':>7}{'total':>12}{'max':>12}")
        for name, p in sorted(
            s["phases"].items(), key=lambda kv: -kv[1]["total_s"]
        ):
            lines.append(
                f"{name:<28}{p['count']:>7}{_fmt_s(p['total_s']):>12}"
                f"{_fmt_s(p['max_s']):>12}"
            )
    if s["pairs_per_sec"]:
        lines.append("")
        train_s = s["pairs_total"] / s["pairs_per_sec"]
        lines.append(
            f"throughput: {s['pairs_per_sec']:,.0f} pairs/s "
            f"({s['pairs_total']:,.0f} pairs in {_fmt_s(train_s)} of "
            f"training spans)"
        )
    if s.get("goodput"):
        g = s["goodput"]
        lines.append("")
        fr = g.get("fractions") or {}
        lines.append(
            "goodput: "
            + "  ".join(
                f"{b} {100 * fr.get(b, 0.0):.1f}%"
                for b in ("compute", "input_stall", "checkpoint",
                          "preempted", "other")
            )
        )
        achieved = g.get("achieved_pairs_per_sec")
        peak_rate = g.get("peak_pairs_per_sec")
        if achieved is not None and peak_rate:
            lines.append(
                f"  achieved {achieved:,.0f} pairs/s vs peak "
                f"{peak_rate:,.0f} (utilization "
                f"{g.get('utilization', 0) or 0:.1%})"
            )
    if s.get("kernels"):
        ks = s["kernels"]
        lines.append("")
        lines.append(
            f"kernels: {ks.get('kernels', 0)} attributed, "
            f"{_fmt_s(ks.get('wall_s', 0.0))} observed wall, "
            f"{_fmt_s(ks.get('compile_s', 0.0))} compiling "
            "(full table: cli.obs kernels)"
        )
        for top in ks.get("top") or []:
            util = top.get("utilization")
            lines.append(
                f"  {top['name']:<26}{100 * top.get('wall_share', 0.0):>6.1f}"
                f"% wall  "
                + (f"util {util:.1%}" if util is not None else "util ?")
                + (f"  [{top['bound']}-bound]" if top.get("bound") else "")
            )
    if s["peak"]:
        lines.append("")
        for k in sorted(s["peak"]):
            v = s["peak"][k]
            shown = _fmt_bytes(v) if k.endswith("bytes") else f"{v:,.0f}"
            lines.append(f"peak {k}: {shown}")
    lines.append("")
    if s["stalls"]:
        lines.append(f"stalls: {len(s['stalls'])}")
        for st in s["stalls"][:20]:
            dur = st.get("dur")
            budget = st.get("budget")
            lines.append(
                f"  {st.get('step')}: "
                f"{_fmt_s(dur) if dur is not None else '?'} "
                f"(budget {_fmt_s(budget) if budget is not None else '?'})"
            )
        if len(s["stalls"]) > 20:
            lines.append(f"  ... and {len(s['stalls']) - 20} more")
    else:
        lines.append("stalls: none")
    return "\n".join(lines)


def find_runs(root: str) -> List[str]:
    """Run directories (holding events/manifest) under ``root``, direct
    children first — lets ``obs report`` take a parent directory."""
    out = []
    for dirpath, _, filenames in os.walk(root):
        if MANIFEST_NAME in filenames or EVENTS_NAME in filenames:
            out.append(dirpath)
    return sorted(out)
