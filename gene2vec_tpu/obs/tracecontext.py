"""W3C-traceparent-style distributed trace context.

A request that traverses the fleet — drill/loadgen client → front-door
proxy → resilient-client attempt (possibly retried or hedged) → replica
HTTP handler → batcher ticket → engine compute — carries ONE
:class:`TraceContext` across every hop, serialized on the wire as the
standard ``traceparent`` HTTP header::

    traceparent: 00-<trace_id:32 hex>-<span_id:16 hex>-<01|00>

* ``trace_id`` names the whole request tree (the cross-process join
  key); ``span_id`` names the sender's hop, and becomes the receiver's
  parent; the trailing flags byte carries the **sampled** bit.
* Each hop derives its own id with :meth:`TraceContext.child` — the
  ``parent_id`` field is in-process lineage only and never travels.
* Sampling is decided ONCE, at the trace root (a client's
  ``trace_sample`` knob, a server's :class:`Sampler` for headerless
  traffic), and every downstream hop honors the propagated bit: an
  unsampled trace costs one header parse and nothing else.

The ambient side lives in ``obs/trace.py``: while a context is
installed for the current thread (:func:`use`), every tracer record
written from that thread is stamped with ``trace``/``tsid``/``tpid``
fields, which is what ``cli.obs trace`` reassembles into the
cross-process tree (docs/OBSERVABILITY.md#distributed-tracing).

Stdlib-only and import-light on purpose: the serve hot path touches
this module per request.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import random
import threading
from typing import Iterator, Optional

TRACEPARENT_HEADER = "traceparent"

_TRACE_ID_LEN = 32
_SPAN_ID_LEN = 16
_HEX = set("0123456789abcdef")


def _rand_hex(n_chars: int) -> str:
    return os.urandom(n_chars // 2).hex()


def _is_hex(s: str, length: int) -> bool:
    return len(s) == length and not (set(s) - _HEX)


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One hop's identity within a distributed trace."""

    trace_id: str                    # 32 lowercase hex chars
    span_id: str                     # 16 lowercase hex chars (this hop)
    parent_id: Optional[str] = None  # sender/enclosing hop; never on the wire
    sampled: bool = True

    def to_header(self) -> str:
        """The ``traceparent`` value advertising THIS hop as the parent."""
        return (
            f"00-{self.trace_id}-{self.span_id}-"
            f"{'01' if self.sampled else '00'}"
        )

    @classmethod
    def from_header(cls, value: Optional[str]) -> Optional["TraceContext"]:
        """Parse a ``traceparent`` header; None for anything malformed
        (garbage from the network must never crash a handler).  Unknown
        future versions are accepted per the W3C spec (parse the fields
        we know); version ``ff`` is explicitly invalid."""
        if not value or not isinstance(value, str):
            return None
        parts = value.strip().lower().split("-")
        if len(parts) < 4:
            return None
        version, trace_id, span_id, flags = parts[:4]
        if not _is_hex(version, 2) or version == "ff":
            return None
        if not _is_hex(trace_id, _TRACE_ID_LEN) or trace_id == "0" * 32:
            return None
        if not _is_hex(span_id, _SPAN_ID_LEN) or span_id == "0" * 16:
            return None
        if not _is_hex(flags, 2):
            return None
        return cls(
            trace_id=trace_id,
            span_id=span_id,
            parent_id=None,
            sampled=bool(int(flags, 16) & 0x01),
        )

    def child(self) -> "TraceContext":
        """A new hop in the same trace, parented to this one — retries,
        hedges, and downstream handlers each get their own."""
        return TraceContext(
            trace_id=self.trace_id,
            span_id=_rand_hex(_SPAN_ID_LEN),
            parent_id=self.span_id,
            sampled=self.sampled,
        )


def new_trace(sampled: bool = True) -> TraceContext:
    """A fresh root context (no parent)."""
    return TraceContext(
        trace_id=_rand_hex(_TRACE_ID_LEN),
        span_id=_rand_hex(_SPAN_ID_LEN),
        parent_id=None,
        sampled=sampled,
    )


class Sampler:
    """Head sampling for traffic that arrives WITHOUT a traceparent:
    roll once per request and mint a sampled root at ``rate`` (0 never,
    1 always).  Propagated contexts bypass the sampler entirely — the
    root's decision already stands."""

    def __init__(self, rate: float, rng: Optional[random.Random] = None):
        self.rate = max(0.0, min(1.0, float(rate)))
        self._rng = rng if rng is not None else random.Random()

    def maybe_new_trace(self) -> Optional[TraceContext]:
        """A sampled root context, or None when this request is not
        selected (None means: do not trace at all, not even unsampled —
        headerless untraced requests must pay zero trace cost)."""
        if self.rate <= 0.0:
            return None
        if self._rng.random() >= self.rate:
            return None
        return new_trace(sampled=True)


# -- ambient (thread-local) context ------------------------------------------

_local = threading.local()


def current() -> Optional[TraceContext]:
    """The context installed for this thread, or None."""
    return getattr(_local, "ctx", None)


@contextlib.contextmanager
def use(ctx: Optional[TraceContext]) -> Iterator[Optional[TraceContext]]:
    """Install ``ctx`` for the current thread for the ``with`` body
    (``use(None)`` is a no-op pass-through, so call sites don't need a
    conditional).  Always restores the previous context — handlers
    recycle threads."""
    if ctx is None:
        yield None
        return
    prev = getattr(_local, "ctx", None)
    _local.ctx = ctx
    try:
        yield ctx
    finally:
        _local.ctx = prev
