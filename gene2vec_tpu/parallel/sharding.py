"""Sharding specs for SGNS training.

Two strategies (SURVEY §2.4 / BASELINE configs 2 & 5):

* **Data parallel** — tables replicated, the example axis of each batch
  sharded over ``data``.  The scatter-add updates into a replicated table
  force XLA to all-reduce the per-shard contributions over ICI; that psum
  IS the gradient all-reduce, emitted from sharding annotations rather
  than written as NCCL calls.
* **Row parallel (vocab-sharded)** — table rows sharded over ``model``
  (each device owns V/P contiguous rows), batch sharded over ``data``.
  XLA lowers ``table[idx]`` gathers / ``at[idx].add`` scatters on the
  sharded operand into masked local ops + collectives (all-gather of
  touched rows forward, reduce-scatter of row grads backward) — the
  communication-efficient pattern for a table too big to replicate
  (dim=512 × full vocab and beyond).

Both are expressed purely as ``NamedSharding`` trees + in-step
``with_sharding_constraint`` — the step code in sgns/step.py is identical.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gene2vec_tpu.sgns.model import SGNSParams


@dataclasses.dataclass(frozen=True)
class SGNSSharding:
    """Bundle of shardings for params / corpus / batch under a mesh."""

    mesh: Mesh
    vocab_sharded: bool = False
    data_axis: str = "data"
    model_axis: str = "model"

    # -- specs -------------------------------------------------------------

    def param_spec(self) -> P:
        return P(self.model_axis, None) if self.vocab_sharded else P(None, None)

    def params_sharding(self) -> SGNSParams:
        s = NamedSharding(self.mesh, self.param_spec())
        return SGNSParams(emb=s, ctx=s)

    def corpus_sharding(self) -> NamedSharding:
        # Corpus rows spread over the data axis; reshuffle gathers across
        # shards (cheap relative to the step itself).
        return NamedSharding(self.mesh, P(self.data_axis, None))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    # -- in-step constraints ----------------------------------------------

    def constrain_batch(self, batch: jax.Array) -> jax.Array:
        """Shard the pair-batch axis over ``data`` — this single annotation
        is what makes the whole step data-parallel."""
        return jax.lax.with_sharding_constraint(
            batch, NamedSharding(self.mesh, P(self.data_axis, None))
        )

    def constrain_params(self, params: SGNSParams) -> SGNSParams:
        s = NamedSharding(self.mesh, self.param_spec())
        return SGNSParams(
            emb=jax.lax.with_sharding_constraint(params.emb, s),
            ctx=jax.lax.with_sharding_constraint(params.ctx, s),
        )

    def constrain_acc(self, acc: jax.Array) -> jax.Array:
        """Pin the step's (V, D+1) gradient accumulator to the TABLE's row
        sharding.  Without this the SPMD partitioner materializes the
        accumulator replicated under vocab-sharded tables and ALL-REDUCES
        it — ~200 MB/step at dim=512 on the 8-way mesh, the dominant
        collective in the round-5 HLO audit
        (experiments/results/hlo_comm_r5.json); constrained, the scatter
        lowers to masked local updates on the owning shards."""
        return jax.lax.with_sharding_constraint(
            acc, NamedSharding(self.mesh, self.param_spec())
        )


def two_stage_topk(axis: str, scores: jax.Array, k: int, *,
                   base=None, ids: Optional[jax.Array] = None):
    """Distributed top-k merge, called INSIDE a ``shard_map`` body: each
    shard takes the local top-k of its ``scores`` columns, then only the
    ``(B, P*k)`` candidate sets all-gather and the final top-k selects —
    1 KB/query at the full-vocab dim-512 geometry vs 98 KB/query for the
    single-shot ``lax.top_k`` the SPMD partitioner lowers (it
    all-gathers the whole score matrix).  Exact over whatever the local
    scores cover: any global winner is in its own shard's local top-k,
    so the candidate union always contains the answer.

    Column→global-row mapping: ``base`` (a scalar offset) for the
    contiguous row-shard case (serve/engine.py), or ``ids`` (a (B, N)
    array of global row ids) when columns are arbitrary candidates
    (serve/ann.py's IVF/quantized scans).  Exactly one must be given.
    """
    from jax import lax
    import jax.numpy as jnp

    if (base is None) == (ids is None):
        raise ValueError("pass exactly one of base= or ids=")
    lk = min(k, scores.shape[1])
    ls, li = lax.top_k(scores, lk)
    gi = li + base if ids is None else jnp.take_along_axis(ids, li, axis=1)
    ls_all = lax.all_gather(ls, axis, axis=1, tiled=True)
    gi_all = lax.all_gather(gi, axis, axis=1, tiled=True)
    fs, fi = lax.top_k(ls_all, k)
    return fs, jnp.take_along_axis(gi_all, fi, axis=1)


def shard_ranges(total_rows: int, num_shards: int,
                 pad_to_multiple: bool = False):
    """Contiguous ``[start, end)`` row ranges assigning ``total_rows``
    to ``num_shards`` — the cross-process analogue of the mesh row
    split.  Default is balanced (first ``total % n`` shards take the
    ceiling), which is what the serving fleet uses;
    ``pad_to_multiple=True`` reproduces the DEVICE layout instead
    (every shard spans ``ceil(total/n)`` padded rows, trailing shards
    may run past ``total_rows`` — their overhang is pad, masked by the
    per-shard ``valid`` row count), which is what the bitwise-parity
    tests against the in-mesh ``two_stage_topk`` need."""
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    if total_rows < 0:
        raise ValueError(f"total_rows must be >= 0, got {total_rows}")
    if pad_to_multiple:
        per = -(-total_rows // num_shards) if total_rows else 0
        return [(i * per, (i + 1) * per) for i in range(num_shards)]
    base, extra = divmod(total_rows, num_shards)
    out = []
    start = 0
    for i in range(num_shards):
        end = start + base + (1 if i < extra else 0)
        out.append((start, end))
        start = end
    return out


def shard_of_row(row: int, ranges) -> int:
    """Owning shard index for a global row under ``ranges`` (the
    gene→shard half of the front door's routing table)."""
    for i, (start, end) in enumerate(ranges):
        if start <= row < end:
            return i
    raise ValueError(f"row {row} outside every shard range")


def merge_shard_topk(parts, k: int):
    """Cross-PROCESS top-k merge: the gather+select stage of
    :func:`two_stage_topk`, lifted off the mesh so the fleet front door
    can merge shard-local candidate sets arriving over HTTP
    (``serve/shardgroup.py``).

    ``parts`` is a sequence — in shard order, exactly like the tiled
    ``all_gather`` concatenates — of ``(scores, rows)`` pairs, each
    ``(B, lk_i)`` float32 scores (descending per row, a shard-local
    top-k) with matching GLOBAL row ids.  Returns ``(B, k')`` merged
    scores + rows where ``k' = min(k, total candidates)``.

    Selection semantics are ``lax.top_k``'s exactly — descending by
    score, ties broken toward the earlier position in the concatenated
    candidate axis — so the result is bitwise-identical to the in-mesh
    ``two_stage_topk`` on the same table (the property test in
    tests/test_shard.py holds this).  A dead shard simply contributes
    no columns: the merge degrades to the exact answer over the live
    shards' rows, never to a wrong one."""
    import numpy as np

    parts = [p for p in parts if p is not None]
    if not parts:
        raise ValueError("merge_shard_topk needs at least one shard part")
    scores = np.concatenate(
        [np.asarray(s, dtype=np.float32) for s, _ in parts], axis=1
    )
    rows = np.concatenate(
        [np.asarray(r) for _, r in parts], axis=1
    )
    k_eff = min(int(k), scores.shape[1])
    # stable argsort on the negated scores == lax.top_k tie-breaking
    # (equal scores keep candidate order, i.e. lower concat index wins)
    order = np.argsort(-scores, axis=1, kind="stable")[:, :k_eff]
    return (
        np.take_along_axis(scores, order, axis=1),
        np.take_along_axis(rows, order, axis=1),
    )


def row_sharding(mesh: Mesh, axis: str = "model") -> NamedSharding:
    """Row-shard a (V, D) embedding matrix over ``axis`` — each device
    owns V/P contiguous vocab rows.  This is the serve engine's layout
    for tables too big to replicate: the query×tableᵀ matmul computes
    per-shard score columns locally and only the top-k selection
    communicates (see serve/engine.py and the ``serve`` section of
    analysis/budgets.json for the enforced per-query byte ceiling)."""
    return NamedSharding(mesh, P(axis, None))


def no_sharding() -> Optional[SGNSSharding]:
    """Single-device marker (constraints become no-ops in the trainer)."""
    return None
