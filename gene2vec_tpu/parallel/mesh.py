"""Device-mesh construction.

The reference has no distributed backend at all (SURVEY §2.4: no
NCCL/MPI/Gloo; parallelism is gensim Hogwild threads + Ray tasks).  The
TPU-native communication layer is: pick a Mesh, annotate shardings, let XLA
emit the collectives over ICI/DCN.  Two logical axes:

* ``data``  — shards the pair stream (data parallelism);
* ``model`` — shards embedding-table rows over the vocab (row parallelism,
  BASELINE config 5).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

from gene2vec_tpu.config import MeshConfig


def make_mesh(
    config: MeshConfig = MeshConfig(), devices: Optional[Sequence] = None
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    model = max(1, config.model)
    data = config.data if config.data > 0 else n // model
    if data * model != n:
        raise ValueError(
            f"mesh {data}x{model} does not cover {n} devices; "
            f"set MeshConfig(data=..., model=...) so data*model == len(devices)"
        )
    dev_array = np.asarray(devices).reshape(data, model)
    return Mesh(dev_array, (config.data_axis, config.model_axis))


def single_device_mesh() -> Mesh:
    """1x1 mesh over the default device — lets all sharded code paths run
    unchanged on one chip."""
    return Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1), ("data", "model"))
