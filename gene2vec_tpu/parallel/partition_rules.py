"""Declarative checkpoint->device placement: regex rules over param names.

A *partition rules* list is an ordered sequence of ``(pattern,
PartitionSpec)`` pairs.  :func:`match_partition_rules` walks any pytree
of arrays, joins each leaf's tree path into a ``/``-separated name
(``"embedding/unit"``, ``"dense_0/kernel"``), and assigns the spec of
the **first** rule whose regex ``re.search``-matches that name.  Two
hard guarantees keep the mapping total:

* scalar and size-1 leaves are never partitioned — they get ``PS()``
  regardless of the rules (partitioning a scalar is always a bug);
* a leaf no rule matches falls back to **replicated** (``PS()``) with a
  ``RuntimeWarning`` naming the leaf — a new head with an unanticipated
  param name degrades to replication, it does not crash the serve loop.

This replaces the per-model imperative placement paths: the serve
registry (``serve/registry.py``) and the continuous-loop adoption path
(``loop/trainer.py``) both derive their device placement from one rules
list, so a dim512 SGNS table and a GGIPNN interaction head land on the
same mesh without model-specific loading code.  The shard/gather
closures are ``jit``-compiled identity functions constrained by
``out_shardings`` — the modern pjit spelling — so placement is an XLA
transfer, batched and async, not a per-leaf host loop.

The pattern follows the ``match_partition_rules`` idiom from the
EasyLM/levanter lineage (SNIPPETS.md [2]/[3]); the deliberate deviation
is the no-match fallback (replicate + warn, where the reference raises)
because a serving fleet must keep answering while a new checkpoint
family rolls out.
"""

from __future__ import annotations

import re
import warnings
from typing import Any, Callable, List, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from gene2vec_tpu.parallel.mesh import single_device_mesh

#: rules covering every param family this repo ships: SGNS tables
#: (``emb``/``ctx``), the serve registry's unit-normalized table
#: (``embedding/unit``), and GGIPNN dense layers (kernels row-sharded
#: on the vocab-sized embedding layer would be wrong — heads replicate,
#: only vocab-dimension tables row-shard over ``model``).
DEFAULT_SERVE_RULES: Tuple[Tuple[str, PS], ...] = (
    (r"(^|/)(emb|ctx)$", PS("model", None)),
    (r"(^|/)(unit|table|embedding)$", PS("model", None)),
    # GGIPNN / generic dense heads: small, replicate everywhere
    (r"(^|/)(kernel|bias|w[0-9]*|b[0-9]*)$", PS()),
)

#: replicated-everything rules (single-device serving, tests)
REPLICATED_RULES: Tuple[Tuple[str, PS], ...] = ((r".*", PS()),)


def _key_name(entry: Any) -> str:
    """One tree-path entry -> its bare name (DictKey 'emb' -> 'emb',
    GetAttrKey .emb -> 'emb', SequenceKey [0] -> '0')."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def tree_path_name(path: Sequence[Any]) -> str:
    """A flattened tree path -> the ``/``-joined rule-matching name."""
    return "/".join(_key_name(p) for p in path)


def spec_for_name(
    rules: Sequence[Tuple[str, PS]], name: str, shape: Tuple[int, ...] = None
) -> PS:
    """The spec the rules assign to one named leaf.  ``shape`` (when
    known) short-circuits scalars/size-1 to ``PS()``; no-match warns and
    replicates."""
    if shape is not None and (len(shape) == 0 or int(np.prod(shape)) == 1):
        return PS()
    for pattern, spec in rules:
        if re.search(pattern, name):
            return spec
    warnings.warn(
        f"partition_rules: no rule matched param {name!r}; "
        "falling back to replicated",
        RuntimeWarning,
        stacklevel=2,
    )
    return PS()


def match_partition_rules(
    rules: Sequence[Tuple[str, PS]], params: Any
) -> Any:
    """Map a param pytree onto a same-shaped pytree of PartitionSpecs.

    First-matching-rule wins (ordering is the API: put the specific
    patterns first, a catch-all last).  Scalar and size-1 leaves are
    forced to ``PS()`` before rules are consulted.
    """
    def assign(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        return spec_for_name(rules, tree_path_name(path), shape=shape)

    return jax.tree_util.tree_map_with_path(assign, params)


def named_sharding_tree(specs: Any, mesh: Mesh) -> Any:
    """PartitionSpec tree -> NamedSharding tree under ``mesh``."""
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        specs,
        is_leaf=lambda x: isinstance(x, PS),
    )


def make_shard_and_gather_fns(
    specs: Any, mesh: Mesh = None
) -> Tuple[Any, Any]:
    """Per-leaf ``(shard_fns, gather_fns)`` closure trees for a spec
    tree: ``shard_fns`` place host arrays onto the mesh per spec,
    ``gather_fns`` pull them back fully replicated (for checkpoint
    save).  Both are jit-compiled identities constrained by
    ``out_shardings`` — the pjit idiom — so repeated loads of the same
    geometry reuse one compiled transfer."""
    mesh = single_device_mesh() if mesh is None else mesh
    replicated = NamedSharding(mesh, PS())

    def make_shard(spec: PS) -> Callable[[Any], jax.Array]:
        sharding = NamedSharding(mesh, spec)
        fn = jax.jit(lambda x: x, out_shardings=sharding)
        return lambda x: fn(jax.numpy.asarray(x))

    def make_gather(spec: PS) -> Callable[[Any], np.ndarray]:
        fn = jax.jit(lambda x: x, out_shardings=replicated)
        return lambda x: np.asarray(fn(x))

    is_spec = lambda x: isinstance(x, PS)  # noqa: E731
    shard_fns = jax.tree_util.tree_map(make_shard, specs, is_leaf=is_spec)
    gather_fns = jax.tree_util.tree_map(make_gather, specs, is_leaf=is_spec)
    return shard_fns, gather_fns


def shard_params(
    rules: Sequence[Tuple[str, PS]], params: Any, mesh: Mesh = None
) -> Any:
    """One-shot declarative placement: match rules, build shard
    closures, apply leaf-wise.  The convenience entry point the
    adoption paths use."""
    specs = match_partition_rules(rules, params)
    shard_fns, _ = make_shard_and_gather_fns(specs, mesh)
    return jax.tree_util.tree_map(
        lambda fn, leaf: fn(leaf), shard_fns, params
    )


def gather_params(
    rules: Sequence[Tuple[str, PS]], params: Any, mesh: Mesh = None
) -> Any:
    """Inverse of :func:`shard_params`: device tree -> replicated host
    numpy tree (what a checkpoint writer wants)."""
    specs = match_partition_rules(rules, params)
    _, gather_fns = make_shard_and_gather_fns(specs, mesh)
    return jax.tree_util.tree_map(
        lambda fn, leaf: fn(leaf), gather_fns, params
    )


def parse_rules(
    raw: Sequence[Sequence[Any]], model_axis: str = "model"
) -> List[Tuple[str, PS]]:
    """Catalog-spec JSON rules -> runtime rules.  Each entry is
    ``[pattern, axes]`` where ``axes`` is a list of mesh-axis names or
    null (e.g. ``["(^|/)unit$", ["model", null]]``); an empty axes list
    means replicated.  Unknown shapes raise ValueError at spec-load
    time, not at first request."""
    rules: List[Tuple[str, PS]] = []
    for entry in raw:
        if len(entry) != 2:
            raise ValueError(
                f"partition rule must be [pattern, axes], got {entry!r}"
            )
        pattern, axes = entry
        re.compile(pattern)  # fail fast on a bad regex
        if axes is None:
            axes = []
        if not isinstance(axes, (list, tuple)):
            raise ValueError(
                f"rule axes must be a list of axis names/null, got {axes!r}"
            )
        rules.append((str(pattern), PS(*[a for a in axes])))
    return rules


__all__ = [
    "DEFAULT_SERVE_RULES",
    "REPLICATED_RULES",
    "match_partition_rules",
    "spec_for_name",
    "tree_path_name",
    "named_sharding_tree",
    "make_shard_and_gather_fns",
    "shard_params",
    "gather_params",
    "parse_rules",
]
