"""Multi-host (pod-slice) entry point.

The reference's only inter-process transport is Ray's object store during
corpus construction (``src/generate_gene_pairs.py:173-188``); training is
single-host.  The TPU-native multi-host story (SURVEY §5) is
``jax.distributed`` + SPMD: every host runs the *same* program, calls
:func:`initialize` once before any jax API touches devices, and from then
on ``jax.devices()`` is the global device list — ``make_mesh`` lays all
hosts' chips into one Mesh, pjit shards over it, and XLA routes
collectives over ICI within a slice and DCN across slices.  No explicit
communication code exists anywhere in the framework; sharding annotations
are the communication layer.

Launch recipe (documented in docs/DISTRIBUTED.md):

* **TPU pod slice** (GKE/queued resources): run the same script on every
  host calling ``initialize(auto=True)`` — jax auto-detects the
  coordinator, process count, and process id from the TPU metadata
  server.
* **Anything else** (CPU fleet, GPU cluster): pass
  ``coordinator_address="host0:1234"``, ``num_processes=N`` and
  ``process_id=i`` (or set ``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``
  /``JAX_PROCESS_ID`` and call with no arguments).

After ``initialize()``, per-host input pipelines feed each host's shard of
the global batch (``process_index()``/``process_count()`` below give the
shard coordinates), exactly like the single-host data-parallel path.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[list] = None,
    auto: bool = False,
) -> bool:
    """Idempotent ``jax.distributed.initialize`` wrapper.

    Returns True when a multi-process runtime is active after the call,
    False for the single-process no-op case (nothing configured — the
    local run stays exactly as before).  Arguments default to the
    ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID`` environment variables.

    On a TPU pod slice pass ``auto=True``: jax auto-detects coordinator,
    process count and process id from the TPU metadata server.  Auto mode
    is opt-in rather than sniffed from the environment because single-chip
    TPU hosts can carry pod-looking variables (this development image
    injects ``TPU_WORKER_HOSTNAMES=localhost`` into every process), and
    must stay plain single-process runs.

    Must be called before any other jax API touches the backend
    (``jax.devices()`` etc. lock the runtime single-process).
    """
    global _initialized
    if _initialized:
        return jax.process_count() > 1
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None and not auto:
        return False  # nothing configured: single process, no side effects
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True
    return jax.process_count() > 1


def shutdown() -> None:
    """Tear down the distributed runtime (tests; end of program)."""
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def process_index() -> int:
    """This host's rank — selects its shard of the global pair stream."""
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def local_device_count() -> int:
    return jax.local_device_count()
