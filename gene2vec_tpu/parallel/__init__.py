from gene2vec_tpu.parallel.mesh import make_mesh  # noqa: F401
from gene2vec_tpu.parallel.sharding import SGNSSharding  # noqa: F401
