from gene2vec_tpu.parallel.distributed import (  # noqa: F401
    initialize as initialize_distributed,
    process_count,
    process_index,
)
from gene2vec_tpu.parallel.mesh import make_mesh  # noqa: F401
from gene2vec_tpu.parallel.sharding import SGNSSharding  # noqa: F401
