"""Single dataclass-based config system.

The reference uses three ad-hoc flag styles (positional argparse in
``src/gene2vec.py:8-15``, rich argparse in ``src/generate_gene_pairs.py:12-42``,
TF1 ``tf.flags`` in ``src/GGIPNN_Classification.py:14-32``) plus hardcoded
constant blocks (``src/gene2vec.py:57-63``).  Here every subsystem reads one
frozen dataclass; CLI front-ends populate them.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class SGNSConfig:
    """Embedding-training configuration.

    Defaults mirror the reference's hardcoded parameter block
    (``src/gene2vec.py:57-63``: dim=200, sg=1, window=1, min_count=1,
    max_iter=10) and gensim-3.4's own SGNS defaults (5 negatives,
    alpha 0.025 → 1e-4, unigram^0.75 noise distribution).
    """

    dim: int = 200
    num_iters: int = 10            # outer iterations, each = 1 epoch + checkpoint
    objective: str = "sgns"        # "sgns" | "cbow" | "sg_hs" | "cbow_hs"
    window: int = 1                # corpus lines are pairs; window>1 is accepted
                                   # for longer "sentences" but pairs degenerate
                                   # to symmetric pair prediction (SURVEY §2.2.1)
    min_count: int = 1
    negatives: int = 5
    ns_exponent: float = 0.75
    lr: float = 0.025              # start learning rate (gensim alpha)
    min_lr: float = 1e-4           # linear decay floor (gensim min_alpha)
    batch_pairs: int = 4096        # corpus pairs per step (×2 training examples)
    seed: int = 1
    table_dtype: str = "float32"   # emb/ctx table storage.  "bfloat16"
                                   # buys +7% throughput at MEASURED
                                   # parity on the real-scale protocol
                                   # (holdout AUC 0.8897 vs f32's
                                   # 0.8896, dim 200, B=16,384).  Round 5
                                   # made bf16 safe at ANY scale via
                                   # stochastic-rounded write-back
                                   # (bf16_stochastic_round below): the
                                   # round-4 failure mode — updates <
                                   # |w|/512 rounding away every step so
                                   # small-scale runs never learn — is
                                   # gone because the EXPECTED update
                                   # equals the f32 update.
    bf16_stochastic_round: bool = True
                                   # bf16 tables: write back with 16
                                   # random carry bits below the mantissa
                                   # (sgns/step.py
                                   # _stochastic_round_bf16) instead of
                                   # round-to-nearest.  Untouched rows
                                   # pass through bit-identically.
                                   # False restores round-4 nearest
                                   # rounding (for A/B comparisons).
    compute_dtype: str = "float32"
    both_directions: bool = True   # emit (a→b) and (b→a) per corpus pair
    combiner: str = "capped"       # duplicate-row gradients: "capped" (sum,
                                   # capped at C x mean for overloaded rows —
                                   # stable at any batch size; a row's
                                   # positive and negative gradients shrink
                                   # together, see sgns/step.py invariants)
                                   # | "mean" | "sum" (sequential-SGD-like,
                                   # oracle parity at batch≈1)
    negative_mode: str = "stratified"
                                   # "stratified" (default): exact head +
                                   # random tail blocks — contiguous noise
                                   # traffic, ~1.4x shared-auto throughput
                                   # at measured quality parity (holdout
                                   # AUC 0.896 vs 0.878 oracle; sgns/step.py
                                   # _step_stratified, PERF_NOTES round 3)
                                   # | "shared": one noise pool per step
                                   # (MXU matmuls, pool-row scatter)
                                   # | "per_example": gensim's per-example
                                   # draws (oracle parity)
    strat_head: int = 256          # stratified: exact-expectation head rows
                                   # (clamped to vocab/2 for small vocabs)
    strat_block: int = 512         # stratified: rows per random tail block
                                   # (clamped to the tail size)
    strat_group: int = 256         # stratified: examples per tail-block
                                   # draw.  The tail term's cost scales
                                   # with the number of groups E/group
                                   # (vmapped dynamic slices are issue-
                                   # bound per slice) AND with the total
                                   # tail row traffic G x S, so larger
                                   # groups buy throughput at the price
                                   # of more examples sharing one block
                                   # draw; growing strat_block alongside
                                   # keeps per-example repulsion rank.
                                   # Post-dense-head frontier (PERF_NOTES
                                   # round-4 geometry II): (256, 512) =
                                   # 5.5-5.8M pairs/s at holdout AUC
                                   # 0.8896 (oracle 0.878) — the chosen
                                   # default; (128, 512) = 4.4M at
                                   # 0.8960 for maximum-quality runs;
                                   # (768, 768) = 6.35M falls BELOW
                                   # oracle parity (0.8751) and is not
                                   # offered as a default.
                                   # shared_groups>0 overrides the size.
    positive_head: int = 512       # dense-head positives (stratified mode,
                                   # single-device): batches arrive class-
                                   # segmented [HH|HT|TT] by head membership
                                   # (token row < positive_head of the
                                   # frequency-sorted vocab), and head-token
                                   # emb/ctx rows are gathered/scattered as
                                   # one-hot MXU matmuls over the contiguous
                                   # table[:positive_head] slab — only
                                   # tail-token examples pay dynamic row
                                   # ops.  0 disables (plain gathers).  The
                                   # trainer falls back to 0 under sharding
                                   # or non-stratified/one-direction
                                   # configs.  Measured (v5e, V=24,447
                                   # Zipf, B=16,384): 3.69M -> ~4.5M
                                   # pairs/s at H=512, epoch loss equal to
                                   # 4 decimals, holdout AUC 0.8960 vs the
                                   # plain path's 0.8971 (same run-to-run
                                   # band; oracle 0.878) — sweep in
                                   # experiments/results/positive_head_r4*,
                                   # PERF_NOTES round 4.
    positive_mid: int = 2048       # second dense positive slab (round 5):
                                   # rows [positive_head, positive_head +
                                   # positive_mid) form a MID frequency
                                   # band whose examples also move via
                                   # one-hot MXU matmuls — batches become
                                   # 6-class [HH|HM|HT|MM|MT|TT].  Each
                                   # level's one-hot FLOPs scale with ITS
                                   # example count x ITS slab width, so
                                   # the mid band covers rows the single-
                                   # level head could not afford.  Sweep
                                   # (v5e, V=24,447 Zipf, B=16,384,
                                   # PERF_NOTES round 5): 2048 = 6.31 and
                                   # 6.34M pairs/s across two runs vs
                                   # 5.81-5.93M at mid=0; 6.24M fresh-
                                   # process.  0 disables (round-4
                                   # two-class layout).
    pos_layout_shards: int = 0     # dense-head batch layout: number of
                                   # per-device [HH|HT|TT] blocks per
                                   # batch.  0 = auto (the mesh's data-
                                   # axis size under sharding, else 1).
                                   # An explicit value reproduces a mesh
                                   # layout on one device — used by the
                                   # sharded-vs-unsharded parity tests,
                                   # since the block layout changes the
                                   # example order (not the example set).
    hs_dense_depth: int = 10       # hierarchical softmax: tree levels
                                   # scored densely against the contiguous
                                   # shallow-node prefix (huffman.py
                                   # split_shallow; <= 2^depth - 1 slab
                                   # rows).  Hot tokens' whole paths live
                                   # in the prefix, so only rare tokens'
                                   # deep levels pay per-row gathers.
                                   # 0 = classic all-sparse path (also
                                   # the layout older node-table
                                   # checkpoints were saved in — resuming
                                   # one across a depth change scrambles
                                   # node vectors, not the exported emb).
    shared_pool: int = 1024        # shared-mode total noise-pool size floor
                                   # (importance-weighted down to `negatives`
                                   # per example)
    shared_pool_auto: bool = True  # size the pool at 0.8*E*negatives total
                                   # draws — the measured quality-parity
                                   # point vs per-example draws; a small
                                   # pool under a large batch (the round-2
                                   # bench config: P=64, B=16384) diverges
                                   # under "sum" and freezes the loss under
                                   # "capped" (docs/QUALITY_NOTES.md)
    shared_groups: int = 0         # sub-batches with independent pool slices
                                   # (0 = auto: one group per 32 examples).
                                   # At fixed total pool, quality is flat in
                                   # group size while smaller groups cost
                                   # less matmul — and one whole-batch pool
                                   # repels ctx rows only along batch-mean
                                   # directions and lets the geometry
                                   # collapse — see sgns/step.py invariant 3
    shuffle_each_iter: bool = True # reference reshuffles every iteration
                                   # (src/gene2vec.py:80)
    shuffle_mode: str = "offset"   # per-epoch reshuffle: "offset" (host-shuffled
                                   # corpus + random circular offset + random
                                   # batch order — O(1) gathers) | "full" (exact
                                   # per-epoch permutation; a V-row random
                                   # gather per epoch, latency-bound on TPU)
    txt_output: bool = True        # also export matrix-txt + w2v-format per iter
    async_checkpoint: bool = False
                                   # per-iteration checkpoints written by the
                                   # resilience/ double-buffered background
                                   # writer: the train loop stages a host copy
                                   # and moves on; disk I/O overlaps the next
                                   # epoch (docs/RESILIENCE.md).  jax SGNS
                                   # trainer only; the CPU oracle backends
                                   # ignore it (their epochs are host-bound
                                   # anyway).
    timeline: bool = True          # per-iteration phase timeline (obs/
                                   # timeline.py) written to timeline.jsonl;
                                   # overhead gated <= 2% by budgets.json
                                   # "perf" (BENCH_PERF_r10.json)
    kernel_profile: bool = False   # kernel cost attribution (obs/
                                   # profiler.py): AOT cost analysis of the
                                   # epoch step at startup + per-epoch wall
                                   # accounting, written to kernels.jsonl;
                                   # overhead gated <= 2% by budgets.json
                                   # "kernels" (BENCH_KERNELS_r18.json)

    # parallelism
    data_axis: str = "data"
    model_axis: str = "model"
    vocab_sharded: bool = False    # shard table rows over the model axis
    donate: bool = True


@dataclasses.dataclass(frozen=True)
class GGIPNNConfig:
    """Gene-gene-interaction MLP config.

    Defaults mirror ``src/GGIPNN_Classification.py:14-32`` and
    ``src/GGIPNN.py``: batch 128, 1 epoch, Adam 1e-3, dropout keep 0.5,
    hidden widths (100, 100, 10), L2 λ=0, frozen pretrained embedding.
    """

    embedding_dim: int = 200
    sequence_length: int = 2
    num_classes: int = 2
    hidden_dims: Tuple[int, int, int] = (100, 100, 10)
    dropout_keep_prob: float = 0.5
    l2_lambda: float = 0.0
    embed_train: bool = False
    use_pretrained: bool = True
    batch_size: int = 128
    num_epochs: int = 1
    learning_rate: float = 1e-3
    evaluate_every: int = 200
    checkpoint_every: int = 1000
    seed: int = 10
    scan_fit: bool = True          # whole-epoch jitted scan (per-epoch dev
                                   # eval); False = reference's per-batch
                                   # step loop with every-N-steps evaluation


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh shape. axes (data, model); product must divide device count."""

    data: int = -1                 # -1: all remaining devices
    model: int = 1
    data_axis: str = "data"
    model_axis: str = "model"


@dataclasses.dataclass(frozen=True)
class CorpusConfig:
    """Co-expression pair-corpus construction (reference
    ``src/generate_gene_pairs.py:12-42``)."""

    corr_threshold: float = 0.9
    min_study_samples: int = 20
    min_total_counts: float = 10.0
    parallel: bool = False
    ensembl: bool = False
    num_workers: int = 0           # 0 → os.cpu_count()


@dataclasses.dataclass(frozen=True)
class TSNEConfig:
    """t-SNE defaults from ``src/tsne_multi_core.py:31,42-52``."""

    pca_dims: int = 50
    perplexity: float = 30.0
    learning_rate: float = 200.0
    n_iter: int = 1000
    early_exaggeration: float = 12.0
    exaggeration_iters: int = 250
    momentum_start: float = 0.5
    momentum_final: float = 0.8
    momentum_switch_iter: int = 250
    seed: int = 0
    compute_dtype: str = "float32" # (N, N) kernel arrays; "bfloat16"
                                   # halves HBM traffic of the exact
                                   # O(N²) iteration (~0.4% relative
                                   # rounding on P/num — layouts agree
                                   # with f32 to visualization accuracy;
                                   # reductions always accumulate f32)
