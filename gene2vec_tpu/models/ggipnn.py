"""GGIPNN — gene-gene-interaction prediction MLP, as a Flax module.

Behavioral re-design of the TF1 graph in ``src/GGIPNN.py:3-83``: embedding
lookup of a (B, 2) gene-id batch → flatten to (B, 2·D) → Dense(100)+ReLU →
dropout → Dense(100)+ReLU → dropout → Dense(10)+ReLU → dropout →
Dense(num_classes) softmax.  Quirks preserved where behaviorally relevant
(SURVEY §2.2):

* dropout **also after the last hidden layer**, keep-prob 0.5 train / 1.0
  eval (#12, ``src/GGIPNN.py:56-58``);
* hidden widths hardcoded (100, 100, 10) — the reference's
  ``hidden_dimension`` flag is mostly decorative (#8);
* L2 applies to kernels only, default λ=0 (#10 — the reference's bias
  filter is a no-op anyway);
* the TF1 ``/cpu:0`` pin on the table (#9) is deliberately inverted: on TPU
  the table lives in HBM with everything else.

The frozen-vs-trainable pretrained-table switch (``embedTrain``,
``src/GGIPNN_Classification.py:16``) is handled in the optimizer (see
ggipnn_train.py), not the module — functionally the cleaner seam in JAX.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from gene2vec_tpu.config import GGIPNNConfig


class GGIPNN(nn.Module):
    """MLP over concatenated pair embeddings."""

    vocab_size: int
    embedding_dim: int = 200
    hidden_dims: Sequence[int] = (100, 100, 10)
    num_classes: int = 2
    dropout_keep_prob: float = 0.5

    @nn.compact
    def __call__(self, gene_ids: jax.Array, train: bool = False) -> jax.Array:
        """(B, 2) int ids → (B, num_classes) logits."""
        # U(-1, 1) table init as in the reference (src/GGIPNN.py:17);
        # overwritten when a pretrained table is loaded.
        table = self.param(
            "embedding",
            lambda key, shape: jax.random.uniform(key, shape, jnp.float32, -1.0, 1.0),
            (self.vocab_size, self.embedding_dim),
        )
        x = table[gene_ids]                               # (B, 2, D)
        x = x.reshape((x.shape[0], -1))                   # (B, 2·D)
        drop = nn.Dropout(
            rate=1.0 - self.dropout_keep_prob, deterministic=not train
        )
        for i, width in enumerate(self.hidden_dims):
            x = nn.Dense(width, name=f"hidden{i + 1}")(x)
            x = nn.relu(x)
            x = drop(x)
        return nn.Dense(self.num_classes, name="output")(x)

    @classmethod
    def from_config(cls, cfg: GGIPNNConfig, vocab_size: int) -> "GGIPNN":
        return cls(
            vocab_size=vocab_size,
            embedding_dim=cfg.embedding_dim,
            hidden_dims=tuple(cfg.hidden_dims),
            num_classes=cfg.num_classes,
            dropout_keep_prob=cfg.dropout_keep_prob,
        )


def loss_fn(
    logits: jax.Array, labels_onehot: jax.Array, params, l2_lambda: float = 0.0
) -> Tuple[jax.Array, jax.Array]:
    """Softmax cross-entropy (+ optional kernel L2) and accuracy — the
    reference's loss/accuracy pair (``src/GGIPNN.py:72-83``)."""
    logp = jax.nn.log_softmax(logits)
    xent = -jnp.mean(jnp.sum(labels_onehot * logp, axis=-1))
    if l2_lambda:
        l2 = sum(
            jnp.sum(jnp.square(leaf))
            for path, leaf in jax.tree_util.tree_leaves_with_path(params)
            if any(
                getattr(p, "key", None) == "kernel" for p in path
            )
        )
        xent = xent + l2_lambda * l2
    acc = jnp.mean(
        (jnp.argmax(logits, -1) == jnp.argmax(labels_onehot, -1)).astype(jnp.float32)
    )
    return xent, acc
