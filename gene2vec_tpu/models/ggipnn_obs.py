"""GGIPNN run-directory observability — ``runs/<timestamp>/`` parity.

The reference writes, per run (``src/GGIPNN_Classification.py:130-163``):

* ``summaries/train``: loss + accuracy scalars and, for every variable
  with a gradient, a gradient histogram and a gradient-sparsity
  (zero-fraction) scalar, all merged per training step;
* ``summaries/dev``: loss + accuracy scalars at the evaluation cadence;
* ``checkpoints/``: a ``tf.train.Saver`` snapshot every
  ``checkpoint_every`` steps keeping the ``max_to_keep=5`` most recent.

:class:`GGIPNNRun` reproduces that layout.  Scalars/histograms go through
tensorboardX when installed; a ``metrics.csv`` per writer is always
written (the in-repo convention, ``utils/metrics.py``), so the artifacts
exist — and tests can assert on them — without the optional dependency.
Checkpoints are flat ``.npz`` files of the param pytree (loadable with
:func:`load_checkpoint`), pruned to the most recent ``max_to_keep``.
"""

from __future__ import annotations

import os
import re
import time
from typing import Dict, Optional

import numpy as np

from gene2vec_tpu.obs.run import Run
from gene2vec_tpu.utils.metrics import MetricsLogger


def _flatten_params(params, prefix="") -> Dict[str, np.ndarray]:
    out = {}
    for k, v in params.items():
        path = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten_params(v, path + "/"))
        else:
            out[path] = np.asarray(v)
    return out


def load_checkpoint(path: str) -> Dict[str, np.ndarray]:
    """Flat ``{'dense1/kernel': array, ...}`` dict from a run checkpoint."""
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


class GGIPNNRun:
    """One training run's artifact directory (reference ``runs/<ts>/``).

    Parameters mirror the reference flags: ``max_to_keep`` is
    ``num_checkpoints`` (default 5, ``src/GGIPNN_Classification.py:24``).
    """

    def __init__(self, out_dir: Optional[str] = None, max_to_keep: int = 5,
                 base_dir: str = "runs", config=None):
        if out_dir is None:
            out_dir = os.path.join(base_dir, str(int(time.time())))
        self.out_dir = os.path.abspath(out_dir)
        self.checkpoint_dir = os.path.join(self.out_dir, "checkpoints")
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        train_dir = os.path.join(self.out_dir, "summaries", "train")
        dev_dir = os.path.join(self.out_dir, "summaries", "dev")
        self._train = MetricsLogger(
            os.path.join(train_dir, "metrics.csv"), tensorboard_dir=train_dir
        )
        self._dev = MetricsLogger(
            os.path.join(dev_dir, "metrics.csv"), tensorboard_dir=dev_dir
        )
        self.max_to_keep = max_to_keep
        # the unified observability layer rides in the same run dir:
        # manifest.json + events.jsonl + metrics.prom next to summaries/
        # (docs/OBSERVABILITY.md), so `obs report <run_dir>` works here too
        self.obs = Run(self.out_dir, name="ggipnn", config=config)

    # -- summaries ---------------------------------------------------------

    def log_train(self, step: int, loss: float, accuracy: float,
                  grads: Optional[dict] = None) -> None:
        """Train-writer scalars; with ``grads`` (a param-shaped pytree) also
        the per-variable gradient histogram + sparsity the reference merges
        into every train summary (``src/GGIPNN_Classification.py:129-137``)."""
        metrics = {"loss": float(loss), "accuracy": float(accuracy)}
        if grads is not None:
            flat = _flatten_params(grads)
            for name, g in flat.items():
                metrics[f"{name}/grad/sparsity"] = float((g == 0).mean())
                if self._train._tb is not None:
                    self._train._tb.add_histogram(f"{name}/grad/hist", g, step)
        self._train.log(step, metrics)
        self.obs.registry.counter("train_steps_total").inc()
        self.obs.registry.gauge("train_loss").set(float(loss))
        self.obs.registry.gauge("train_accuracy").set(float(accuracy))

    def log_dev(self, step: int, loss: float, accuracy: float) -> None:
        self._dev.log(
            step, {"loss": float(loss), "accuracy": float(accuracy)}
        )
        self.obs.event("dev_eval", step=step, loss=float(loss),
                       accuracy=float(accuracy))

    # -- checkpoints -------------------------------------------------------

    def checkpoint(self, step: int, params: dict) -> str:
        """``checkpoints/model-<step>.npz``, pruned to ``max_to_keep``."""
        path = os.path.join(self.checkpoint_dir, f"model-{step}.npz")
        with self.obs.span("checkpoint", step=step):
            np.savez(path, **_flatten_params(params))
        kept = sorted(
            (
                int(m.group(1)), f
            )
            for f in os.listdir(self.checkpoint_dir)
            if (m := re.fullmatch(r"model-(\d+)\.npz", f))
        )
        for _, f in kept[: max(0, len(kept) - self.max_to_keep)]:
            os.remove(os.path.join(self.checkpoint_dir, f))
        return path

    def close(self) -> None:
        self._train.close()
        self._dev.close()
        self.obs.close()
