"""GGIPNN data utilities.

Behavioral equivalents of ``src/GGIPNN_util.py``:

* **transductive vocab** — fit over train+valid+test pair text together
  (``src/GGIPNN_Classification.py:61-62``, SURVEY §2.2 #5): the model indexes
  a fixed pretrained gene vocabulary, so every split's genes must be in it;
* ``batch_iter`` — epoch-shuffling batch iterator (``src/GGIPNN_util.py:18-35``);
* one-hot labels (``src/GGIPNN_util.py:37-50``).

Unlike the reference's ``myFit`` (which silently depends on 2-token lines —
quirk #7: ``j = 1`` instead of ``j += 1``, ``src/GGIPNN_util.py:82``), the
encoder here is explicit about the pair shape.
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, List, Sequence, Tuple

import numpy as np


class PairTextVocab:
    """Token → contiguous id over 2-token pair lines, in first-seen order
    (matching the reference's dict-accumulation order semantics)."""

    __slots__ = ("token_to_id", "id_to_token")

    def __init__(self) -> None:
        self.token_to_id: Dict[str, int] = {}
        self.id_to_token: List[str] = []

    def __len__(self) -> int:
        return len(self.id_to_token)

    def fit(self, *line_sets: Iterable[str]) -> "PairTextVocab":
        """Fit over any number of line iterables (pass all splits at once
        for the reference's transductive behavior)."""
        for lines in line_sets:
            for line in lines:
                for tok in line.split():
                    if tok not in self.token_to_id:
                        self.token_to_id[tok] = len(self.id_to_token)
                        self.id_to_token.append(tok)
        return self

    def transform(self, lines: Iterable[str]) -> np.ndarray:
        """Pair lines → (N, 2) int32. Raises on out-of-vocab tokens (cannot
        happen when the vocab was fit transductively)."""
        out: List[Tuple[int, int]] = []
        for line in lines:
            toks = line.split()
            if len(toks) != 2:
                raise ValueError(f"expected 2 tokens per line, got {toks!r}")
            out.append((self.token_to_id[toks[0]], self.token_to_id[toks[1]]))
        return np.asarray(out, dtype=np.int32).reshape(-1, 2)


def one_hot_labels(labels: Sequence, num_classes: int = 2) -> np.ndarray:
    """Label sequence → (N, C) float32 one-hot; labels are ints or digit
    strings (the reference's label files hold '0'/'1' lines)."""
    idx = np.asarray([int(l) for l in labels], dtype=np.int64)
    if idx.size and (idx.min() < 0 or idx.max() >= num_classes):
        raise ValueError(f"label out of range [0, {num_classes})")
    out = np.zeros((len(idx), num_classes), dtype=np.float32)
    out[np.arange(len(idx)), idx] = 1.0
    return out


def batch_iter(
    data: np.ndarray,
    batch_size: int,
    num_epochs: int,
    shuffle: bool = True,
    seed: int = 10,
) -> Iterator[np.ndarray]:
    """Epoch-shuffling batch iterator over a stacked array — the behavior of
    ``src/GGIPNN_util.py:18-35`` (ragged final batch kept, reshuffle per
    epoch)."""
    data = np.asarray(data)
    n = data.shape[0]
    num_batches = (n - 1) // batch_size + 1 if n else 0
    rng = np.random.RandomState(seed)
    for _ in range(num_epochs):
        order = rng.permutation(n) if shuffle else np.arange(n)
        for b in range(num_batches):
            yield data[order[b * batch_size : min((b + 1) * batch_size, n)]]


def read_lines(path: str) -> List[str]:
    with open(path, "r", encoding="utf-8") as f:
        return [line.strip() for line in f if line.strip()]
